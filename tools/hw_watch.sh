#!/usr/bin/env bash
# Tunnel watcher: probe every INTERVAL seconds, log each probe, and run
# the full hardware queue (tools/hw_session.sh) automatically at the
# first healthy window.  Detached use:
#
#   nohup setsid bash tools/hw_watch.sh >/dev/null 2>&1 &
#
# Probes append to perf/tunnel_probes_r5.log (same evidence trail as
# rounds 2-4); the session run logs to perf/hw_session_logs/ as usual.
# A marker file perf/hw_watch.ran stops duplicate sessions if the
# watcher is restarted after a successful run.
set -u
cd "$(dirname "$0")/.."

INTERVAL=${HW_WATCH_INTERVAL:-900}
LOG=perf/tunnel_probes_r5.log
MARK=perf/hw_watch.ran
mkdir -p perf perf/hw_session_logs

while true; do
  plat=$(timeout --kill-after=30 "${HW_PROBE_TIMEOUT:-170}" python -c "from mpi_tpu.utils.platform import probe_platform; print(probe_platform())" 2>/dev/null | tail -1)
  echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) probe=${plat:-error}" >> "$LOG"
  # MARK is round-scoped the same way the queue's .done markers are: a
  # marker older than VERDICT.md belongs to a finished previous round
  # and must not block this round's queue.  No VERDICT.md yet (fresh
  # round, file not written) must also unblock: -nt is false when the
  # left file is absent, so a stale marker would gate the queue forever.
  if [ "${plat:-}" = "tpu" ] && { [ ! -e "$MARK" ] || [ ! -e VERDICT.md ] || [ VERDICT.md -nt "$MARK" ]; }; then
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) tunnel healthy — running hw_session" >> "$LOG"
    # append with a window header: the queue spans multiple windows by
    # design, and a later degrading window must not erase the record of
    # the one that banked results
    echo "===== hw_watch window $(date -u +%Y-%m-%dT%H:%M:%SZ) =====" \
      >> perf/hw_session_logs/hw_watch_run.log
    bash tools/hw_session.sh >> perf/hw_session_logs/hw_watch_run.log 2>&1
    rc=$?
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) hw_session exited rc=$rc" >> "$LOG"
    # rc=0 now means every step either succeeded this window or holds a
    # .done marker from a previous one (the queue resumes across short
    # windows), so it is exactly the "program complete" condition
    if [ $rc -eq 0 ]; then
      touch "$MARK"
    fi
  fi
  sleep "$INTERVAL"
done
