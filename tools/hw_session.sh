#!/usr/bin/env bash
# Resumable multi-window hardware queue: one command so a short tunnel
# window is not wasted on orchestration.  Each step is independently
# resumable across windows (.done markers, round-scoped against
# VERDICT.md); artifacts land under perf/ and logs under
# perf/hw_session_logs/.  Steps are ordered cheapest / highest-
# information first (VERDICT r4 item 2): the observed failure mode is a
# window dying ~10 min in, so the first minutes must bank a flagship
# number (bench banks its 8192^2 rung within ~2 min of a healthy
# probe), then the compile smoke + fused-stepper parity run (seconds to
# ~2 min), then the ladders.
#
# Steps are resumable ACROSS windows: a step that exits 0 drops a
# .done marker (gitignored) and is skipped on the next full-queue run —
# tunnel windows can be shorter than the queue (observed 2026-07-31:
# ~10 min), so successive windows must make incremental progress
# instead of re-measuring the head of the queue every time.  Naming a
# step explicitly re-runs it regardless; HW_FORCE=1 re-runs everything.
#
#   bash tools/hw_session.sh            # run the full queue (resume)
#   bash tools/hw_session.sh bench      # force just one step
set -uo pipefail
cd "$(dirname "$0")/.."

LOGS=perf/hw_session_logs
mkdir -p "$LOGS"

# A degrading tunnel hangs RPCs rather than failing them (observed
# 2026-07-31: probe and a ladder child both blocked indefinitely), so
# both the gate and every step run under a hard timeout — a stuck step
# must not eat the rest of a healthy window.
PROBE_TIMEOUT=${HW_PROBE_TIMEOUT:-170}
STEP_TIMEOUT=${HW_STEP_TIMEOUT:-1800}
# bench.py budgets its own probe window + bank + ladder retries + CPU
# fallback + mesh rungs (computed worst case ~9,900s with every child
# timing out, +900s for the 1x1-mesh rung on a single-chip tunnel), so
# its step gets a larger allowance than the single-measurement tools.
BENCH_TIMEOUT=${HW_BENCH_TIMEOUT:-11700}

probe() {
  timeout --kill-after=30 "$PROBE_TIMEOUT" python -c "from mpi_tpu.utils.platform import probe_platform; import sys; sys.exit(0 if probe_platform() == 'tpu' else 1)"
}

FAILED=()

step() {  # step <name> <cmd...>
  local name=$1; shift
  # a marker OLDER than VERDICT.md predates this round (the round driver
  # writes a fresh VERDICT.md at each round boundary) — the rewritten
  # code must be re-measured, so stale markers do not skip
  if [ "$want" = all ] && [ "${HW_FORCE:-0}" != 1 ] \
      && [ "$LOGS/$name.done" -nt VERDICT.md ]; then
    echo "=== hw_session: $name already done (rm $LOGS/$name.done to redo) ==="
    return 0
  fi
  echo "=== hw_session: $name ==="
  if ! probe; then
    echo "hw_session: tunnel not answering before '$name' — stopping" >&2
    if [ ${#FAILED[@]} -gt 0 ]; then
      echo "hw_session: FAILED steps so far: ${FAILED[*]} (see $LOGS/)" >&2
    fi
    exit 1
  fi
  local start_stamp=""
  if [ "$name" = bench ]; then
    start_stamp=$(mktemp)  # only the bench freshness gate reads it
  fi
  # TERM first so bench.py's crash-guard can flush its attempt history;
  # KILL 60s later unsticks a truly hung RPC that ignores TERM.
  local t="$STEP_TIMEOUT"
  [ "$name" = bench ] && t="$BENCH_TIMEOUT"
  ( timeout --kill-after=60 "$t" "$@" ) 2>&1 | tee "$LOGS/$name.log"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "hw_session: '$name' timed out after ${t}s (hung tunnel?)" >&2
  fi
  echo "=== $name done (rc=$rc) ==="
  # bench.py exits 0 BY CONTRACT even when every TPU attempt failed
  # (degraded CPU fallback) or only a bank-size rung landed — "done"
  # must mean a FRESH undegraded TPU flagship, or a dead window would
  # permanently skip the flagship re-measure (the artifact ships in the
  # tree, hence the freshness stamp; the path honors bench.py's env
  # override)
  local bench_art="${MPI_TPU_BENCH_ARTIFACT:-perf/bench_last.json}"
  if [ "$rc" -eq 0 ] && [ "$name" = bench ] && {
      ! [ "$bench_art" -nt "$start_stamp" ] || ! python - "$bench_art" <<'PY'
import json, sys
import bench  # repo root is the cwd; flagship size stays defined once
try:
    d = json.load(open(sys.argv[1]))["result"]
except Exception:
    sys.exit(1)
ok = (d.get("platform") == "tpu" and "degraded" not in d
      and "note" not in d and d.get("size") == bench.SIZES[0])
sys.exit(0 if ok else 1)
PY
  }; then
    echo "hw_session: bench banked no fresh undegraded TPU flagship — not marking done" >&2
    rc=1
  fi
  [ -n "$start_stamp" ] && rm -f "$start_stamp"
  # later steps still run (bench failing must not block the ladders),
  # but a failed step must not vanish into an exit-0 "queue complete"
  if [ "$rc" -ne 0 ]; then FAILED+=("$name"); else touch "$LOGS/$name.done"; fi
  return 0
}

want=${1:-all}

# 1. Bench first: banks the 8192^2 rung within ~2 minutes of a healthy
#    probe, so the round holds a fresh hardware number whatever happens
#    to the rest of the queue.
[ "$want" = all ] || [ "$want" = bench ] && \
  step bench python bench.py

# 2. Mosaic compile-only smoke of every Pallas kernel variant PLUS the
#    shard_map-composed fused steppers (seconds per variant; catches
#    compile regressions across the whole kernel matrix even in a short
#    window — the single highest-information cheap step, VERDICT r4
#    items 1a/2).
[ "$want" = all ] || [ "$want" = mosaic ] && \
  step mosaic python tools/mosaic_smoke.py

# 3. Fused sharded-stepper parity RUN on the chip (VERDICT r4 item 1b):
#    one real Mosaic-compiled execution of the shard_map-composed
#    use_pallas steppers on a 1x1 mesh, asserted bit-exact vs the XLA
#    engines; JSON evidence in perf/fused_stepper_tpu.json.
[ "$want" = all ] || [ "$want" = fused ] && \
  step fused python tools/fused_stepper_check.py

# 4. LtL temporal-blocking ladder: keep gens>1 in the dispatch only
#    where a row wins (unblocks the policy wiring, VERDICT r4 item 4).
[ "$want" = all ] || [ "$want" = gens ] && \
  step gens python tools/ltl_gens_ladder.py

# 5. Engine ladder refresh — the Wallace-tree LtL rewrite moved the
#    bit-sliced compute bound ~3.5x; expect bosco rows well above the
#    old 106 Gcell/s.
[ "$want" = all ] || [ "$want" = ladder ] && \
  step ladder python tools/engine_ladder.py

# 6. Throughput roof (16-way parallel chains) + regenerated %roof table.
[ "$want" = all ] || [ "$want" = roof ] && \
  step roof python tools/roofline.py --measure-roof

# 7. Weak-scaling rung on real hardware: with one visible chip this
#    banks the 1-device row of the 8->256 ladder (ready to run as-is on
#    a slice, where it ladders across the visible chips; VERDICT r3
#    item 5).
[ "$want" = all ] || [ "$want" = sweep ] && \
  step sweep python tools/sweep.py --steps 100 --tile 8192 --comm-every 8 \
    --jsonl perf/weakscale_hw.jsonl --out-dir perf --time-file weakscale_hw

# 8. Hardware spot-check of the new Mosaic-compiled paths (overlap +
#    gens) at product scale via the CLI: radius-2 gens dispatch and a
#    bosco (r=5, bs_sum kernel) run, timed reports written to perf/.
if [ "$want" = all ] || [ "$want" = spot ]; then
  step spot-r2g4 python -m mpi_tpu.cli 16384 16384 0 64 hw_spot 1 \
    --backend tpu --rule "R2,B10-13,S8-12" --comm-every 4 \
    --out-dir perf --name hw-spot-r2g4
  step spot-bosco python -m mpi_tpu.cli 16384 16384 0 32 hw_spot 0 \
    --backend tpu --rule bosco \
    --out-dir perf --name hw-spot-bosco
fi

if [ ${#FAILED[@]} -gt 0 ]; then
  echo "hw_session: FAILED steps: ${FAILED[*]} (see $LOGS/)" >&2
  exit 1
fi
echo "hw_session: queue complete; review perf/ artifacts and PERF.md"
