"""The gossip wire: push-pull digest exchange over the serving port.

Each node periodically POSTs its digest to every peer's
``/cluster/gossip`` endpoint (the ordinary serving front — no second
listener, no second port to firewall).  The receiver applies the digest
and answers with its *own* digest, which the sender applies in turn —
push-pull, so one side initiating a round synchronizes both directions
and a 2-node slice converges in a single interval even if only one
node's timer has fired yet.

Digests are tiny JSON: peer id, a per-sender sequence number (late or
duplicate deliveries are discarded by the receiver — idempotent by
construction), liveness facts, the sender's open-breaker label set, its
cumulative usage-ledger totals, and its local ``sid -> node`` routes.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from mpi_tpu.cluster.proxy import (
    FORWARDED_HEADER, PeerUnreachable, proxy_request,
)

GOSSIP_PATH = "/cluster/gossip"


def send_digest(addr: str, digest: dict, timeout_s: float = 5.0) -> dict:
    """POST ``digest`` to one peer; returns the peer's reply (its own
    digest rides in ``reply["digest"]``).  Raises
    :class:`~mpi_tpu.cluster.proxy.PeerUnreachable` on transport
    failure and on a non-JSON or non-200 answer (a peer that cannot
    speak the protocol is as gone as one that cannot speak at all)."""
    body = json.dumps(digest).encode()
    status, _, data = proxy_request(
        addr, "POST", GOSSIP_PATH, body,
        # gossip must never be re-routed by the receiving core
        headers={FORWARDED_HEADER: digest.get("node", "?"),
                 "Content-Type": "application/json",
                 "Content-Length": str(len(body))},
        timeout_s=timeout_s)
    if status != 200:
        raise PeerUnreachable(f"peer {addr} answered {status} to gossip")
    try:
        reply = json.loads(data)
    except ValueError as e:
        raise PeerUnreachable(f"peer {addr} sent non-JSON gossip reply: {e}")
    if not isinstance(reply, dict):
        raise PeerUnreachable(f"peer {addr} sent malformed gossip reply")
    return reply


class Gossiper:
    """The background heartbeat thread: one round of
    ``node.gossip_now()`` every ``interval_s`` until stopped.  Daemon —
    a serving process exiting never waits on gossip."""

    def __init__(self, node, interval_s: float):
        self._node = node
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mpi_tpu-gossip")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._node.gossip_now()
            except Exception:  # noqa: BLE001 — heartbeats must outlive bugs
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
