"""The gossip wire: push-pull digest exchange over the serving port.

Each node periodically POSTs its digest to every peer's
``/cluster/gossip`` endpoint (the ordinary serving front — no second
listener, no second port to firewall).  The receiver applies the digest
and answers with its *own* digest, which the sender applies in turn —
push-pull, so one side initiating a round synchronizes both directions
and a 2-node slice converges in a single interval even if only one
node's timer has fired yet.

Digests are tiny JSON: peer id, a per-sender sequence number (late or
duplicate deliveries are discarded by the receiver — idempotent by
construction), liveness facts, the sender's open-breaker label set, its
cumulative usage-ledger totals, and its local ``sid -> node`` routes.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from mpi_tpu.cluster.proxy import (
    FORWARDED_HEADER, PeerUnreachable, proxy_request,
)

GOSSIP_PATH = "/cluster/gossip"
JOIN_PATH = "/cluster/join"
ADOPT_PATH = "/cluster/adopt"


def _post_json(addr: str, path: str, payload: dict, timeout_s: float,
               sender: str) -> dict:
    """POST one cluster-protocol message and parse the JSON reply.
    Raises :class:`~mpi_tpu.cluster.proxy.PeerUnreachable` on transport
    failure and on a non-JSON or non-200 answer (a peer that cannot
    speak the protocol is as gone as one that cannot speak at all)."""
    body = json.dumps(payload).encode()
    status, _, data = proxy_request(
        addr, "POST", path, body,
        # protocol messages must never be re-routed by the receiving core
        headers={FORWARDED_HEADER: sender,
                 "Content-Type": "application/json",
                 "Content-Length": str(len(body))},
        timeout_s=timeout_s)
    if status != 200:
        raise PeerUnreachable(f"peer {addr} answered {status} to {path}")
    try:
        reply = json.loads(data)
    except ValueError as e:
        raise PeerUnreachable(f"peer {addr} sent non-JSON reply "
                              f"to {path}: {e}")
    if not isinstance(reply, dict):
        raise PeerUnreachable(f"peer {addr} sent malformed reply to {path}")
    return reply


def send_digest(addr: str, digest: dict, timeout_s: float = 5.0) -> dict:
    """POST ``digest`` to one peer; returns the peer's reply (its own
    digest rides in ``reply["digest"]``)."""
    return _post_json(addr, GOSSIP_PATH, digest, timeout_s,
                      digest.get("node", "?"))


def send_join(addr: str, node: str, timeout_s: float = 5.0) -> dict:
    """Announce ``node`` to an existing member (``POST /cluster/join``).
    The reply carries the member's digest, so one successful join
    teaches the joiner the whole membership in a single round."""
    return _post_json(addr, JOIN_PATH, {"node": node}, timeout_s, node)


def send_adopt(addr: str, node: str, sids: list,
               timeout_s: float = 5.0) -> dict:
    """Ask a ring successor to adopt ``sids`` from the shared state dir
    (the drain handoff: ``POST /cluster/adopt``)."""
    return _post_json(addr, ADOPT_PATH, {"sids": list(sids), "from": node},
                      timeout_s, node)


class Gossiper:
    """The background heartbeat thread: one round of
    ``node.gossip_now()`` every ``interval_s`` until stopped.  Daemon —
    a serving process exiting never waits on gossip."""

    def __init__(self, node, interval_s: float):
        self._node = node
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mpi_tpu-gossip")
        self._thread.start()

    def _loop(self) -> None:
        try:
            # announce ourselves before the first round (off the caller's
            # thread: startup must never block on a peer that is itself
            # still starting up)
            self._node.join_cluster()
        except Exception:  # noqa: BLE001 — join is best-effort
            pass
        while not self._stop.wait(self.interval_s):
            try:
                self._node.gossip_now()
            except Exception:  # noqa: BLE001 — heartbeats must outlive bugs
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
