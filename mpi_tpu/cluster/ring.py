"""Consistent-hash ring + persisted routing table: who owns a session.

Session placement has two layers, consulted in order:

1. the :class:`RoutingTable` — explicit ``sid -> node`` entries,
   recorded at create time and merged from peers' gossip digests.  With
   a ``--state-dir`` the table persists (tmp+fsync+replace, same
   crash-safety idiom as ``serve/recovery.py``), so a restarted front
   still knows where surviving sessions live even if its ring view
   changed;
2. the :class:`HashRing` — sha1 consistent hashing with virtual nodes,
   the stateless fallback that lets any front place a *new* session id
   identically without coordination.

Both are pure data structures (no sockets); ``cluster/node.py`` wires
them to the gossip protocol.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
from typing import Dict, List, Optional


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hashing over a fixed node set.  ``replicas`` virtual
    points per node smooth the distribution (with 2 nodes and 64 vnodes
    the split is within a few percent of even); the node set is pinned
    at construction — membership is static per process lifetime, which
    is exactly the ``--peers`` contract."""

    def __init__(self, nodes: List[str], replicas: int = 64):
        if not nodes:
            raise ValueError("ring needs at least one node")
        self.nodes = sorted(set(nodes))
        self.replicas = int(replicas)
        points = []
        for node in self.nodes:
            for i in range(self.replicas):
                points.append((_hash(f"{node}#{i}"), node))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]

    def owner(self, key: str) -> str:
        """The node owning ``key`` — the first virtual point clockwise
        from the key's hash (wrapping)."""
        i = bisect.bisect_right(self._points, _hash(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]


class RoutingTable:
    """Thread-safe ``sid -> node`` map with optional JSON persistence.

    Entries only ever *add or overwrite* (a session's owner is fixed for
    its lifetime; a re-learned entry is idempotent), and a missing or
    corrupt file loads as empty — routing degrades to the ring, never
    blocks startup."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._routes: Dict[str, str] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                if isinstance(data, dict):
                    self._routes = {str(k): str(v) for k, v in data.items()}
            except (OSError, ValueError):
                pass                    # tolerate a torn file: ring fallback

    def get(self, sid: str) -> Optional[str]:
        with self._lock:
            return self._routes.get(sid)

    def record(self, sid: str, node: str) -> None:
        self.update({sid: node})

    def update(self, routes: Dict[str, str]) -> None:
        """Merge ``routes`` in (gossip apply / local create) and persist
        when anything changed."""
        if not routes:
            return
        with self._lock:
            changed = False
            for sid, node in routes.items():
                if self._routes.get(sid) != node:
                    self._routes[str(sid)] = str(node)
                    changed = True
            snapshot = dict(self._routes) if changed and self.path else None
        if snapshot is not None:
            self._save(snapshot)

    def _save(self, snapshot: Dict[str, str]) -> None:
        try:
            tmp = f"{self.path}.tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass                        # persistence is best-effort

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._routes)
