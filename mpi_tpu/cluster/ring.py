"""Consistent-hash ring + persisted routing table: who owns a session.

Session placement has two layers, consulted in order:

1. the :class:`RoutingTable` — explicit ``sid -> (node, epoch)``
   entries, recorded at create time and merged from peers' gossip
   digests.  The epoch is the cluster's membership clock (bumped on
   every join / confirmed death / drain): a failover adoption records
   the new owner at a *higher* epoch, so merge order cannot resurrect a
   route into a dead address.  With a ``--state-dir`` the table
   persists (tmp+fsync+replace, same crash-safety idiom as
   ``serve/recovery.py``), so a restarted front still knows where
   surviving sessions live even if its ring view changed;
2. the :class:`HashRing` — sha1 consistent hashing with virtual nodes,
   the stateless fallback that lets any front place a *new* session id
   identically without coordination.

Both are pure data structures (no sockets); ``cluster/node.py`` wires
them to the gossip protocol and rebuilds the ring on membership change.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

# persisted format: {"v": 2, "routes": {sid: [node, epoch]}}.  A v1
# file (flat {sid: node}) loads with every entry at epoch 0 — see
# MIGRATION.md.
TABLE_VERSION = 2


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hashing over a node set.  ``replicas`` virtual points
    per node smooth the distribution (with 2 nodes and 64 vnodes the
    split is within a few percent of even).  The instance is immutable;
    dynamic membership (``cluster/node.py``) swaps in a freshly built
    ring on every epoch bump — readers always see one coherent view."""

    def __init__(self, nodes: List[str], replicas: int = 64):
        if not nodes:
            raise ValueError("ring needs at least one node")
        self.nodes = sorted(set(nodes))
        self.replicas = int(replicas)
        points = []
        for node in self.nodes:
            for i in range(self.replicas):
                points.append((_hash(f"{node}#{i}"), node))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]

    def owner(self, key: str) -> str:
        """The node owning ``key`` — the first virtual point clockwise
        from the key's hash (wrapping)."""
        i = bisect.bisect_right(self._points, _hash(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]


class RoutingTable:
    """Thread-safe ``sid -> (node, epoch)`` map with optional JSON
    persistence.

    Merge rule: a strictly newer epoch always wins (failover/drain
    re-homing); at equal epochs the last writer wins (the pre-epoch
    behavior — owners are fixed for a session's lifetime, so same-epoch
    disagreement only ever means a re-learned identical entry).  A
    missing file loads as empty; a corrupt one *also* loads as empty
    but is counted (``resets``, scraped as
    ``mpi_tpu_routing_table_resets_total``) and warned about — routing
    degrades to the ring, never blocks startup, but no longer silently.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._routes: Dict[str, Tuple[str, int]] = {}
        self.resets = 0                 # corrupt-file recoveries
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                self._routes = self._parse(data)
            except (OSError, ValueError):
                self.resets += 1
                print(f"[mpi_tpu] warning: routing table {path} is "
                      f"corrupt or unreadable; starting empty (placement "
                      f"degrades to the ring until routes are re-learned)",
                      file=sys.stderr)

    @staticmethod
    def _parse(data) -> Dict[str, Tuple[str, int]]:
        if not isinstance(data, dict):
            raise ValueError("routing table must be a JSON object")
        if data.get("v") == TABLE_VERSION:
            routes = data.get("routes")
            if not isinstance(routes, dict):
                raise ValueError("v2 routing table lacks a routes object")
            items = routes.items()
        else:
            items = data.items()        # v1: flat sid -> node, epoch 0
        out: Dict[str, Tuple[str, int]] = {}
        for sid, val in items:
            if isinstance(val, (list, tuple)) and len(val) == 2:
                out[str(sid)] = (str(val[0]), int(val[1]))
            else:
                out[str(sid)] = (str(val), 0)
        return out

    def get(self, sid: str) -> Optional[str]:
        with self._lock:
            entry = self._routes.get(sid)
            return entry[0] if entry is not None else None

    def entry(self, sid: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._routes.get(sid)

    def record(self, sid: str, node: str, epoch: int = 0) -> None:
        self.update({sid: (node, epoch)})

    def update(self, routes: Dict) -> None:
        """Merge ``routes`` in (gossip apply / local create / adoption)
        and persist when anything changed.  Values are ``(node, epoch)``
        pairs or bare node strings (epoch 0 — the pre-epoch digest
        shape, still accepted from old peers)."""
        if not routes:
            return
        with self._lock:
            changed = False
            for sid, val in routes.items():
                if isinstance(val, (list, tuple)):
                    node, epoch = str(val[0]), int(val[1])
                else:
                    node, epoch = str(val), 0
                cur = self._routes.get(str(sid))
                if cur is not None and epoch < cur[1]:
                    continue            # stale: an older membership epoch
                if cur != (node, epoch):
                    self._routes[str(sid)] = (node, epoch)
                    changed = True
            snapshot = dict(self._routes) if changed and self.path else None
        if snapshot is not None:
            self._save(snapshot)

    def _save(self, snapshot: Dict[str, Tuple[str, int]]) -> None:
        try:
            tmp = f"{self.path}.tmp"
            with open(tmp, "w") as f:
                json.dump({"v": TABLE_VERSION,
                           "routes": {sid: [node, epoch]
                                      for sid, (node, epoch)
                                      in snapshot.items()}}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass                        # persistence is best-effort

    def snapshot(self) -> Dict[str, str]:
        """``sid -> node`` (the pre-epoch shape — placement callers
        only need the owner)."""
        with self._lock:
            return {sid: node for sid, (node, _) in self._routes.items()}

    def snapshot_entries(self) -> Dict[str, List]:
        """``sid -> [node, epoch]`` — the JSON-ready shape gossip
        digests carry."""
        with self._lock:
            return {sid: [node, epoch]
                    for sid, (node, epoch) in self._routes.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._routes)
