"""``mpi_tpu.cluster`` — N serving processes as one logical service.

The single-process serve stack (PRs 6-10) is complete and self-
verifying; this subsystem federates it across a pod slice without
changing any single-process byte: with ``--peers`` unset nothing here
is imported on a request path.

* **sticky session routing** (``ring.py``) — consistent hashing on the
  session id plus a small persisted routing table; any front answers
  any request, proxying (``proxy.py``) one hop to the owner.
* **membership + gossip** (``gossip.py``, ``node.py``) — a stdlib
  push-pull digest protocol over the serving port carrying heartbeats,
  breaker open/close labels (one host's poisoned plan quarantines its
  siblings'), and usage-ledger totals.
* **cluster observability** — ``/usage`` and ``/healthz`` grow a
  ``cluster`` roll-up block; ``/metrics`` stays per-process with
  ``host``/``process`` constant labels for Prometheus-native
  federation.

See README "Multi-host serving" for the topology and flags.
"""

from mpi_tpu.cluster.gossip import GOSSIP_PATH, Gossiper, send_digest
from mpi_tpu.cluster.node import ClusterNode, node_tag
from mpi_tpu.cluster.proxy import (
    FORWARDED_HEADER, SESSION_ID_HEADER, PeerUnreachable, proxy_request,
    split_addr,
)
from mpi_tpu.cluster.ring import HashRing, RoutingTable

__all__ = [
    "ClusterNode", "Gossiper", "HashRing", "PeerUnreachable",
    "RoutingTable", "FORWARDED_HEADER", "GOSSIP_PATH", "SESSION_ID_HEADER",
    "node_tag", "proxy_request", "send_digest", "split_addr",
]
