""":class:`ClusterNode` — one serving process's membership in the slice.

The node owns the cluster-local state the rest of the stack consults:

* identity — the advertised ``host:port`` is the node id; its 6-hex-char
  sha1 ``tag`` namespaces session ids (``s3-ab12cd``) and ticket ids
  (``t7@ab12cd``) so any front can read an id and know the owner without
  a lookup;
* membership — an epoch-versioned member map (ISSUE 14): every entry is
  ``addr -> (status, version)`` where the version is the epoch at which
  that fact was asserted.  Joins (``POST /cluster/join``), suspect →
  confirmed-dead transitions (missed heartbeats), and drains each bump
  the epoch; gossip carries the whole map and higher versions win (tie:
  dead wins), so views converge without coordination.  The consistent-
  hash ring is rebuilt from the *alive* members on every change;
* placement — :meth:`owner_addr` (routing table first, consistent-hash
  ring fallback) answers "which process serves this session".  Routes
  carry the epoch they were recorded at, so a failover adoption's route
  beats the dead owner's stale one in every merge order;
* failover — when a peer is confirmed dead, the new ring owner of each
  orphaned session restores it from the shared ``--state-dir`` via the
  deterministic replay path (``serve/recovery.py``) and re-records +
  gossips the route.  Tickets are process-local by contract: the dead
  node's tag is kept as a tombstone so its tickets keep answering the
  exact structured 404 (adoption never resurrects a ticket);
* drain — :meth:`drain` checkpoints every local session at its current
  generation, hands each to its ring successor (``POST
  /cluster/adopt``), and flips ``/healthz`` to draining.  The handoff
  is synchronous per successor: routes move only after the successor
  confirmed adoption, so no generation is ever lost;
* gossip — :meth:`digest`/:meth:`apply_digest` implement the push-pull
  exchange (``cluster/gossip.py`` drives it on a timer;
  :meth:`gossip_now` runs one synchronous round, which the tests and
  ``tools/cluster_smoke.py`` use for determinism).  A digest carries
  heartbeat + session count, the epoch + member map, the sender's
  open-breaker labels, cumulative usage-ledger totals, and routes;
* roll-ups — :meth:`usage_rollup` (the ``cluster`` block on
  ``GET /usage``) sums the latest ledger snapshot from every node
  exactly; :meth:`health_block` (the ``cluster`` block on ``/healthz``)
  reports per-peer liveness from heartbeat age.

Everything here is stdlib; nothing imports jax.  Network faults from
``--inject-faults`` (sites ``gossip``/``proxy``) are injected through
:meth:`net_fault`, so membership convergence and failover are testable
under deterministic seeded partitions.
"""

from __future__ import annotations

import hashlib
import re
import sys
import threading
import time
from typing import Dict, List, Optional

from mpi_tpu.config import ConfigError
from mpi_tpu.cluster.gossip import (
    Gossiper, send_adopt, send_digest, send_join,
)
from mpi_tpu.cluster.proxy import PeerUnreachable, split_addr
from mpi_tpu.cluster.ring import HashRing, RoutingTable
from mpi_tpu.serve.faults import InjectedNetworkFault


def node_tag(addr: str) -> str:
    """The 6-hex-char tag a node stamps into the ids it allocates —
    deterministic from the advertised address, so every peer can map an
    id back to its owner without any protocol round."""
    return hashlib.sha1(addr.encode()).hexdigest()[:6]


class _PeerState:
    """What gossip has taught us about one live peer (guarded by the
    node lock)."""

    __slots__ = ("addr", "tag", "last_seen", "last_seq", "sessions",
                 "ledger", "slo", "tenants", "breakers_open", "added_at",
                 "inc", "suspect", "persist_degraded")

    def __init__(self, addr: str):
        self.addr = addr
        self.tag = node_tag(addr)
        self.last_seen: Optional[float] = None      # monotonic heartbeat
        self.last_seq = 0
        self.sessions = 0
        self.ledger: Optional[dict] = None          # latest totals() snapshot
        self.slo: Optional[dict] = None             # latest compact SLO state
        self.tenants: Optional[dict] = None         # latest tenant windows
        self.breakers_open: List[str] = []
        self.added_at = time.monotonic()            # suspect clock baseline
        self.inc: Optional[float] = None            # sender incarnation
        self.suspect = False
        # the peer's persistence-degraded bit (ISSUE 18): while True,
        # its recent checkpoints are known-unwritten, so failover must
        # not adopt its sessions from the shared state dir
        self.persist_degraded = False


class ClusterNode:
    """One process's view of the slice.  Constructed after the serving
    socket is bound (the advertise address must be real), attached via
    ``SessionManager.attach_cluster`` and ``AppCore.cluster``."""

    def __init__(self, advertise: str, peers: List[str], manager, *,
                 interval_s: float = 1.0, timeout_s: float = 5.0,
                 down_after_s: Optional[float] = None,
                 dead_after_s: Optional[float] = None,
                 proxy_retries: int = 2,
                 proxy_backoff_s: float = 0.05,
                 proxy_timeout_s: Optional[float] = None,
                 state_dir: Optional[str] = None, obs=None):
        split_addr(advertise)           # validate early: ValueError on junk
        self.id = advertise
        self.tag = node_tag(advertise)
        self.manager = manager
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        # proxy-hop hardening (ISSUE 14 satellite): idempotent verbs
        # retry with backoff; the hop timeout is its own knob
        if proxy_retries < 0:
            raise ConfigError(
                f"proxy retries must be >= 0, got {proxy_retries}")
        self.proxy_retries = int(proxy_retries)
        self.proxy_backoff_s = max(0.0, float(proxy_backoff_s))
        self.proxy_timeout_s = (float(proxy_timeout_s)
                                if proxy_timeout_s is not None
                                else self.timeout_s)
        # a peer is "down"/suspect when its heartbeat is older than
        # down_after_s (also the TTL on remote-open breaker quarantines);
        # it is CONFIRMED dead — removed from the ring, sessions adopted
        # — when the silence exceeds dead_after_s
        self.down_after_s = (float(down_after_s) if down_after_s is not None
                             else max(3.0 * self.interval_s, 1.5))
        self.dead_after_s = (float(dead_after_s) if dead_after_s is not None
                             else 3.0 * self.down_after_s)
        if self.dead_after_s < self.down_after_s:
            raise ConfigError(
                f"dead-after ({self.dead_after_s}s) must be >= down-after "
                f"({self.down_after_s}s)")
        self.peers: Dict[str, _PeerState] = {}
        for addr in peers:
            addr = addr.strip()
            if not addr or addr == advertise:
                continue                # tolerate self in the peer list
            split_addr(addr)
            self.peers.setdefault(addr, _PeerState(addr))
        tags = {self.tag: self.id}
        for ps in self.peers.values():
            other = tags.setdefault(ps.tag, ps.addr)
            if other != ps.addr:
                raise ConfigError(
                    f"peer tag collision: {other!r} and {ps.addr!r} both "
                    f"hash to {ps.tag!r}; change one address")
        # membership: addr -> [status, version]; the version is the
        # epoch at which the fact was asserted (higher wins, tie: dead
        # wins).  Dead entries persist as tombstones — they keep the
        # fact circulating and anchor the ticket-404 contract.
        self.epoch = 0
        self.members: Dict[str, List] = {self.id: ["alive", 0]}
        for addr in self.peers:
            self.members[addr] = ["alive", 0]
        self._dead: Dict[str, dict] = {}            # addr -> tombstone info
        self._dead_tags: Dict[str, str] = {}        # tag -> dead addr
        self.draining = False
        self.ring = HashRing([self.id] + list(self.peers))
        # the routing table is per-node even under a shared --state-dir
        # (the session records are shared for failover; each node's
        # learned view is its own)
        path = (f"{state_dir}/routing-{self.tag}.json" if state_dir
                else None)
        self.table = RoutingTable(path)
        self._lock = threading.Lock()
        self._adopt_lock = threading.Lock()
        self._no_adopt: set = set()     # sids with no record: don't re-try
        self._seq = 0
        self._inc = time.time()         # incarnation: resets peer seq gates
        self.gossip_sent = 0
        self.gossip_received = 0
        self.gossip_stale = 0           # duplicate/late digests discarded
        self.gossip_errors = 0
        self.membership_changes = {"join": 0, "rejoin": 0, "confirm_dead": 0}
        self.failover_adopted = 0
        self.failover_lost = 0
        self.drain_handed_off = 0
        self.drain_adopted = 0
        self._gossiper = Gossiper(self, interval_s)
        self._obs = obs
        self._sid_next = 1
        self.sync_local_sessions()
        if obs is not None:
            self._bind_metrics(obs)

    # -- identity & placement ----------------------------------------------

    def new_session_id(self) -> str:
        """The next session id this node may allocate — globally unique
        because the tag is, whichever front the create landed on."""
        with self._lock:
            n = self._sid_next
            self._sid_next += 1
        return f"s{n}-{self.tag}"

    def sync_local_sessions(self) -> None:
        """Re-announce the manager's local sessions to the routing
        table and resume the sid counter past them (boot restore and
        cluster-deferred restore both land here) — a restart with the
        same ``--state-dir`` cannot re-issue a live id."""
        sids = self.manager.session_ids()
        start = 1
        for sid in sids:
            m = re.match(r"s(\d+)", sid)
            if m:
                start = max(start, int(m.group(1)) + 1)
        with self._lock:
            self._sid_next = max(self._sid_next, start)
            epoch = self.epoch
        self.table.update({sid: (self.id, epoch) for sid in sids})

    def owner_addr(self, sid: str) -> str:
        """The node serving ``sid``: an explicit route when one is known
        (create-time record, gossip, or adoption), else the ring's
        stateless placement.  Routes naming nodes outside the live
        membership are ignored — a stale table must degrade to the
        ring, never proxy into a dead address."""
        route = self.table.get(sid)
        if route is not None and (route == self.id or route in self.peers):
            return route
        return self.ring.owner(sid)

    def ticket_owner_addr(self, tid: str) -> Optional[str]:
        """The live peer owning ticket ``tid``, or None when it is
        local (our tag, an unsuffixed pre-cluster id, or an unknown tag
        — the local lookup then answers the structured 404 the contract
        promises)."""
        _, sep, tag = tid.partition("@")
        if not sep or tag == self.tag:
            return None
        with self._lock:
            for ps in self.peers.values():
                if ps.tag == tag:
                    return ps.addr
        return None

    def dead_ticket_addr(self, tid: str) -> Optional[str]:
        """The confirmed-dead member a ticket's tag names, if any.
        Tickets are process-local and died with their process; the
        transport answers the exact structured 404 (``{"error",
        "peer"}``) without a doomed proxy attempt — failover adoption
        restores *sessions*, never tickets."""
        _, sep, tag = tid.partition("@")
        if not sep or tag == self.tag:
            return None
        with self._lock:
            return self._dead_tags.get(tag)

    def record_route(self, sid: str, node: Optional[str] = None) -> None:
        """Record ``sid``'s owner (default: this node).  The allocating
        front passes the peer it just placed a create on: a route known
        only to its owner is lost if the owner dies before its first
        gossip round, and failover can only adopt orphans somebody's
        table (or the sid's tag suffix) still names."""
        with self._lock:
            epoch = self.epoch
        self.table.update({sid: (node or self.id, epoch)})

    # -- fault injection (sites: gossip, proxy) ----------------------------

    def net_fault(self, site: str, peer: str) -> None:
        """The chaos seam: consult the manager's fault injector before
        an outbound network attempt.  An injected drop/partition
        surfaces as :class:`PeerUnreachable` — exactly what a real
        severed link raises, so every downstream path (gossip error
        counting, proxy retry, suspect/confirm) is the production
        one."""
        faults = getattr(self.manager, "faults", None)
        if faults is None:
            return
        try:
            faults.net_hook(site, peer)
        except InjectedNetworkFault as e:
            raise PeerUnreachable(str(e)) from e

    def inbound_cut(self, site: str) -> bool:
        faults = getattr(self.manager, "faults", None)
        return faults is not None and faults.inbound_cut(site)

    # -- membership --------------------------------------------------------

    def _rebuild_ring_locked(self) -> None:
        alive = [a for a, (st, _) in self.members.items() if st == "alive"]
        if self.id not in alive:
            alive.append(self.id)       # we are always our own member
        self.ring = HashRing(alive)

    def _admit_locked(self, addr: str, version: int) -> bool:
        """Create live peer state for ``addr`` (lock held).  False when
        the address cannot be admitted (tag collision — warned, not
        fatal: one junk joiner must not take the node down)."""
        tag = node_tag(addr)
        if tag == self.tag or any(ps.tag == tag
                                  for ps in self.peers.values()):
            print(f"[mpi_tpu] warning: cannot admit {addr!r}: tag "
                  f"{tag!r} collides with an existing member",
                  file=sys.stderr)
            return False
        self.peers[addr] = _PeerState(addr)
        self.members[addr] = ["alive", int(version)]
        self._dead_tags.pop(tag, None)
        self._dead.pop(addr, None)
        return True

    def handle_join(self, addr: str) -> dict:
        """``POST /cluster/join`` — admit a fresh process at any
        advertise address.  Idempotent: a known member re-joining is
        re-asserted alive at a fresh epoch (so a racing death tombstone
        elsewhere loses the merge).  The reply carries our digest —
        one successful join teaches the joiner the whole membership."""
        addr = str(addr).strip()
        split_addr(addr)                # ValueError -> structured 400
        kind = None
        if addr != self.id:
            with self._lock:
                self.epoch += 1
                was_dead = addr in self._dead
                if addr in self.peers:
                    self.members[addr] = ["alive", self.epoch]
                    kind = "rejoin"
                elif self._admit_locked(addr, self.epoch):
                    self._rebuild_ring_locked()
                    kind = "rejoin" if was_dead else "join"
                epoch = self.epoch
            if kind is not None:
                self.membership_changes[kind] += 1
                self.event("membership_change", kind=kind, member=addr,
                            epoch=epoch)
        return {"ok": True, "node": self.id, "epoch": self.epoch,
                "members": self._members_copy(), "digest": self.digest()}

    def join_cluster(self) -> int:
        """Announce ourselves to every seed peer (best-effort; returns
        how many answered).  This is what lets a *replacement* process
        enter at a fresh address: its seeds may not list it in their
        own ``--peers``, and plain gossip from an unknown sender is
        dropped — the explicit join is the admission path."""
        joined = 0
        for addr in list(self.peers):
            try:
                reply = send_join(addr, self.id, timeout_s=self.timeout_s)
            except PeerUnreachable:
                continue
            joined += 1
            their = reply.get("digest")
            if isinstance(their, dict):
                self.apply_digest(their)
        return joined

    def check_membership(self) -> List[str]:
        """Advance the suspect → confirmed-dead state machine from
        heartbeat ages (driven by every gossip round; tests call it
        directly).  Returns the addresses confirmed dead this pass —
        each is removed from membership and the ring at a bumped epoch,
        and its orphaned sessions go through failover adoption."""
        now = time.monotonic()
        confirmed = []
        with self._lock:
            for addr, ps in self.peers.items():
                ref = ps.last_seen if ps.last_seen is not None else ps.added_at
                age = now - ref
                if age > self.dead_after_s:
                    confirmed.append(addr)
                else:
                    ps.suspect = age > self.down_after_s
        for addr in confirmed:
            self._confirm_dead(addr)
        return confirmed

    def _confirm_dead(self, addr: str) -> None:
        with self._lock:
            ps = self.peers.pop(addr, None)
            if ps is None:
                return                  # raced with another confirmation
            self.epoch += 1
            epoch = self.epoch
            self.members[addr] = ["dead", epoch]
            self._dead_tags[ps.tag] = addr
            self._dead[addr] = {
                "tag": ps.tag,
                "last_seen": (ps.last_seen if ps.last_seen is not None
                              else ps.added_at),
                "sessions": ps.sessions,
                "persist_degraded": ps.persist_degraded,
            }
            self._rebuild_ring_locked()
            self.membership_changes["confirm_dead"] += 1
        adopted, lost = self._failover(addr, ps.tag, epoch,
                                       degraded=ps.persist_degraded)
        self.event("membership_change", kind="confirm_dead", member=addr,
                    epoch=epoch, adopted=adopted, lost=lost)

    def _failover(self, addr: str, tag: str, epoch: int,
                  degraded: bool = False):
        """Adopt the dead node's orphaned sessions that the post-death
        ring assigns to THIS node, from the shared state dir, via the
        deterministic replay path.  Routes re-record at the death epoch
        so they beat the dead owner's stale entries in every merge.
        ``degraded``: the dead peer's last gossiped persistence bit —
        True means its recent checkpoints are known-unwritten, so
        adopting its records would silently resurrect stale boards;
        the sessions are counted lost instead (a loud, honest outcome
        the operator can act on: scrub → repair → adopt)."""
        mgr = self.manager
        store = getattr(mgr, "store", None)
        adopted = lost = 0
        candidates = {sid for sid, node in self.table.snapshot().items()
                      if node == addr}
        if degraded:
            n = len(candidates)
            if store is not None:
                suffix = f"-{tag}"
                n = len(candidates | {sid for sid in store.list_ids()
                                      if sid.endswith(suffix)})
            if n:
                print(f"warning: not adopting {n} session(s) from dead "
                      f"peer {addr}: its persistence was degraded "
                      f"(checkpoints known-unwritten); run tools/scrub.py "
                      f"on the state dir, then POST /cluster/adopt",
                      file=sys.stderr)
            with self._lock:
                self.failover_lost += n
            return 0, n
        if store is not None:
            # records the dead node persisted but whose routes never
            # reached us: the sid carries the ALLOCATING front's tag, so
            # this over-approximates (a session allocated at the dead
            # front may have been placed elsewhere) — the held-set and
            # ring gates below discard the false positives
            suffix = f"-{tag}"
            candidates.update(sid for sid in store.list_ids()
                              if sid.endswith(suffix))
        held = set(mgr.session_ids())
        for sid in sorted(candidates):
            if sid in held:
                continue                # already (still) served here
            if self.ring.owner(sid) != self.id:
                continue                # the new owner adopts, not us
            with self._adopt_lock:
                ok = mgr.adopt_session(sid)
            if ok:
                adopted += 1
                self.table.update({sid: (self.id, epoch)})
            else:
                lost += 1
        with self._lock:
            self.failover_adopted += adopted
            self.failover_lost += lost
        return adopted, lost

    def handle_adopt(self, sids: List[str]) -> dict:
        """``POST /cluster/adopt`` — a draining peer hands us sessions
        it has just checkpointed.  Restore each from the shared state
        dir and claim the route at a fresh epoch."""
        with self._lock:
            self.epoch += 1
            epoch = self.epoch
        adopted, failed = [], []
        for sid in sids:
            sid = str(sid)
            self._no_adopt.discard(sid)
            with self._adopt_lock:
                ok = self.manager.adopt_session(sid)
            if ok:
                self.table.update({sid: (self.id, epoch)})
                adopted.append(sid)
            else:
                failed.append(sid)
        with self._lock:
            self.drain_adopted += len(adopted)
        return {"ok": not failed, "node": self.id, "epoch": epoch,
                "adopted": adopted, "failed": failed}

    def drain(self) -> dict:
        """``POST /cluster/drain`` — migrate every local session to its
        ring successor and flip ``/healthz`` to draining.  Per
        successor: checkpoint each session at its CURRENT generation
        (full grid snapshot — the adopter replays zero generations),
        ask the successor to adopt, and only then move the routes and
        release the local copies.  A successor that cannot adopt leaves
        its batch local and still served — zero lost generations either
        way."""
        with self._lock:
            others = [n for n in self.ring.nodes if n != self.id]
            if not others:
                raise ConfigError("cannot drain the only cluster member")
            self.draining = True
            self.epoch += 1
            epoch = self.epoch
        succ_ring = HashRing(others)
        handoffs: Dict[str, List[str]] = {}
        for sid in self.manager.session_ids():
            handoffs.setdefault(succ_ring.owner(sid), []).append(sid)
        moved: Dict[str, List[str]] = {}
        errors: Dict[str, str] = {}
        for succ, batch in sorted(handoffs.items()):
            try:
                for sid in batch:
                    self.manager.checkpoint_now(sid)
                self.net_fault("proxy", succ)
                reply = send_adopt(succ, self.id, batch,
                                   timeout_s=self.proxy_timeout_s)
            except (PeerUnreachable, KeyError, OSError) as e:
                # OSError covers the drain checkpoint failing to land
                # (injected io fault, degraded store): the batch stays
                # local and served — handing it off would lose every
                # generation since the last durable record
                errors[succ] = str(e)
                continue
            accepted = [sid for sid in reply.get("adopted") or []
                        if sid in batch]
            if accepted:
                self.table.update({sid: (succ, epoch) for sid in accepted})
                for sid in accepted:
                    try:
                        self.manager.release(sid)
                    except KeyError:
                        pass
                moved[succ] = accepted
        n_moved = sum(len(v) for v in moved.values())
        with self._lock:
            self.drain_handed_off += n_moved
        self.event("membership_change", kind="drain", member=self.id,
                    epoch=epoch, handed_off=n_moved)
        self.gossip_now()               # push the moved routes out now
        return {"ok": not errors, "node": self.id, "draining": True,
                "epoch": epoch, "handed_off": n_moved, "handoffs": moved,
                "errors": errors}

    # -- gossip ------------------------------------------------------------

    def digest(self) -> dict:
        """This node's current digest.  Breaker labels are the LOCAL
        open set only — remote-open quarantines learned from gossip are
        never re-announced, so a label can circulate only while its
        origin still asserts it (no echo keeping a closed breaker
        alive).  Membership rides as the full epoch-versioned map;
        routes as the full table with their epochs."""
        mgr = self.manager
        with self._lock:
            self._seq += 1
            seq = self._seq
            epoch = self.epoch
        sids = mgr.session_ids()
        missing = [sid for sid in sids if self.table.get(sid) is None]
        if missing:
            self.table.update({sid: (self.id, epoch) for sid in missing})
        return {
            "node": self.id,
            "seq": seq,
            "inc": self._inc,
            "epoch": epoch,
            "members": self._members_copy(),
            "sessions": len(sids),
            "breakers_open": mgr.cache.breaker_stats()["open"],
            "ledger": (mgr.obs.ledger.totals()
                       if mgr.obs is not None else None),
            # armed-only (ISSUE 15): unarmed nodes gossip None and the
            # /slo roll-up counts them as not reporting
            "slo": (mgr.obs.slo.compact()
                    if mgr.obs is not None and mgr.obs.slo is not None
                    else None),
            # armed-only (ISSUE 16): per-tenant window-spend snapshots
            # (absolute, merge_totals discipline — latest per node) so
            # quotas gate against cluster-wide spend, not node slices
            "tenants": (mgr.admission.window_snapshot()
                        if getattr(mgr, "admission", None) is not None
                        else None),
            # the degraded bit (ISSUE 18): True while this node's state
            # dir is refusing writes — peers must not failover-adopt
            # from records we may not have written
            "persist_degraded": bool(
                getattr(mgr, "store", None) is not None
                and mgr.store.is_degraded()),
            "routes": self.table.snapshot_entries(),
        }

    def _members_copy(self) -> Dict[str, List]:
        with self._lock:
            return {addr: list(entry)
                    for addr, entry in self.members.items()}

    def apply_digest(self, digest: dict) -> bool:
        """Fold one received digest in; returns True when it advanced
        state.  Any delivery refreshes the sender's heartbeat, but only
        a sequence number beyond the last seen applies — duplicates and
        stragglers are idempotent no-ops on every roll-up.  A digest
        from a tombstoned member is an implicit rejoin (it is evidently
        alive); one from a complete stranger is dropped — admission is
        ``/cluster/join``'s job."""
        addr = digest.get("node")
        seq = digest.get("seq")
        if not isinstance(seq, int):
            return False
        ps = self.peers.get(addr)
        if ps is None:
            if addr in self._dead:
                self._readmit(addr)
                ps = self.peers.get(addr)
            if ps is None:
                return False            # unknown sender or junk: drop
        with self._lock:
            ps.last_seen = time.monotonic()
            ps.suspect = False
            inc = digest.get("inc")
            if (isinstance(inc, (int, float)) and ps.inc is not None
                    and inc != ps.inc):
                ps.last_seq = 0         # restarted peer: fresh seq space
            if isinstance(inc, (int, float)):
                ps.inc = inc
            if seq <= ps.last_seq:
                self.gossip_stale += 1
                return False
            ps.last_seq = seq
            ps.sessions = int(digest.get("sessions") or 0)
            ledger = digest.get("ledger")
            ps.ledger = ledger if isinstance(ledger, dict) else None
            slo = digest.get("slo")
            ps.slo = slo if isinstance(slo, dict) else None
            tenants = digest.get("tenants")
            ps.tenants = tenants if isinstance(tenants, dict) else None
            ps.breakers_open = [str(b) for b in
                                (digest.get("breakers_open") or [])]
            ps.persist_degraded = bool(digest.get("persist_degraded"))
            breakers = list(ps.breakers_open)
            self.gossip_received += 1
        self.manager.cache.set_remote_open(addr, breakers,
                                           ttl_s=self.down_after_s)
        self._merge_members(digest)
        routes = digest.get("routes")
        if isinstance(routes, dict):
            self.table.update(routes)
            self._adopt_routed_here(routes)
        return True

    def _merge_members(self, digest: dict) -> None:
        """Fold the sender's member map in: higher versions win, ties
        go to dead (a death is asserted, liveness only observed).  A
        tombstone naming US at a version we have not outbid is a wrong
        obituary — re-assert alive at a fresh epoch so the correction
        out-versions it everywhere."""
        members = digest.get("members")
        if not isinstance(members, dict):
            return
        newly_dead = []
        changed_ring = False
        with self._lock:
            for maddr, entry in members.items():
                if (not isinstance(entry, (list, tuple)) or len(entry) != 2):
                    continue
                st, ver = str(entry[0]), entry[1]
                if not isinstance(ver, int) or st not in ("alive", "dead"):
                    continue
                if maddr == self.id:
                    mine = self.members[self.id]
                    if st == "dead" and ver >= mine[1]:
                        self.epoch = max(self.epoch, ver) + 1
                        self.members[self.id] = ["alive", self.epoch]
                        changed_ring = True     # ring itself is fine, but
                        # peers rebuilt theirs without us; re-announcing at
                        # a higher version re-admits us on their side
                    continue
                cur = self.members.get(maddr)
                if cur is not None and (ver < cur[1] or
                                        (ver == cur[1]
                                         and (st == cur[0]
                                              or cur[0] == "dead"))):
                    continue            # stale, identical, or losing tie
                if st == "alive":
                    if maddr in self.peers:
                        self.members[maddr] = ["alive", ver]
                    elif self._admit_locked(maddr, ver):
                        changed_ring = True
                else:
                    self.members[maddr] = ["dead", ver]
                    dead_ps = self.peers.pop(maddr, None)
                    if dead_ps is not None:
                        self._dead_tags[dead_ps.tag] = maddr
                        self._dead[maddr] = {
                            "tag": dead_ps.tag,
                            "last_seen": (dead_ps.last_seen
                                          if dead_ps.last_seen is not None
                                          else dead_ps.added_at),
                            "sessions": dead_ps.sessions,
                        }
                        newly_dead.append((maddr, dead_ps.tag))
                        changed_ring = True
            self.epoch = max(self.epoch,
                             digest.get("epoch") if isinstance(
                                 digest.get("epoch"), int) else 0)
            if changed_ring:
                self._rebuild_ring_locked()
        for maddr, tag in newly_dead:
            with self._lock:
                self.membership_changes["confirm_dead"] += 1
                epoch = self.epoch
            adopted, lost = self._failover(maddr, tag, epoch)
            self.event("membership_change", kind="confirm_dead",
                        member=maddr, epoch=epoch, adopted=adopted,
                        lost=lost, learned=True)

    def _readmit(self, addr: str) -> None:
        """A tombstoned member contacted us — it is evidently alive.
        Re-admit it at a fresh epoch (the implicit-rejoin half of
        partition healing; the explicit half is ``/cluster/join``)."""
        with self._lock:
            if addr in self.peers or addr not in self._dead:
                return
            self.epoch += 1
            if not self._admit_locked(addr, self.epoch):
                return
            self._rebuild_ring_locked()
            epoch = self.epoch
            self.membership_changes["rejoin"] += 1
        self.event("membership_change", kind="rejoin", member=addr,
                    epoch=epoch)

    def _adopt_routed_here(self, routes: dict) -> None:
        """The gossip backup for drain handoff: a route naming US for a
        session we do not hold means a peer moved it here (its direct
        /cluster/adopt may have raced or failed).  Adopt from the
        shared state dir; a sid with no record is remembered and never
        re-tried (e.g. a closed session whose route still circulates)."""
        mgr = self.manager
        if getattr(mgr, "store", None) is None:
            return
        held = set(mgr.session_ids())
        for sid, val in routes.items():
            node = val[0] if isinstance(val, (list, tuple)) else val
            if node != self.id or sid in held or sid in self._no_adopt:
                continue
            with self._adopt_lock:
                ok = mgr.adopt_session(sid)
            if ok:
                with self._lock:
                    self.drain_adopted += 1
            else:
                self._no_adopt.add(sid)

    def gossip_now(self) -> None:
        """One synchronous push-pull round with every live peer,
        followed by one membership check (the timer thread's body; also
        the deterministic hook the tests drive).  The chaos harness
        taps the send path through :meth:`net_fault` — an injected
        drop counts as a gossip error exactly like a severed link."""
        digest = self.digest()
        for addr in list(self.peers):
            try:
                self.net_fault("gossip", addr)
                reply = send_digest(addr, digest, timeout_s=self.timeout_s)
            except PeerUnreachable:
                with self._lock:
                    self.gossip_errors += 1
                continue
            with self._lock:
                self.gossip_sent += 1
            their = reply.get("digest")
            if isinstance(their, dict):
                self.apply_digest(their)
        self.check_membership()

    def start(self) -> None:
        # the join runs on the gossip thread (Gossiper fires it before
        # its first round): two processes booting together must not
        # block each other's startup on a synchronous mutual join —
        # neither is accepting yet, and the stall would push the first
        # heartbeat past dead_after_s and flap the membership
        self._gossiper.start()

    def stop(self) -> None:
        self._gossiper.stop()

    # -- roll-ups ----------------------------------------------------------

    def tenant_spend(self, tenant: str):
        """Peer spend for one tenant: ``(device_s, cells, sessions)``
        summed over each peer's latest gossiped window snapshot (the
        QuotaGate adds the local books itself).  Same exactness contract
        as ``usage_rollup``: absolute snapshots, latest per node, at
        most one gossip interval stale."""
        device_s, cells, sessions = 0.0, 0, 0
        with self._lock:
            snaps = [ps.tenants for ps in self.peers.values()
                     if ps.tenants is not None]
        for snap in snaps:
            row = snap.get(tenant)
            if not isinstance(row, dict):
                continue
            try:
                device_s += float(row.get("device_s") or 0.0)
                cells += int(row.get("cells") or 0)
                sessions += int(row.get("sessions") or 0)
            except (TypeError, ValueError):
                continue                # junk from a peer never rejects
        return device_s, cells, sessions

    def usage_rollup(self) -> dict:
        """The ``cluster`` block on ``GET /usage``: exact sums over the
        local ledger plus each peer's latest gossiped totals (cumulative
        snapshots, not deltas — replacement is idempotent, so the sum is
        exact as of each peer's last digest, at most one interval
        stale)."""
        from mpi_tpu.obs.ledger import merge_totals

        mgr = self.manager
        local = mgr.obs.ledger.totals() if mgr.obs is not None else None
        by_node: Dict[str, Optional[dict]] = {self.id: local}
        with self._lock:
            for addr, ps in self.peers.items():
                by_node[addr] = ps.ledger
        reporting = [t for t in by_node.values() if t]
        return {
            "node": self.id,
            "nodes": len(by_node),
            "nodes_reporting": len(reporting),
            "totals": merge_totals(reporting),
            "by_node": by_node,
        }

    def slo_rollup(self) -> dict:
        """The ``cluster`` block on ``GET /slo``: the local compact SLO
        state plus each peer's latest gossiped one.  Transition counts
        are CUMULATIVE per node, so summing the snapshots is exact as of
        each peer's last digest (the ledger roll-up discipline); a peer
        whose heartbeat says it is down lands in ``partial`` like the
        trace fan-out's — its stale snapshot stays visible in
        ``by_node`` but the roll-up admits it is incomplete."""
        _rank = {"ok": 0, "warning": 1, "critical": 2}
        mgr = self.manager
        local = (mgr.obs.slo.compact()
                 if mgr.obs is not None and mgr.obs.slo is not None
                 else None)
        by_node: Dict[str, Optional[dict]] = {self.id: local}
        with self._lock:
            for addr, ps in self.peers.items():
                by_node[addr] = ps.slo
        partial = sorted(addr for addr, st in
                         self.health_block()["peers"].items()
                         if not st["alive"])
        reporting = [s for s in by_node.values() if s]
        states = [s.get("worst") for s in reporting]
        states = [s for s in states if s in _rank]
        burning: Dict[str, str] = {}
        for snap in reporting:
            for name, state in (snap.get("states") or {}).items():
                if state in _rank and state != "ok" and \
                        _rank[state] > _rank.get(burning.get(name), -1):
                    burning[name] = state
        return {
            "node": self.id,
            "nodes": len(by_node),
            "nodes_reporting": len(reporting),
            "partial": partial,
            "complete": not partial,
            "worst": (max(states, key=_rank.__getitem__)
                      if states else "ok"),
            "burning": burning,
            "transitions_total": sum(
                int(s.get("transitions") or 0) for s in reporting),
            "by_node": by_node,
        }

    def health_block(self) -> dict:
        """The ``cluster`` block on ``/healthz``: per-peer liveness from
        heartbeat age, with the membership state machine spelled out
        (``alive``/``suspect``/``dead``).  Confirmed-dead members stay
        listed (alive: False) so operators and the trace fan-out see
        them; they are out of the ring regardless.  A down peer never
        flips the node's own ``ok`` — this process can still serve
        everything it owns."""
        now = time.monotonic()
        peers = {}
        with self._lock:
            for addr, ps in self.peers.items():
                ref = (ps.last_seen if ps.last_seen is not None
                       else ps.added_at)
                age = None if ps.last_seen is None else now - ps.last_seen
                alive = age is not None and age <= self.down_after_s
                peers[addr] = {
                    "alive": alive,
                    "state": ("alive" if alive else
                              "suspect" if now - ref > self.down_after_s
                              else "down"),
                    "last_seen_age_s": (None if age is None
                                        else round(age, 3)),
                    "sessions": ps.sessions,
                    "breakers_open": list(ps.breakers_open),
                }
            for addr, info in self._dead.items():
                peers[addr] = {
                    "alive": False,
                    "state": "dead",
                    "last_seen_age_s": round(now - info["last_seen"], 3),
                    "sessions": info["sessions"],
                    "breakers_open": [],
                }
            epoch, draining = self.epoch, self.draining
        return {"node": self.id, "tag": self.tag,
                "size": 1 + len([a for a in peers
                                 if peers[a]["state"] != "dead"]),
                "epoch": epoch, "draining": draining,
                "peers": peers}

    def info(self) -> dict:
        """``GET /cluster`` — the operator's one-stop membership view."""
        with self._lock:
            gossip = {
                "interval_s": self.interval_s,
                "sent": self.gossip_sent,
                "received": self.gossip_received,
                "stale": self.gossip_stale,
                "errors": self.gossip_errors,
            }
            members = {addr: list(entry)
                       for addr, entry in self.members.items()}
            failover = {
                "adopted": self.failover_adopted,
                "lost": self.failover_lost,
                "drain_handed_off": self.drain_handed_off,
                "drain_adopted": self.drain_adopted,
                "membership_changes": dict(self.membership_changes),
            }
        out = self.health_block()
        out["ring"] = self.ring.nodes
        out["members"] = members
        out["routes"] = len(self.table)
        out["gossip"] = gossip
        out["failover"] = failover
        return out

    # -- observability -----------------------------------------------------

    def event(self, name: str, **fields) -> None:
        """Emit one membership trace event (no-op without obs).  The
        call sites pass the kind literal directly so the obs-drift
        extraction sees every emitted name."""
        if self._obs is not None:
            self._obs.event(name, 0.0, time.time(), node=self.id, **fields)

    def _bind_metrics(self, obs) -> None:
        """Cluster metric families (scrape-time callbacks, same
        no-shadow-counting rule as ``Obs.bind_manager``).  Registered
        only in cluster mode — single-process scrapes keep their exact
        pre-cluster family set."""
        m = obs.metrics

        def _peer_liveness():
            peers = self.health_block()["peers"]
            alive = sum(1 for p in peers.values() if p["alive"])
            return [({"state": "alive"}, alive),
                    ({"state": "down"}, len(peers) - alive)]

        m.gauge_fn("mpi_tpu_cluster_peers",
                   "Cluster peers by gossip liveness state",
                   _peer_liveness)

        def _gossip_counts():
            with self._lock:
                return [({"direction": "sent"}, self.gossip_sent),
                        ({"direction": "received"}, self.gossip_received),
                        ({"direction": "stale"}, self.gossip_stale),
                        ({"direction": "error"}, self.gossip_errors)]

        m.counter_fn("mpi_tpu_cluster_gossip_total",
                     "Gossip digests exchanged, by direction/outcome",
                     _gossip_counts)

        def _epoch():
            with self._lock:
                return [({}, self.epoch)]

        m.gauge_fn("mpi_tpu_cluster_epoch",
                   "Membership epoch (bumps on join/confirm-dead/drain)",
                   _epoch)

        def _membership_changes():
            with self._lock:
                return [({"kind": k}, v)
                        for k, v in sorted(self.membership_changes.items())]

        m.counter_fn("mpi_tpu_cluster_membership_changes_total",
                     "Membership transitions applied, by kind",
                     _membership_changes)

        def _failover_sessions():
            with self._lock:
                return [({"outcome": "adopted"}, self.failover_adopted),
                        ({"outcome": "lost"}, self.failover_lost)]

        m.counter_fn("mpi_tpu_cluster_failover_sessions_total",
                     "Dead peers' sessions adopted from the shared "
                     "state dir (or lost: no record found)",
                     _failover_sessions)

        def _drain_sessions():
            with self._lock:
                return [({"direction": "handed_off"}, self.drain_handed_off),
                        ({"direction": "adopted"}, self.drain_adopted)]

        m.counter_fn("mpi_tpu_cluster_drain_sessions_total",
                     "Sessions migrated by drain, by direction",
                     _drain_sessions)

        def _table_resets():
            return [({}, self.table.resets)]

        m.counter_fn("mpi_tpu_routing_table_resets_total",
                     "Corrupt routing-table files discarded at load "
                     "(placement degraded to the ring)",
                     _table_resets)
