""":class:`ClusterNode` — one serving process's membership in the slice.

The node owns the cluster-local state the rest of the stack consults:

* identity — the advertised ``host:port`` is the node id; its 6-hex-char
  sha1 ``tag`` namespaces session ids (``s3-ab12cd``) and ticket ids
  (``t7@ab12cd``) so any front can read an id and know the owner without
  a lookup;
* placement — :meth:`owner_addr` (routing table first, consistent-hash
  ring fallback) answers "which process serves this session";
* gossip — :meth:`digest`/:meth:`apply_digest` implement the push-pull
  exchange (``cluster/gossip.py`` drives it on a timer;
  :meth:`gossip_now` runs one synchronous round, which the tests and
  ``tools/cluster_smoke.py`` use for determinism).  A digest carries
  heartbeat + session count, the sender's open-breaker labels (applied
  to the local :class:`~mpi_tpu.serve.cache.EngineCache` as
  remote-open quarantines), cumulative usage-ledger totals, and the
  sender's local routes;
* roll-ups — :meth:`usage_rollup` (the ``cluster`` block on
  ``GET /usage``) sums the latest ledger snapshot from every node
  exactly; :meth:`health_block` (the ``cluster`` block on ``/healthz``)
  reports per-peer liveness from heartbeat age.

Everything here is stdlib; nothing imports jax.
"""

from __future__ import annotations

import hashlib
import itertools
import re
import threading
import time
from typing import Dict, List, Optional

from mpi_tpu.config import ConfigError
from mpi_tpu.cluster.gossip import Gossiper, send_digest
from mpi_tpu.cluster.proxy import PeerUnreachable, split_addr
from mpi_tpu.cluster.ring import HashRing, RoutingTable


def node_tag(addr: str) -> str:
    """The 6-hex-char tag a node stamps into the ids it allocates —
    deterministic from the advertised address, so every peer can map an
    id back to its owner without any protocol round."""
    return hashlib.sha1(addr.encode()).hexdigest()[:6]


class _PeerState:
    """What gossip has taught us about one peer (guarded by the node
    lock)."""

    __slots__ = ("addr", "tag", "last_seen", "last_seq", "sessions",
                 "ledger", "breakers_open")

    def __init__(self, addr: str):
        self.addr = addr
        self.tag = node_tag(addr)
        self.last_seen: Optional[float] = None      # monotonic heartbeat
        self.last_seq = 0
        self.sessions = 0
        self.ledger: Optional[dict] = None          # latest totals() snapshot
        self.breakers_open: List[str] = []


class ClusterNode:
    """One process's view of the slice.  Constructed after the serving
    socket is bound (the advertise address must be real), attached via
    ``SessionManager.attach_cluster`` and ``AppCore.cluster``."""

    def __init__(self, advertise: str, peers: List[str], manager, *,
                 interval_s: float = 1.0, timeout_s: float = 5.0,
                 down_after_s: Optional[float] = None,
                 state_dir: Optional[str] = None, obs=None):
        split_addr(advertise)           # validate early: ValueError on junk
        self.id = advertise
        self.tag = node_tag(advertise)
        self.manager = manager
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        # a peer is "down" when its heartbeat is older than this; also
        # the TTL on remote-open breaker quarantines, so a dead peer's
        # poisoned-plan warnings age out with its liveness
        self.down_after_s = (float(down_after_s) if down_after_s is not None
                             else max(3.0 * self.interval_s, 1.5))
        self.peers: Dict[str, _PeerState] = {}
        for addr in peers:
            addr = addr.strip()
            if not addr or addr == advertise:
                continue                # tolerate self in the peer list
            split_addr(addr)
            self.peers.setdefault(addr, _PeerState(addr))
        tags = {self.tag: self.id}
        for ps in self.peers.values():
            other = tags.setdefault(ps.tag, ps.addr)
            if other != ps.addr:
                raise ConfigError(
                    f"peer tag collision: {other!r} and {ps.addr!r} both "
                    f"hash to {ps.tag!r}; change one address")
        self.ring = HashRing([self.id] + list(self.peers))
        path = (f"{state_dir}/routing.json" if state_dir else None)
        self.table = RoutingTable(path)
        self._lock = threading.Lock()
        self._seq = 0
        self.gossip_sent = 0
        self.gossip_received = 0
        self.gossip_stale = 0           # duplicate/late digests discarded
        self.gossip_errors = 0
        self._gossiper = Gossiper(self, interval_s)
        # session ordinals resume past any restored local sessions so a
        # restart with the same --state-dir cannot re-issue a live id
        start = 1
        for sid in manager.session_ids():
            m = re.match(r"s(\d+)", sid)
            if m:
                start = max(start, int(m.group(1)) + 1)
        self._sid_counter = itertools.count(start)
        # restored sessions re-announce themselves to the table (and to
        # peers, via the routes in every digest)
        self.table.update({sid: self.id for sid in manager.session_ids()})
        if obs is not None:
            self._bind_metrics(obs)

    # -- identity & placement ----------------------------------------------

    def new_session_id(self) -> str:
        """The next session id this node may allocate — globally unique
        because the tag is, whichever front the create landed on."""
        return f"s{next(self._sid_counter)}-{self.tag}"

    def owner_addr(self, sid: str) -> str:
        """The node serving ``sid``: an explicit route when one is known
        (create-time record or gossip), else the ring's stateless
        placement.  Routes naming nodes outside the slice are ignored —
        a stale table must degrade to the ring, not to a black hole."""
        route = self.table.get(sid)
        if route is not None and (route == self.id or route in self.peers):
            return route
        return self.ring.owner(sid)

    def ticket_owner_addr(self, tid: str) -> Optional[str]:
        """The peer owning ticket ``tid``, or None when it is local (our
        tag, an unsuffixed pre-cluster id, or an unknown tag — the local
        lookup then answers the structured 404 the contract promises)."""
        _, sep, tag = tid.partition("@")
        if not sep or tag == self.tag:
            return None
        with self._lock:
            for ps in self.peers.values():
                if ps.tag == tag:
                    return ps.addr
        return None

    def record_route(self, sid: str) -> None:
        self.table.update({sid: self.id})

    # -- gossip ------------------------------------------------------------

    def digest(self) -> dict:
        """This node's current digest.  Breaker labels are the LOCAL
        open set only — remote-open quarantines learned from gossip are
        never re-announced, so a label can circulate only while its
        origin still asserts it (no echo keeping a closed breaker
        alive)."""
        mgr = self.manager
        with self._lock:
            self._seq += 1
            seq = self._seq
        sids = mgr.session_ids()
        return {
            "node": self.id,
            "seq": seq,
            "sessions": len(sids),
            "breakers_open": mgr.cache.breaker_stats()["open"],
            "ledger": (mgr.obs.ledger.totals()
                       if mgr.obs is not None else None),
            "routes": {sid: self.id for sid in sids},
        }

    def apply_digest(self, digest: dict) -> bool:
        """Fold one received digest in; returns True when it advanced
        state.  Any delivery refreshes the sender's heartbeat, but only
        a sequence number beyond the last seen applies — duplicates and
        stragglers are idempotent no-ops on every roll-up."""
        addr = digest.get("node")
        seq = digest.get("seq")
        ps = self.peers.get(addr)
        if ps is None or not isinstance(seq, int):
            return False                # unknown sender or junk: drop
        with self._lock:
            ps.last_seen = time.monotonic()
            if seq <= ps.last_seq:
                self.gossip_stale += 1
                return False
            ps.last_seq = seq
            ps.sessions = int(digest.get("sessions") or 0)
            ledger = digest.get("ledger")
            ps.ledger = ledger if isinstance(ledger, dict) else None
            ps.breakers_open = [str(b) for b in
                                (digest.get("breakers_open") or [])]
            breakers = list(ps.breakers_open)
            self.gossip_received += 1
        self.manager.cache.set_remote_open(addr, breakers,
                                           ttl_s=self.down_after_s)
        routes = digest.get("routes")
        if isinstance(routes, dict):
            self.table.update({str(s): str(n) for s, n in routes.items()})
        return True

    def gossip_now(self) -> None:
        """One synchronous push-pull round with every peer (the timer
        thread's body; also the deterministic hook the tests drive)."""
        digest = self.digest()
        for addr in list(self.peers):
            try:
                reply = send_digest(addr, digest, timeout_s=self.timeout_s)
            except PeerUnreachable:
                with self._lock:
                    self.gossip_errors += 1
                continue
            with self._lock:
                self.gossip_sent += 1
            their = reply.get("digest")
            if isinstance(their, dict):
                self.apply_digest(their)

    def start(self) -> None:
        self._gossiper.start()

    def stop(self) -> None:
        self._gossiper.stop()

    # -- roll-ups ----------------------------------------------------------

    def usage_rollup(self) -> dict:
        """The ``cluster`` block on ``GET /usage``: exact sums over the
        local ledger plus each peer's latest gossiped totals (cumulative
        snapshots, not deltas — replacement is idempotent, so the sum is
        exact as of each peer's last digest, at most one interval
        stale)."""
        from mpi_tpu.obs.ledger import merge_totals

        mgr = self.manager
        local = mgr.obs.ledger.totals() if mgr.obs is not None else None
        by_node: Dict[str, Optional[dict]] = {self.id: local}
        with self._lock:
            for addr, ps in self.peers.items():
                by_node[addr] = ps.ledger
        reporting = [t for t in by_node.values() if t]
        return {
            "node": self.id,
            "nodes": len(by_node),
            "nodes_reporting": len(reporting),
            "totals": merge_totals(reporting),
            "by_node": by_node,
        }

    def health_block(self) -> dict:
        """The ``cluster`` block on ``/healthz``: per-peer liveness from
        heartbeat age.  A down peer never flips the node's own ``ok`` —
        this process can still serve everything it owns."""
        now = time.monotonic()
        peers = {}
        with self._lock:
            for addr, ps in self.peers.items():
                age = (None if ps.last_seen is None
                       else now - ps.last_seen)
                peers[addr] = {
                    "alive": age is not None and age <= self.down_after_s,
                    "last_seen_age_s": (None if age is None
                                        else round(age, 3)),
                    "sessions": ps.sessions,
                    "breakers_open": list(ps.breakers_open),
                }
        return {"node": self.id, "tag": self.tag, "size": 1 + len(peers),
                "peers": peers}

    def info(self) -> dict:
        """``GET /cluster`` — the operator's one-stop membership view."""
        with self._lock:
            gossip = {
                "interval_s": self.interval_s,
                "sent": self.gossip_sent,
                "received": self.gossip_received,
                "stale": self.gossip_stale,
                "errors": self.gossip_errors,
            }
        out = self.health_block()
        out["ring"] = self.ring.nodes
        out["routes"] = len(self.table)
        out["gossip"] = gossip
        return out

    # -- observability -----------------------------------------------------

    def _bind_metrics(self, obs) -> None:
        """Cluster metric families (scrape-time callbacks, same
        no-shadow-counting rule as ``Obs.bind_manager``).  Registered
        only in cluster mode — single-process scrapes keep their exact
        pre-cluster family set."""
        m = obs.metrics

        def _peer_liveness():
            peers = self.health_block()["peers"]
            alive = sum(1 for p in peers.values() if p["alive"])
            return [({"state": "alive"}, alive),
                    ({"state": "down"}, len(peers) - alive)]

        m.gauge_fn("mpi_tpu_cluster_peers",
                   "Cluster peers by gossip liveness state",
                   _peer_liveness)

        def _gossip_counts():
            with self._lock:
                return [({"direction": "sent"}, self.gossip_sent),
                        ({"direction": "received"}, self.gossip_received),
                        ({"direction": "stale"}, self.gossip_stale),
                        ({"direction": "error"}, self.gossip_errors)]

        m.counter_fn("mpi_tpu_cluster_gossip_total",
                     "Gossip digests exchanged, by direction/outcome",
                     _gossip_counts)
