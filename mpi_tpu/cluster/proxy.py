"""One-hop HTTP proxying between cluster peers (stdlib ``http.client``).

A front that does not own a session forwards the request to the owner
and relays the response bytes verbatim — the client cannot tell which
process served it.  Forwarded requests carry the ``X-Gol-Forwarded``
header, which the receiving core treats as "handle locally, no matter
what the ring says": one hop maximum, so a stale routing view can never
loop a request around the slice.
"""

from __future__ import annotations

import http.client
from typing import Dict, Optional, Tuple

# set on every proxied request (value: the forwarding node's id); its
# presence short-circuits routing on the receiving side
FORWARDED_HEADER = "X-Gol-Forwarded"
# create-path only: the forwarding front chose the session id (ids are
# allocated by the front that takes the request so the ring placement
# decision and the id agree)
SESSION_ID_HEADER = "X-Gol-Session-Id"


class PeerUnreachable(RuntimeError):
    """The owning peer did not answer (connect/read failure or timeout).
    The transport layer maps this to the structured 503 — or, for ticket
    reads, the structured 404 the single-process restart contract
    already promises."""


def split_addr(addr: str) -> Tuple[str, int]:
    """``host:port`` -> (host, port); raises ValueError on junk."""
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"peer address must look like host:port, "
                         f"got {addr!r}")
    return host, int(port)


def proxy_request(addr: str, method: str, path: str, body: bytes = b"",
                  headers: Optional[Dict[str, str]] = None,
                  timeout_s: float = 5.0) -> Tuple[int, str, bytes]:
    """Send one request to ``addr`` and return ``(status, content_type,
    body)``.  Any transport-level failure raises :class:`PeerUnreachable`
    — an HTTP error *status* from the peer is a successful proxy (the
    peer's structured error is the answer)."""
    host, port = split_addr(addr)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(method, path, body=body or None, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        ctype = resp.getheader("Content-Type") or "application/json"
        return resp.status, ctype, data
    except (OSError, http.client.HTTPException) as e:
        raise PeerUnreachable(
            f"peer {addr} unreachable: {type(e).__name__}: {e}") from e
    finally:
        conn.close()
