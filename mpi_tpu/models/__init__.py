"""Cellular-automaton model families (rule definitions)."""

from mpi_tpu.models.rules import (
    Rule,
    LIFE,
    HIGHLIFE,
    SEEDS,
    DAY_AND_NIGHT,
    BOSCO,
    rule_from_name,
)

__all__ = [
    "Rule",
    "LIFE",
    "HIGHLIFE",
    "SEEDS",
    "DAY_AND_NIGHT",
    "BOSCO",
    "rule_from_name",
]
