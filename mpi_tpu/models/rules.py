"""Totalistic cellular-automaton rules (outer-totalistic, Moore neighborhood).

The reference hardcodes Conway's B3/S23 in two places (``next()`` at
``/root/reference/main.cpp:79-90`` and the count/apply passes at
``/root/reference/main_serial.cpp:45-71``).  Here the rule is data: a pair of
neighbor-count sets (birth, survive) plus a neighborhood radius, which
generalizes to HighLife, Seeds, Day & Night, and Larger-than-Life-style
radius-r rules with one code path in every backend.

Convention: the neighbor count is over the *extended Moore neighborhood
excluding the center cell* — ``(2r+1)² − 1`` neighbors.  This matches the
reference's ``next()`` (8-neighbor sum, center excluded).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Tuple


def _intervals(counts: Iterable[int]) -> Tuple[Tuple[int, int], ...]:
    """Compress a set of ints into sorted, inclusive (lo, hi) intervals.

    Backends apply rules as OR-of-range-tests (vectorizes as comparisons —
    no gathers on the VPU), so contiguous runs are collapsed.
    """
    s = sorted(set(int(c) for c in counts))
    if not s:
        return ()
    out: List[Tuple[int, int]] = []
    lo = hi = s[0]
    for c in s[1:]:
        if c == hi + 1:
            hi = c
        else:
            out.append((lo, hi))
            lo = hi = c
    out.append((lo, hi))
    return tuple(out)


@dataclass(frozen=True)
class Rule:
    """An outer-totalistic rule: born on counts in `birth`, stays alive on
    counts in `survive`, over a radius-`radius` Moore neighborhood."""

    name: str
    birth: frozenset = field(default_factory=frozenset)
    survive: frozenset = field(default_factory=frozenset)
    radius: int = 1

    def __post_init__(self):
        object.__setattr__(self, "birth", frozenset(int(b) for b in self.birth))
        object.__setattr__(self, "survive", frozenset(int(s) for s in self.survive))
        nmax = self.max_count
        if self.radius < 1:
            raise ValueError(f"radius must be >= 1, got {self.radius}")
        if self.radius > 7:
            # Backends accumulate neighbor counts in uint8; r=7 gives a max
            # count of 224, r=8 would give 288 and wrap silently.
            raise ValueError(
                f"radius must be <= 7 (uint8 count accumulators), got {self.radius}"
            )
        for c in self.birth | self.survive:
            if not (0 <= c <= nmax):
                raise ValueError(
                    f"rule {self.name!r}: count {c} out of range [0, {nmax}] "
                    f"for radius {self.radius}"
                )

    @property
    def max_count(self) -> int:
        """Largest possible neighbor count: (2r+1)² − 1."""
        side = 2 * self.radius + 1
        return side * side - 1

    @property
    def birth_intervals(self) -> Tuple[Tuple[int, int], ...]:
        return _intervals(self.birth)

    @property
    def survive_intervals(self) -> Tuple[Tuple[int, int], ...]:
        return _intervals(self.survive)

    def tables(self):
        """(birth_table, survive_table) as length-(max_count+1) uint8 numpy
        arrays — the form the native C++ engine consumes."""
        import numpy as np

        n = self.max_count + 1
        bt = np.zeros(n, dtype=np.uint8)
        st = np.zeros(n, dtype=np.uint8)
        for c in self.birth:
            bt[c] = 1
        for c in self.survive:
            st[c] = 1
        return bt, st

    def __str__(self) -> str:
        b = "".join(str(c) for c in sorted(self.birth)) if self.radius == 1 else repr(sorted(self.birth))
        s = "".join(str(c) for c in sorted(self.survive)) if self.radius == 1 else repr(sorted(self.survive))
        return f"{self.name} (B{b}/S{s}, r={self.radius})"


# The classic rules (radius 1, 8 neighbors).
LIFE = Rule("life", frozenset({3}), frozenset({2, 3}))
HIGHLIFE = Rule("highlife", frozenset({3, 6}), frozenset({2, 3}))
SEEDS = Rule("seeds", frozenset({2}), frozenset())
DAY_AND_NIGHT = Rule("daynight", frozenset({3, 6, 7, 8}), frozenset({3, 4, 6, 7, 8}))

# Larger-than-Life: "Bosco's rule", radius 5 (120 neighbors, center excluded).
# Standard statement counts the center: born 34..45, survive 34..58 of 121.
# With the center excluded, survival of a live cell shifts down by one.
BOSCO = Rule("bosco", frozenset(range(34, 46)), frozenset(range(33, 58)), radius=5)

_REGISTRY = {r.name: r for r in (LIFE, HIGHLIFE, SEEDS, DAY_AND_NIGHT, BOSCO)}


def rule_from_name(name: str) -> Rule:
    """Look up a built-in rule, or parse a 'B3/S23' / 'B36/S23' style string
    (radius-1) or 'R5,B34-45,S33-57' Larger-than-Life style string."""
    key = name.lower()
    if key in _REGISTRY:
        return _REGISTRY[key]
    if key.startswith("b") and "/s" in key:
        bpart, spart = key[1:].split("/s", 1)
        return Rule(
            name,
            frozenset(int(ch) for ch in bpart if ch.isdigit()),
            frozenset(int(ch) for ch in spart if ch.isdigit()),
        )
    if key.startswith("r") and ",b" in key:
        try:
            rpart, bpart, spart = key.split(",")
            radius = int(rpart[1:])

            def parse_range(p: str) -> frozenset:
                p = p[1:]  # strip leading b/s
                out = set()
                for piece in p.split("+"):
                    if "-" in piece:
                        lo, hi = piece.split("-")
                        out.update(range(int(lo), int(hi) + 1))
                    elif piece:
                        out.add(int(piece))
                return frozenset(out)

            return Rule(name, parse_range(bpart), parse_range(spart), radius=radius)
        except (ValueError, IndexError) as e:
            raise ValueError(f"cannot parse rule string {name!r}") from e
    raise ValueError(
        f"unknown rule {name!r}; built-ins: {sorted(_REGISTRY)}; "
        "or use 'B3/S23' / 'R5,B34-45,S33-57' syntax"
    )
