"""Run configuration — the framework's equivalent of the reference's
``distrOpt`` POD struct (``/root/reference/main.cpp:16-27``) and its CLI
validation block (``main.cpp:171-199``).

Differences from the reference, by design (SURVEY.md §5.6):

* boundary condition is an explicit flag (the reference's serial program is
  periodic, its MPI program non-periodic — quirk #2);
* the rule is a parameter (reference hardcodes B3/S23);
* validation is relaxed where the reference's limits were incidental
  (non-square grids and non-square device counts are allowed as long as the
  mesh divides the grid), but every reference rule can be enforced with
  ``strict=True``;
* the seed feeds the decomposition-invariant hash init, not libc ``rand``.

There is no broadcast step: every process/host parses the same argv and
derives the identical config (the TPU-native answer to the reference's
``MPI_Bcast`` of a custom struct datatype, ``main.cpp:158-164,233``).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Optional, Tuple

from mpi_tpu.models.rules import Rule, LIFE, rule_from_name


class ConfigError(ValueError):
    """Invalid run configuration (the fail-fast analog of the reference's
    ``MPI_Abort`` on bad args, ``main.cpp:176,189,197``)."""


@dataclass(frozen=True)
class GolConfig:
    rows: int
    cols: int
    steps: int
    snapshot_every: int = 0          # 0 = no snapshots (reference: file_jump + save_file)
    seed: int = 0
    rule: Rule = LIFE
    boundary: str = "periodic"       # "periodic" | "dead"
    backend: str = "tpu"             # "tpu" | "serial" | "cpp" | "cpp-par"
    mesh_shape: Optional[Tuple[int, int]] = None  # device mesh (rows_axis, cols_axis); None = auto
    program_name: str = ""           # master .gol name; "" = timestamp at run time
    out_dir: str = "."
    workers: int = 0                 # native backend threads; 0 = auto

    def __post_init__(self):
        if self.rows <= 0 or self.cols <= 0:
            raise ConfigError(f"grid size must be positive, got {self.rows}x{self.cols}")
        if self.steps < 0:
            raise ConfigError(f"steps must be >= 0, got {self.steps}")
        if self.snapshot_every < 0:
            raise ConfigError(f"snapshot_every must be >= 0, got {self.snapshot_every}")
        if self.boundary not in ("periodic", "dead"):
            raise ConfigError(f"boundary must be 'periodic' or 'dead', got {self.boundary!r}")
        if self.backend not in ("tpu", "serial", "cpp", "cpp-par"):
            raise ConfigError(
                f"backend must be one of tpu/serial/cpp/cpp-par, got {self.backend!r}"
            )
        if self.mesh_shape is not None:
            mi, mj = self.mesh_shape
            if mi < 1 or mj < 1:
                raise ConfigError(f"mesh_shape must be positive, got {self.mesh_shape}")
            if self.rows % mi or self.cols % mj:
                raise ConfigError(
                    f"mesh {self.mesh_shape} does not divide grid {self.rows}x{self.cols}"
                )
            tile_r, tile_c = self.rows // mi, self.cols // mj
            min_tile = 2 * self.rule.radius + 2
            if (mi > 1 and tile_r < min_tile) or (mj > 1 and tile_c < min_tile):
                raise ConfigError(
                    f"tile {tile_r}x{tile_c} too small for radius {self.rule.radius} "
                    f"halo (need >= {min_tile} per sharded axis)"
                )

    def validate_strict(self) -> None:
        """Enforce the reference's exact preconditions (``main.cpp:195``):
        square grid, square mesh, divisibility, tile >= 4 cells/side."""
        if self.rows != self.cols:
            raise ConfigError("strict mode: grid must be square")
        if self.mesh_shape is not None:
            mi, mj = self.mesh_shape
            p = mi * mj
            z = math.isqrt(p)
            if z * z != p or mi != mj:
                raise ConfigError("strict mode: device count must be a perfect square mesh")
            if self.rows % mi:
                raise ConfigError("strict mode: mesh must divide rows")
            if self.rows // mi < 4:
                raise ConfigError("strict mode: tile must be >= 4 cells per side")

    def with_(self, **kw) -> "GolConfig":
        return dataclasses.replace(self, **kw)

    @property
    def cells(self) -> int:
        return self.rows * self.cols

    @staticmethod
    def from_cli_args(
        rows: int,
        cols: int,
        iteration_gap: int,
        iterations: int,
        *,
        rule: str = "life",
        **kw,
    ) -> "GolConfig":
        """Build from the reference's positional contract
        ``rows cols iteration_gap iterations`` (``main.cpp:171-199``)."""
        return GolConfig(
            rows=rows,
            cols=cols,
            steps=iterations,
            snapshot_every=iteration_gap,
            rule=rule_from_name(rule) if isinstance(rule, str) else rule,
            **kw,
        )
