"""Run configuration — the framework's equivalent of the reference's
``distrOpt`` POD struct (``/root/reference/main.cpp:16-27``) and its CLI
validation block (``main.cpp:171-199``).

Differences from the reference, by design (SURVEY.md §5.6):

* boundary condition is an explicit flag (the reference's serial program is
  periodic, its MPI program non-periodic — quirk #2);
* the rule is a parameter (reference hardcodes B3/S23);
* validation is relaxed where the reference's limits were incidental
  (non-square grids and non-square device counts are allowed as long as the
  mesh divides the grid), but every reference rule can be enforced with
  ``strict=True``;
* the seed feeds the decomposition-invariant hash init, not libc ``rand``.

There is no broadcast step: every process/host parses the same argv and
derives the identical config (the TPU-native answer to the reference's
``MPI_Bcast`` of a custom struct datatype, ``main.cpp:158-164,233``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Tuple

from mpi_tpu.models.rules import Rule, LIFE


class ConfigError(ValueError):
    """Invalid run configuration (the fail-fast analog of the reference's
    ``MPI_Abort`` on bad args, ``main.cpp:176,189,197``)."""


def validate_mesh(rows: int, cols: int, mesh_shape: Tuple[int, int], ghost: int) -> None:
    """Grid/mesh compatibility: divisibility and minimum tile size for a
    ``ghost``-deep halo (= rule radius × comm_every).  Called both for
    explicit ``--mesh`` shapes and for auto-chosen device meshes (the TPU
    runner validates after choosing), so every path fails fast with a named
    error instead of a deep shard_map trace error."""
    mi, mj = mesh_shape
    if mi < 1 or mj < 1:
        raise ConfigError(f"mesh_shape must be positive, got {mesh_shape}")
    if rows % mi or cols % mj:
        raise ConfigError(f"mesh {mesh_shape} does not divide grid {rows}x{cols}")
    tile_r, tile_c = rows // mi, cols // mj
    min_tile = 2 * ghost + 2
    hint = "rule radius x comm_every"
    if (mi > 1 and tile_r < min_tile) or (mj > 1 and tile_c < min_tile):
        raise ConfigError(
            f"tile {tile_r}x{tile_c} too small for a {ghost}-deep halo "
            f"({hint}; need >= {min_tile} per sharded axis)"
        )
    if tile_r < ghost or tile_c < ghost:
        # even a 1-shard axis slices a ghost-deep ring off the tile
        # (self-wrap / zero fill) — a smaller tile would silently truncate
        raise ConfigError(
            f"tile {tile_r}x{tile_c} smaller than the {ghost}-deep ghost "
            f"ring ({hint})"
        )


@dataclass(frozen=True)
class GolConfig:
    rows: int
    cols: int
    steps: int
    snapshot_every: int = 0          # 0 = no snapshots (reference: file_jump + save_file)
    seed: int = 0
    rule: Rule = LIFE
    boundary: str = "periodic"       # "periodic" | "dead"
    backend: str = "tpu"             # "tpu" | "serial" | "cpp" | "cpp-par"
    mesh_shape: Optional[Tuple[int, int]] = None  # device mesh (rows_axis, cols_axis); None = auto
    out_dir: str = "."
    workers: int = 0                 # native backend threads; 0 = auto
    comm_every: int = 1              # TPU: generations per halo exchange (1..16)
    overlap: bool = False            # TPU backend (packed or dense, either boundary): overlap ppermute with interior compute
    sparse_tile: int = 0             # TPU: activity-gated stepping tile size in cells; 0 = dense (ops/activity.py)

    def __post_init__(self):
        if self.rows <= 0 or self.cols <= 0:
            raise ConfigError(f"grid size must be positive, got {self.rows}x{self.cols}")
        if self.steps < 0:
            raise ConfigError(f"steps must be >= 0, got {self.steps}")
        if self.snapshot_every < 0:
            raise ConfigError(f"snapshot_every must be >= 0, got {self.snapshot_every}")
        if self.boundary not in ("periodic", "dead"):
            raise ConfigError(f"boundary must be 'periodic' or 'dead', got {self.boundary!r}")
        if self.backend not in ("tpu", "serial", "cpp", "cpp-par"):
            raise ConfigError(
                f"backend must be one of tpu/serial/cpp/cpp-par, got {self.backend!r}"
            )
        if not 1 <= self.comm_every <= 16:
            raise ConfigError(f"comm_every must be in 1..16, got {self.comm_every}")
        if self.comm_every > 1 and self.backend != "tpu":
            raise ConfigError(
                f"comm_every applies to the tpu backend only "
                f"(got backend={self.backend!r})"
            )
        if self.comm_every > 1 and 0 in self.rule.birth:
            raise ConfigError("comm_every > 1 requires a rule without birth-on-0")
        if self.overlap and self.backend != "tpu":
            raise ConfigError("overlap applies to the tpu backend only")
        if self.sparse_tile < 0:
            raise ConfigError(f"sparse_tile must be >= 0, got {self.sparse_tile}")
        if self.sparse_tile:
            if self.backend != "tpu":
                raise ConfigError("sparse_tile applies to the tpu backend only")
            if self.comm_every != 1:
                raise ConfigError(
                    "sparse_tile requires comm_every=1 (the dirty map is "
                    "maintained per generation)")
            if self.overlap:
                raise ConfigError("sparse_tile and overlap are exclusive")
            if self.rows % self.sparse_tile or self.cols % self.sparse_tile:
                raise ConfigError(
                    f"sparse_tile {self.sparse_tile} must divide the grid "
                    f"({self.rows}x{self.cols})")
            if self.sparse_tile < self.rule.radius:
                raise ConfigError(
                    f"sparse_tile {self.sparse_tile} smaller than the rule "
                    f"radius {self.rule.radius} (one-ring dilation would "
                    f"miss changes)")
        if self.mesh_shape is not None:
            if self.backend != "tpu":
                # other backends would silently ignore it (cpp-par
                # decomposes via --workers) — fail fast instead
                raise ConfigError(
                    f"mesh_shape applies to the tpu backend only "
                    f"(got backend={self.backend!r})"
                )
            validate_mesh(
                self.rows, self.cols, self.mesh_shape,
                self.rule.radius * self.comm_every,
            )

    def validate_strict(self, effective_mesh: Optional[Tuple[int, int]] = None) -> None:
        """Enforce the reference's exact preconditions (``main.cpp:195``):
        square grid, square mesh, divisibility, tile >= 4 cells/side.

        ``effective_mesh`` is the decomposition the run will actually use
        (the auto-chosen device mesh, or the cpp-par tile plan) — strict
        mode must judge what runs, not just what was typed (an auto 2x4
        mesh is not a perfect square even though no ``--mesh`` flag was
        given), so when provided it wins over ``mesh_shape``."""
        if self.rows != self.cols:
            raise ConfigError("strict mode: grid must be square")
        mesh = effective_mesh if effective_mesh is not None else self.mesh_shape
        if mesh is not None:
            mi, mj = mesh
            p = mi * mj
            z = math.isqrt(p)
            if z * z != p or mi != mj:
                raise ConfigError(
                    f"strict mode: device count must be a perfect square mesh "
                    f"(effective mesh {mi}x{mj})"
                )
            if self.rows % mi:
                raise ConfigError("strict mode: mesh must divide rows")
            if self.rows // mi < 4:
                raise ConfigError("strict mode: tile must be >= 4 cells per side")

    @property
    def cells(self) -> int:
        return self.rows * self.cols


def plan_signature(config: GolConfig, mesh_shape: Tuple[int, int],
                   segments=()) -> tuple:
    """Hashable key of everything compilation depends on — the EngineCache
    key (``mpi_tpu.serve``).  Two configs with equal signatures can share
    one compiled :class:`~mpi_tpu.backends.tpu.Engine`.

    Deliberately EXCLUDES ``steps``, ``snapshot_every``, ``seed``,
    ``out_dir`` and ``workers``: none of them reach the stepper's traced
    program (seed only picks the initial grid; the step plan only picks
    which segment lengths get compiled, and those are carried separately
    as the sorted distinct ``segments`` set).  ``mesh_shape`` is the
    RESOLVED shape (auto-chosen meshes must not alias an explicit one of
    a different shape), and ``Rule`` is a frozen dataclass of frozensets,
    so the whole tuple hashes.

    The IR verifier (``python -m mpi_tpu.analysis.ir``) gates this key
    in BOTH directions over its config matrix: equal signatures must
    trace to identical canonical jaxprs, and matrix near-pairs differing
    in one signature-visible field must get distinct signatures.  Adding
    a config field that reaches the traced program means adding it here
    AND to ``SIGNATURE_FIELDS`` AND regenerating the IR baseline — see
    MIGRATION.md."""
    return (
        config.rows, config.cols, config.rule, config.boundary,
        config.backend, tuple(mesh_shape), config.comm_every,
        bool(config.overlap), tuple(sorted(set(segments))),
        config.sparse_tile,
    )


# what each position of the plan_signature tuple holds, in order — a
# documented arity contract so the IR verifier's tests fail loudly when
# someone extends the signature without updating the field list (or vice
# versa) instead of silently shifting positions
SIGNATURE_FIELDS = (
    "rows", "cols", "rule", "boundary", "backend", "mesh_shape",
    "comm_every", "overlap", "segments", "sparse_tile",
)


# Config fields the autotuner (mpi_tpu/tune/) may override when applying
# a cached winner.  Deliberately narrow: every field here re-validates
# through GolConfig's __post_init__ on application, and none of them
# changes the *semantics* of the run (comm_every and sparse_tile are
# bit-identical execution strategies; the parity bless in the tuner
# holds them to that).  Plan entries may additionally carry the
# non-config knobs in PLAN_ONLY_KEYS (kernel block shape, serving batch
# hint) which never reach GolConfig.
TUNABLE_FIELDS = ("comm_every", "sparse_tile")
PLAN_ONLY_KEYS = ("blocks", "batch")


def apply_plan(config: GolConfig, plan: dict) -> GolConfig:
    """``config`` with a tune-cache plan's overrides applied.

    Unknown keys raise :class:`ConfigError` (a cache written by a newer
    tuner must fail loudly, not half-apply); the replaced config re-runs
    full validation, so a stale plan that no longer satisfies current
    rules raises too — callers on the serving path catch and fall back
    to the untuned plan, ``python -m mpi_tpu.tune --check`` reports it."""
    import dataclasses

    bad = [k for k in plan if k not in TUNABLE_FIELDS + PLAN_ONLY_KEYS]
    if bad:
        raise ConfigError(
            f"tune plan carries unknown override(s) {sorted(bad)} "
            f"(tunable: {list(TUNABLE_FIELDS)}, "
            f"plan-only: {list(PLAN_ONLY_KEYS)})")
    overrides = {k: plan[k] for k in TUNABLE_FIELDS if k in plan}
    if not overrides:
        return config
    return dataclasses.replace(config, **overrides)


def plan_segments(steps: int, snapshot_every: int) -> List[int]:
    """Split `steps` into evolution-segment lengths between snapshot points
    (shared by every backend so their snapshot series always align)."""
    if snapshot_every <= 0 or snapshot_every >= steps:
        return [steps] if steps else []
    full, rem = divmod(steps, snapshot_every)
    return [snapshot_every] * full + ([rem] if rem else [])
