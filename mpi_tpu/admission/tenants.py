"""Tenant registry: who may spend what (ISSUE 16).

Tenants are declared in a JSON file (``--tenants-file``) or fall back to
a single unlimited ``default`` tenant.  Quotas are metered in **ledger
currency** — device-seconds and cells over a sliding window — plus a cap
on concurrent sessions; they are *not* raw request counts, so a 65536²
step and a 64² step debit what they actually cost.

The file shape mirrors the SLO file (a bare list, or an object with a
``tenants`` key), and validation follows ``slo.normalize_objectives``'s
discipline exactly: every error is a ``ConfigError`` naming the
offending tenant and key, unknown keys are rejected, and duplicates are
refused.  A registry always contains the ``default`` tenant — requests
without an ``X-Gol-Tenant`` header land there, and when the file does
not declare it, an unlimited entry is appended so header-less traffic
behaves exactly as before this subsystem existed.

Tenant spec fields (all but ``name`` optional):

- ``device_s_per_window``: float > 0, device-seconds the tenant may
  settle per window (``null``/absent = unlimited)
- ``cells_per_window``: int > 0, cell-updates per window (unlimited
  when absent)
- ``window_s``: float > 0, the sliding-window length (default 60s)
- ``max_sessions``: int >= 1, concurrent live sessions (unlimited
  when absent)
- ``default_class``: priority class for requests with no override
  (default ``standard``)
- ``max_class``: the highest class the tenant may request; overrides
  above it are capped, not rejected (default ``interactive``)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from mpi_tpu.config import ConfigError
from mpi_tpu.admission.sched import CLASSES, CLASS_RANK, DEFAULT_CLASS, \
    clamp_class

DEFAULT_TENANT = "default"

_TENANT_KEYS = {"name", "device_s_per_window", "cells_per_window",
                "window_s", "max_sessions", "default_class", "max_class"}


def _normalize_tenant(obj: dict, seen: set) -> dict:
    if not isinstance(obj, dict):
        raise ConfigError(f"tenant entry must be an object, got {obj!r}")
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        raise ConfigError(f"tenant needs a non-empty string name, "
                          f"got {name!r}")
    if name in seen:
        raise ConfigError(f"duplicate tenant name {name!r}")
    seen.add(name)
    unknown = set(obj) - _TENANT_KEYS
    if unknown:
        raise ConfigError(f"{name}: unknown keys {sorted(unknown)}")

    device_s = obj.get("device_s_per_window")
    if device_s is not None:
        if not isinstance(device_s, (int, float)) \
                or isinstance(device_s, bool) or device_s <= 0:
            raise ConfigError(f"{name}: device_s_per_window must be a "
                              f"positive number, got {device_s!r}")
        device_s = float(device_s)
    cells = obj.get("cells_per_window")
    if cells is not None:
        if not isinstance(cells, int) or isinstance(cells, bool) \
                or cells <= 0:
            raise ConfigError(f"{name}: cells_per_window must be a "
                              f"positive int, got {cells!r}")
    window_s = obj.get("window_s", 60.0)
    if not isinstance(window_s, (int, float)) or isinstance(window_s, bool) \
            or window_s <= 0:
        raise ConfigError(f"{name}: window_s must be a positive number, "
                          f"got {window_s!r}")
    max_sessions = obj.get("max_sessions")
    if max_sessions is not None:
        if not isinstance(max_sessions, int) or isinstance(max_sessions, bool) \
                or max_sessions < 1:
            raise ConfigError(f"{name}: max_sessions must be an int >= 1, "
                              f"got {max_sessions!r}")
    default_class = obj.get("default_class", DEFAULT_CLASS)
    if default_class not in CLASSES:
        raise ConfigError(f"{name}: default_class must be one of "
                          f"{list(CLASSES)}, got {default_class!r}")
    max_class = obj.get("max_class", CLASSES[0])
    if max_class not in CLASSES:
        raise ConfigError(f"{name}: max_class must be one of "
                          f"{list(CLASSES)}, got {max_class!r}")
    if CLASS_RANK[default_class] < CLASS_RANK[max_class]:
        raise ConfigError(f"{name}: default_class {default_class!r} outranks "
                          f"max_class {max_class!r}")
    return {
        "name": name,
        "device_s_per_window": device_s,
        "cells_per_window": cells,
        "window_s": float(window_s),
        "max_sessions": max_sessions,
        "default_class": default_class,
        "max_class": max_class,
    }


def normalize_tenants(raw) -> Dict[str, dict]:
    """Validate a tenants document (bare list or ``{"tenants": [...]}``)
    into ``{name: spec}``, guaranteeing the default tenant exists."""
    if isinstance(raw, dict):
        unknown = set(raw) - {"tenants"}
        if unknown:
            raise ConfigError(f"unknown top-level keys {sorted(unknown)}")
        raw = raw.get("tenants")
    if not isinstance(raw, list) or not raw:
        raise ConfigError("tenants file needs a non-empty list of tenants "
                          "(bare or under a 'tenants' key)")
    seen: set = set()
    specs = [_normalize_tenant(obj, seen) for obj in raw]
    if DEFAULT_TENANT not in seen:
        specs.append(_normalize_tenant({"name": DEFAULT_TENANT}, seen))
    return {spec["name"]: spec for spec in specs}


def default_tenants() -> Dict[str, dict]:
    """The registry used when no ``--tenants-file`` is given: one
    unlimited default tenant (admission armed, nothing constrained)."""
    return normalize_tenants([{"name": DEFAULT_TENANT}])


def load_tenants_file(path: str) -> Dict[str, dict]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw = json.load(fh)
    except OSError as e:
        raise ConfigError(f"cannot read tenants file {path!r}: {e}") from e
    except ValueError as e:
        raise ConfigError(f"tenants file {path!r} is not JSON: {e}") from e
    return normalize_tenants(raw)


class TenantRegistry:
    """Immutable view over the normalized tenant specs."""

    def __init__(self, specs: Dict[str, dict]):
        if DEFAULT_TENANT not in specs:
            raise ConfigError(f"registry needs the {DEFAULT_TENANT!r} tenant")
        self._specs = dict(specs)

    def names(self) -> List[str]:
        return sorted(self._specs)

    def get(self, name: str) -> dict:
        return self._specs[name]

    def resolve(self, header: Optional[str]) -> str:
        """Header value -> tenant name.  No header means the default
        tenant; an unknown tenant is a client error (400)."""
        if header is None or header == "":
            return DEFAULT_TENANT
        if header not in self._specs:
            raise ConfigError(f"unknown tenant {header!r}")
        return header

    def resolve_class(self, tenant: str, requested: Optional[str]) -> str:
        """The class a request gets: the tenant default when nothing was
        asked, otherwise the ask capped at the tenant's ceiling.  An
        unknown class name is a client error."""
        spec = self._specs[tenant]
        if requested is None or requested == "":
            return spec["default_class"]
        if requested not in CLASSES:
            raise ConfigError(f"unknown priority class {requested!r} "
                              f"(one of {list(CLASSES)})")
        return clamp_class(requested, spec["max_class"])
