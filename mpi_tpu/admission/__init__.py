"""Multi-tenant admission control (ISSUE 16): the control plane that
turns PR-10's usage ledger and PR-15's SLO engine from observers into
actuators.

``AdmissionControl`` is the coordinator the serving stack talks to.  It
owns the :class:`TenantRegistry` (who exists, what they may spend), the
:class:`QuotaGate` (sliding-window spend books in ledger currency,
settled by a post-dispatch ledger hook), the
:class:`WeightedClassPicker` (cost-aware class scheduling inside the
async dispatcher), and the :class:`LoadShedder` (SLO-driven 429s,
lowest class first).

Everything here is **default-off**: an unarmed server has
``manager.admission is None``, registers none of the admission metric
families, adds no trace events, and serves byte-identical ids,
payloads, and scrape text (the PR-12/PR-15 bit-identity discipline —
pinned by ``tests/test_admission.py`` and ``tools/obs_smoke.py``).

Decision flow for one step request, armed:

1. transport resolves tenant (``X-Gol-Tenant`` header, default tenant
   when absent) and class (``X-Gol-Class``, capped at the tenant
   ceiling) and calls ``manager.admission_check``;
2. the shedder answers first (a critical SLO drops low classes before
   quota math runs), then the quota gate charges the CostCard
   *estimate* against the window's *settled* spend — cluster-wide when
   gossiping;
3. a rejection raises :class:`AdmissionReject` before any device work:
   no ``device_dispatch`` span, no ledger debit, a 429 with Retry-After
   and an ``admission_reject`` trace event;
4. on dispatch the ledger settlement hook charges what the step
   actually cost, so estimates never drift the books.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from mpi_tpu.admission.quota import AdmissionReject, QuotaExceeded, \
    QuotaGate, retry_after_header
from mpi_tpu.admission.sched import CLASSES, DEFAULT_CLASS, \
    WeightedClassPicker
from mpi_tpu.admission.shed import LoadShedder, ShedRejected
from mpi_tpu.admission.tenants import DEFAULT_TENANT, TenantRegistry, \
    default_tenants, load_tenants_file, normalize_tenants
from mpi_tpu.obs.cost import ops_per_cell_estimate, roof_ops_per_s

__all__ = [
    "AdmissionControl", "AdmissionReject", "QuotaExceeded", "ShedRejected",
    "TenantRegistry", "QuotaGate", "LoadShedder", "WeightedClassPicker",
    "CLASSES", "DEFAULT_CLASS", "DEFAULT_TENANT",
    "default_tenants", "load_tenants_file", "normalize_tenants",
    "retry_after_header",
]


class AdmissionControl:
    """Tenancy + quota + scheduling + shedding, armed as one unit."""

    def __init__(self, specs: Optional[Dict[str, dict]] = None, *,
                 damp_evals: int = 3, shed_max_level: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = TenantRegistry(specs or default_tenants())
        self.gate = QuotaGate(self.registry, clock=clock)
        self.shedder = LoadShedder(damp_evals=damp_evals,
                                   max_level=shed_max_level)
        self.picker = WeightedClassPicker()
        self.obs = None
        self.manager = None
        self._lock = threading.Lock()
        # (tenant, decision) -> count; decision in admit|quota|shed
        self._decisions: Dict[Tuple[str, str], int] = {}
        # (tenant, class) -> admitted-step count (usage_top's class mix)
        self._class_mix: Dict[Tuple[str, str], int] = {}

    # -- resolution --------------------------------------------------

    def resolve(self, tenant_header: Optional[str]) -> str:
        return self.registry.resolve(tenant_header)

    def resolve_class(self, tenant: str, requested: Optional[str]) -> str:
        return self.registry.resolve_class(tenant, requested)

    # -- estimates ---------------------------------------------------

    def estimate_ops(self, session, steps: int) -> float:
        """Pre-dispatch cost estimate in device ops: CostCard
        ``ops_per_cell x cells`` over the whole request.  Zero until the
        engine has a card (first step of a fresh signature) — an unknown
        cost admits rather than guessing."""
        engine = getattr(session, "engine", None)
        if engine is None:
            return 0.0
        cells = session.config.cells
        try:
            per_cell = ops_per_cell_estimate(engine.cost_cards(), cells)
        except Exception:  # noqa: BLE001 — estimation must never reject
            return 0.0
        if not per_cell:
            return 0.0
        return per_cell * cells * int(steps)

    def estimate(self, session, steps: int) -> Tuple[float, int]:
        """(device-seconds, cells) the request is expected to cost."""
        est_cells = int(steps) * session.config.cells
        est_device_s = self.estimate_ops(session, steps) / roof_ops_per_s()
        return est_device_s, est_cells

    # -- decisions ---------------------------------------------------

    def _count(self, tenant: str, decision: str) -> None:
        with self._lock:
            k = (tenant, decision)
            self._decisions[k] = self._decisions.get(k, 0) + 1

    def _reject_event(self, exc: AdmissionReject, decision: str,
                      qos: Optional[str]) -> None:
        if self.obs is not None:
            fields = {"tenant": exc.tenant, "decision": decision,
                      "retry_after_s": exc.retry_after_s}
            if qos is not None:
                fields["qos"] = qos
            self.obs.event("admission_reject", **fields)

    def admit_step(self, tenant: str, qos: str, est_device_s: float,
                   est_cells: int) -> None:
        """Gate one step request: shed ladder first (a critical SLO
        answers before quota math), then window quota.  Raises
        :class:`AdmissionReject`; counts every decision."""
        try:
            self.shedder.check(tenant, qos)
        except ShedRejected as e:
            self._count(tenant, "shed")
            self._reject_event(e, "shed", qos)
            raise
        try:
            self.gate.admit(tenant, est_device_s, est_cells)
        except QuotaExceeded as e:
            self._count(tenant, "quota")
            self._reject_event(e, "quota", qos)
            raise
        self._count(tenant, "admit")
        with self._lock:
            k = (tenant, qos)
            self._class_mix[k] = self._class_mix.get(k, 0) + 1

    def admit_session(self, tenant: str) -> None:
        """Gate a session create against the tenant's concurrency cap."""
        try:
            self.gate.admit_session(tenant)
        except QuotaExceeded as e:
            self._count(tenant, "quota")
            self._reject_event(e, "quota", None)
            raise
        self._count(tenant, "admit")

    # -- settlement (the post-dispatch ledger hook) -------------------

    def settle(self, kind: str, dur_s: float, riders) -> None:
        """Charge what a dispatch actually cost.  Mirrors the ledger's
        split: duration is shared evenly across riders; cells are each
        rider's own.  ``host`` work settles cells but not device time
        (the quota currency is device-seconds)."""
        if not riders:
            return
        share = dur_s / len(riders) if kind != "host" else 0.0
        for rider in riders:
            sid, _gens, cells = rider[0], rider[1], rider[2]
            tenant = self.gate.tenant_of(sid)
            if tenant is not None:
                self.gate.charge(tenant, share, cells)

    # -- arming ------------------------------------------------------

    def arm(self, manager, obs=None) -> None:
        """Wire into a live stack: install the ledger settlement hook,
        subscribe the shedder to SLO evaluations (when telemetry is
        armed), register the admission metric families, and hand the
        manager its admission handle."""
        self.manager = manager
        manager.admission = self
        self.obs = obs if obs is not None else getattr(manager, "obs", None)
        if self.obs is not None:
            self.obs.ledger.settle_hook = self.settle
            if self.obs.slo is not None:
                self.obs.slo.add_listener(
                    lambda worst: self.shedder.evaluate(worst))
            self.bind_metrics(self.obs.metrics)

    def attach_cluster(self, node) -> None:
        """Quotas become cluster-wide: admit against local + gossiped
        peer window spend (exact sums — latest snapshot per node)."""
        self.gate.remote_spend = node.tenant_spend

    def window_snapshot(self) -> Dict[str, dict]:
        return self.gate.window_snapshot()

    # -- read-outs ---------------------------------------------------

    def bind_metrics(self, m) -> None:
        """The four admission families, registered only when armed (the
        obsreg drift gate exempts this module from the unarmed-required
        set, like the SLO and cluster families)."""
        m.counter_fn(
            "mpi_tpu_admission_decisions_total",
            "Admission decisions by tenant and decision "
            "(admit|quota|shed)",
            self._decisions_read)
        m.gauge_fn(
            "mpi_tpu_shed_level",
            "Load-shed ladder level (0 none, 1 sheds bulk, 2 +standard, "
            "3 +interactive)",
            lambda: self.shedder.level)
        m.gauge_fn(
            "mpi_tpu_quota_remaining",
            "Device-seconds left in each tenant's sliding window "
            "(-1 = unlimited)",
            self._remaining_read)
        m.gauge_fn(
            "mpi_tpu_admission_queue_depth",
            "Queued async tickets by priority class",
            self._depth_read)

    def _decisions_read(self):
        with self._lock:
            items = sorted(self._decisions.items())
        return [({"tenant": t, "decision": d}, v) for (t, d), v in items]

    def _remaining_read(self):
        out = []
        for name in self.registry.names():
            limit = self.registry.get(name)["device_s_per_window"]
            if limit is None:
                out.append(({"tenant": name}, -1.0))
            else:
                spent, _ = self.gate.spent(name)
                out.append(({"tenant": name}, max(0.0, limit - spent)))
        return out

    def _depth_read(self):
        mgr = self.manager
        dispatcher = getattr(mgr, "dispatcher", None) if mgr else None
        depths = dispatcher.depth_by_class() if dispatcher is not None else {}
        return [({"class": c}, depths.get(c, 0)) for c in CLASSES]

    def tenants_block(self) -> dict:
        """The ``GET /usage`` ``tenants`` block: the shed level plus,
        per tenant, spend vs quota, live sessions, class mix, and
        decision counts."""
        with self._lock:
            decisions = dict(self._decisions)
            mix = dict(self._class_mix)
        by_tenant: Dict[str, dict] = {}
        for name in self.registry.names():
            spec = self.registry.get(name)
            device_s, cells = self.gate.spent(name)
            by_tenant[name] = {
                "window_s": spec["window_s"],
                "device_s": device_s,
                "device_s_per_window": spec["device_s_per_window"],
                "cells": cells,
                "cells_per_window": spec["cells_per_window"],
                "sessions": self.gate.sessions_of(name),
                "max_sessions": spec["max_sessions"],
                "default_class": spec["default_class"],
                "max_class": spec["max_class"],
                "class_mix": {c: mix.get((name, c), 0) for c in CLASSES
                              if mix.get((name, c))},
                "decisions": {d: decisions.get((name, d), 0)
                              for d in ("admit", "quota", "shed")
                              if decisions.get((name, d))},
            }
        return {"shed_level": self.shedder.level, "by_tenant": by_tenant}
