"""Sliding-window quota accounting in ledger currency (ISSUE 16).

The ``QuotaGate`` answers one question — *may this tenant spend an
estimated (device-seconds, cells) right now?* — against what the tenant
actually settled over its sliding window.  CostCards provide the
pre-dispatch estimate; the ``UsageLedger`` settlement hook provides the
charge (so the window holds real spend, not guesses).  Rejections carry
a Retry-After computed from the window itself: the instant at which
enough settled charges age out for the estimate to fit.

Cluster mode: each node gossips its local window snapshot (exact sums,
the ``merge_totals`` discipline — latest snapshot per node, never
deltas), and ``admit`` charges the estimate against *cluster-wide*
spend by adding a remote-spend callable the cluster node installs.

Thread-safety: one lock around the books.  ``charge`` runs on dispatch
threads (via the ledger hook), ``admit`` on request threads, and
``window_snapshot`` on the gossip thread.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple


class AdmissionReject(RuntimeError):
    """Base for every admission-control rejection; maps to HTTP 429
    with a Retry-After header sized by ``retry_after_s``."""

    def __init__(self, msg: str, *, tenant: str, retry_after_s: float):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)


class QuotaExceeded(AdmissionReject):
    """Tenant is over a window quota or its concurrent-session cap."""


def retry_after_header(retry_after_s: float) -> Tuple[str, str]:
    """The Retry-After header every backpressure rejection carries:
    integral seconds, never below 1."""
    return ("Retry-After", str(max(1, math.ceil(retry_after_s))))


class QuotaGate:
    """Per-tenant sliding-window spend books plus live-session counts."""

    def __init__(self, registry, clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.clock = clock
        self._lock = threading.Lock()
        # tenant -> deque of (t, device_s, cells) settled charges, plus
        # running window totals so ``spent`` (the per-request hot path)
        # is O(1) — the deque is only walked on rejection (Retry-After)
        self._events: Dict[str, deque] = {}
        self._totals: Dict[str, list] = {}      # tenant -> [device_s, cells]
        # sid -> tenant, for session caps and settlement attribution
        self._sid_tenant: Dict[str, str] = {}
        # cluster hook: tenant -> (device_s, cells, sessions) across peers
        self.remote_spend: Optional[Callable[[str], Tuple[float, int, int]]] \
            = None

    # -- attribution -------------------------------------------------

    def note_session(self, sid: str, tenant: str) -> None:
        with self._lock:
            self._sid_tenant[sid] = tenant

    def drop_session(self, sid: str) -> None:
        with self._lock:
            self._sid_tenant.pop(sid, None)

    def tenant_of(self, sid: str) -> Optional[str]:
        with self._lock:
            return self._sid_tenant.get(sid)

    # -- settlement --------------------------------------------------

    def charge(self, tenant: str, device_s: float, cells: int,
               now: Optional[float] = None) -> None:
        """Record a settled charge (from the ledger hook, post-dispatch)."""
        now = self.clock() if now is None else now
        with self._lock:
            q = self._events.setdefault(tenant, deque())
            q.append((now, float(device_s), int(cells)))
            tot = self._totals.setdefault(tenant, [0.0, 0])
            tot[0] += float(device_s)
            tot[1] += int(cells)
            self._prune(tenant, now)

    def _prune(self, tenant: str, now: float) -> None:
        window_s = self.registry.get(tenant)["window_s"]
        q = self._events.get(tenant)
        tot = self._totals.get(tenant)
        while q and q[0][0] <= now - window_s:
            _, d, c = q.popleft()
            tot[0] -= d
            tot[1] -= c
        if q is not None and not q and tot is not None:
            # empty window: snap the running floats back to exact zero
            # so decrement drift can never accumulate across windows
            tot[0], tot[1] = 0.0, 0

    def spent(self, tenant: str, now: Optional[float] = None) \
            -> Tuple[float, int]:
        """This node's settled (device_s, cells) inside the window."""
        now = self.clock() if now is None else now
        with self._lock:
            self._prune(tenant, now)
            tot = self._totals.get(tenant)
            return (0.0, 0) if tot is None else (tot[0], tot[1])

    def sessions_of(self, tenant: str) -> int:
        with self._lock:
            return sum(1 for t in self._sid_tenant.values() if t == tenant)

    # -- admission ---------------------------------------------------

    def admit(self, tenant: str, est_device_s: float, est_cells: int,
              now: Optional[float] = None) -> None:
        """Raise ``QuotaExceeded`` when the estimate does not fit the
        tenant's remaining window budget (cluster-wide when gossiping).
        Admission happens at enqueue, never after device work."""
        spec = self.registry.get(tenant)
        limit_s = spec["device_s_per_window"]
        limit_cells = spec["cells_per_window"]
        if limit_s is None and limit_cells is None:
            return
        now = self.clock() if now is None else now
        device_s, cells = self.spent(tenant, now)
        rem_device_s, rem_cells, _ = self._remote(tenant)
        device_s += rem_device_s
        cells += rem_cells
        if limit_s is not None and device_s + est_device_s > limit_s:
            raise QuotaExceeded(
                f"tenant {tenant!r} over device-seconds quota "
                f"({device_s:.3f}s spent + {est_device_s:.3f}s estimated "
                f"> {limit_s:.3f}s per {spec['window_s']:.0f}s window)",
                tenant=tenant,
                retry_after_s=self._retry_after(
                    tenant, now, need_device_s=device_s + est_device_s
                    - limit_s))
        if limit_cells is not None and cells + est_cells > limit_cells:
            raise QuotaExceeded(
                f"tenant {tenant!r} over cells quota "
                f"({cells} spent + {est_cells} estimated > {limit_cells} "
                f"per {spec['window_s']:.0f}s window)",
                tenant=tenant,
                retry_after_s=self._retry_after(
                    tenant, now, need_cells=cells + est_cells - limit_cells))

    def admit_session(self, tenant: str) -> None:
        """Raise when one more live session would break the cap
        (cluster-wide when gossiping)."""
        spec = self.registry.get(tenant)
        cap = spec["max_sessions"]
        if cap is None:
            return
        live = self.sessions_of(tenant) + self._remote(tenant)[2]
        if live + 1 > cap:
            raise QuotaExceeded(
                f"tenant {tenant!r} at max_sessions ({live} live, cap {cap})",
                tenant=tenant, retry_after_s=spec["window_s"])

    def _remote(self, tenant: str) -> Tuple[float, int, int]:
        fn = self.remote_spend
        if fn is None:
            return (0.0, 0, 0)
        return fn(tenant)

    def _retry_after(self, tenant: str, now: float, *,
                     need_device_s: float = 0.0, need_cells: int = 0) -> float:
        """Walk the oldest local charges until enough spend has aged out
        for the overshoot to fit; the answer is how long until that
        charge leaves the window.  When local history alone cannot free
        it (remote spend, or an estimate bigger than the whole quota),
        a full window is the honest answer."""
        window_s = self.registry.get(tenant)["window_s"]
        freed_s, freed_cells = 0.0, 0
        with self._lock:
            for t, d, c in self._events.get(tenant) or ():
                freed_s += d
                freed_cells += c
                if freed_s >= need_device_s and freed_cells >= need_cells:
                    return max(0.0, t + window_s - now)
        return window_s

    # -- gossip ------------------------------------------------------

    def window_snapshot(self) -> Dict[str, dict]:
        """This node's current window spend per tenant, for the gossip
        digest.  A full (absolute) snapshot, not a delta — peers keep
        only the latest per node, so sums stay exact under replay."""
        now = self.clock()
        out: Dict[str, dict] = {}
        with self._lock:
            live: Dict[str, int] = {}
            for t in self._sid_tenant.values():
                live[t] = live.get(t, 0) + 1
            for tenant in set(self._events) | set(live):
                self._prune(tenant, now)
                tot = self._totals.get(tenant) or (0.0, 0)
                out[tenant] = {
                    "device_s": tot[0],
                    "cells": tot[1],
                    "sessions": live.get(tenant, 0),
                }
        return out
