"""SLO-driven load shedding (ISSUE 16).

A ``LoadShedder`` listens to the ``SloEngine``'s post-evaluate hook and
maintains a shed *level*: 0 sheds nothing, level 1 drops ``bulk``,
level 2 also drops ``standard``, level 3 drops everything including
``interactive`` (reachable only when ``max_level`` allows it; the
default stops at 2 so interactive traffic survives any automated
response).  Shedding answers 429 + Retry-After, the same shape as a
quota rejection.

Escalation and release reuse slo.py's flap-damping discipline, in both
directions: the *first* critical evaluation sheds bulk immediately
(worsening is immediate, exactly like SLO state transitions), but each
*further* level up needs ``damp_evals`` consecutive critical
evaluations at the current level, and each level down needs
``damp_evals`` consecutive non-critical evaluations — one flapping
window cannot ratchet the ladder to the top or release it early.
"""

from __future__ import annotations

import threading
from typing import Optional

from mpi_tpu.admission.quota import AdmissionReject
from mpi_tpu.admission.sched import CLASSES, CLASS_RANK


class ShedRejected(AdmissionReject):
    """Request dropped by the shed ladder, not by the tenant's quota."""


class LoadShedder:
    """The damped escalation ladder.  ``evaluate(worst)`` is called from
    the telemetry sampler with the SLO engine's worst state; request
    threads call ``check(tenant, qos)``."""

    def __init__(self, *, damp_evals: int = 3, max_level: int = 2,
                 retry_after_s: float = 30.0):
        self.damp_evals = max(1, int(damp_evals))
        self.max_level = max(0, min(len(CLASSES), int(max_level)))
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self.level = 0
        self._critical_streak = 0
        self._clear_streak = 0
        self.transitions = 0

    def evaluate(self, worst: str) -> int:
        """Feed one SLO evaluation; returns the (possibly new) level."""
        with self._lock:
            if worst == "critical":
                self._clear_streak = 0
                self._critical_streak += 1
                if self.level == 0:
                    self._set_level(1)
                elif self._critical_streak >= self.damp_evals:
                    self._critical_streak = 0
                    self._set_level(self.level + 1)
            else:
                self._critical_streak = 0
                if self.level > 0:
                    self._clear_streak += 1
                    if self._clear_streak >= self.damp_evals:
                        self._clear_streak = 0
                        self._set_level(self.level - 1)
            return self.level

    def _set_level(self, level: int) -> None:
        level = max(0, min(self.max_level, level))
        if level != self.level:
            self.level = level
            self.transitions += 1
            self._critical_streak = 0

    def sheds(self, qos: str) -> bool:
        """Level 1 sheds the lowest-ranked class, each further level one
        more: class rank >= len(CLASSES) - level is dropped."""
        return CLASS_RANK[qos] >= len(CLASSES) - self.level

    def check(self, tenant: str, qos: str) -> None:
        if self.level and self.sheds(qos):
            raise ShedRejected(
                f"shedding {qos!r} traffic (shed level {self.level}: SLO "
                f"critical)", tenant=tenant,
                retry_after_s=self.retry_after_s)
