"""Priority classes and the weighted class scheduler (ISSUE 16).

Three classes, strictly ranked ``interactive > standard > bulk``.  A
tenant declares a ``default_class`` (what its requests get with no
override) and a ``max_class`` ceiling (the highest class it may
request); a per-request override is *capped* at the ceiling, never
rejected — asking for more than you are entitled to quietly gets you
your ceiling, the same discipline as a clamped nice value.

Head-of-line scheduling is smooth weighted round-robin over the classes
that currently have work (4:2:1): each pick adds every waiting class's
weight to its credit, takes the class with the most credit (ties go to
the higher-priority class), and debits the winner by the total weight in
play.  Interactive therefore dominates 4:2:1 under sustained load, and
no class with queued work waits more than a bounded number of rounds —
bulk cannot starve interactive *and* interactive cannot starve bulk.
Within the chosen class, tickets order by estimated device cost
ascending (CostCard ``ops_per_cell x cells``, computed at enqueue), so
a bulk mega-board never rides ahead of a viewport-sized request of the
same class.
"""

from __future__ import annotations

from typing import Dict, List, Optional

CLASSES = ("interactive", "standard", "bulk")
CLASS_RANK: Dict[str, int] = {c: i for i, c in enumerate(CLASSES)}
CLASS_WEIGHT: Dict[str, int] = {"interactive": 4, "standard": 2, "bulk": 1}
DEFAULT_CLASS = "standard"


def clamp_class(requested: Optional[str], ceiling: str) -> str:
    """The class a request actually gets: its ask, capped at the
    tenant's ceiling (a lower rank is a higher priority)."""
    if requested is None:
        return ceiling
    if CLASS_RANK[requested] < CLASS_RANK[ceiling]:
        return ceiling
    return requested


class WeightedClassPicker:
    """Smooth weighted round-robin over priority classes.  Deterministic
    (no randomness, no wall clock): the pick sequence for a fixed set of
    waiting classes is a pure function of how many picks came before.
    Callers serialize access (the dispatch loop is single-threaded)."""

    def __init__(self, weights: Optional[Dict[str, int]] = None):
        self.weights = dict(weights or CLASS_WEIGHT)
        self._credit: Dict[str, float] = {c: 0.0 for c in self.weights}

    def pick(self, waiting: List[str]) -> str:
        """The class served this round, from the classes with queued
        work.  Classes with nothing queued accrue no credit — an idle
        class cannot bank priority for later."""
        waiting = [c for c in CLASSES if c in waiting]
        if not waiting:
            raise ValueError("pick() needs at least one waiting class")
        if len(waiting) == 1:
            return waiting[0]
        total = 0
        for c in waiting:
            self._credit[c] += self.weights[c]
            total += self.weights[c]
        # max credit; ties go to the higher-priority (lower-rank) class,
        # which the CLASSES-ordered scan gives for free
        best = max(waiting, key=lambda c: self._credit[c])
        self._credit[best] -= total
        return best

    def reset(self) -> None:
        self._credit = {c: 0.0 for c in self.weights}
