"""``python -m mpi_tpu.tune`` — the autotuner runner and the tune-cache
staleness gate.

Modes (exit-code contract shared with the other analysis runners:
0 clean, 1 findings, 2 internal error):

* default — tune one plan (``--rows/--cols/--rule/...``), persist the
  winner, print a JSON summary;
* ``--check`` — validate every cached entry under CURRENT config rules
  (key still resolves, base still constructs, plan still applies);
  wired into ``tools/ci_gate.sh``.  A missing cache file is clean: no
  entries, nothing stale;
* ``--list`` — dump the cache entries.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpi_tpu.tune",
        description="cost-card-guided plan autotuner "
        "(deep-halo cadence, sparse tile, Pallas blocks, batch)")
    p.add_argument("--rows", type=int, default=1024)
    p.add_argument("--cols", type=int, default=1024)
    p.add_argument("--rule", default="life")
    p.add_argument("--boundary", default="periodic",
                   choices=("periodic", "dead"))
    p.add_argument("--mesh", default=None, metavar="MIxMJ",
                   help="device mesh (default: auto)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=64,
                   help="generations per timed probe")
    p.add_argument("--reps", type=int, default=2,
                   help="timed repetitions per candidate (best-of)")
    p.add_argument("--settle", type=int, default=0,
                   help="untimed generations before each timed window "
                   "(probes state-carrying engines in steady state)")
    p.add_argument("--batch", action="store_true",
                   help="also probe batched (B-board) dispatch as a "
                   "serving hint")
    p.add_argument("--min-speedup", type=float, default=1.05,
                   help="winners inside this noise band stay default")
    p.add_argument("--cache", default=None, metavar="PATH",
                   help="tune cache file (default perf/tune_cache.json; "
                   "env MPI_TPU_TUNE_CACHE)")
    p.add_argument("--check", action="store_true",
                   help="validate every cached entry under current "
                   "config rules and exit (0 clean / 1 findings)")
    p.add_argument("--list", action="store_true", dest="list_entries",
                   help="print the cache entries and exit")
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from mpi_tpu.tune import TuneCache

    cache = TuneCache(args.cache)
    if args.check:
        findings = cache.check()
        for f in findings:
            print(f"tune-check: {f}")
        print(f"tune-check: {len(cache)} entr"
              f"{'y' if len(cache) == 1 else 'ies'} in {cache.path}, "
              f"{len(findings)} finding(s)")
        return 1 if findings else 0
    if args.list_entries:
        print(json.dumps(cache.entries(), indent=1, sort_keys=True))
        return 0
    from mpi_tpu.config import ConfigError, GolConfig
    from mpi_tpu.models.rules import rule_from_name
    from mpi_tpu.tune import tune_plan

    mesh = None
    if args.mesh:
        try:
            mi, mj = args.mesh.lower().split("x")
            mesh = (int(mi), int(mj))
        except ValueError:
            print(f"bad --mesh {args.mesh!r} (want MIxMJ)", file=sys.stderr)
            return 2
    try:
        config = GolConfig(
            rows=args.rows, cols=args.cols, steps=0, seed=args.seed,
            rule=rule_from_name(args.rule), boundary=args.boundary,
            backend="tpu", mesh_shape=mesh)
        res = tune_plan(config, steps=args.steps, reps=args.reps,
                        settle=args.settle,
                        cache=cache, include_batch=args.batch,
                        min_speedup=args.min_speedup,
                        verbose=not args.quiet)
    except ConfigError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 — runner exit-code contract
        print(f"tune failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(res.as_dict(), sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
