"""The probe loop: measure candidates, prune by the cost model, bless
winners.

Probing is REAL timing — build the candidate engine, run ``steps``
generations on the actual board, best-of-``reps`` wall clock — because
the objective the roofline gives us is a bound, not a prediction.  The
cost model's job is pruning: before paying a candidate's XLA compile,
its traced jaxpr is op-counted (:mod:`mpi_tpu.obs.opcount` — tracing
costs milliseconds, compiling seconds) and the candidate is skipped
when even an optimistic throughput bound cannot beat the incumbent.

The bound never discards the incumbent, by construction: the reference
throughput ``R`` is the *demonstrated* ops/s — the max over measured
candidates of (measured cells/s × that candidate's ops/cell), floored
by the platform roof only when one was measured for this box
(``MPI_TPU_ROOF_OPS_PER_S``) — and a candidate's bound is
``margin · R / ops_per_cell`` with ``margin ≥ 1``.  The incumbent's own
bound is therefore ≥ its own measurement, so it always survives
(``tests/test_tune.py`` pins this).  Sparse candidates are never pruned
at all: their cost is data-dependent (the traced program carries both
sides of the activity gate), so the static count is an upper bound on
the wrong quantity.

Blessing, before a winner is persisted:

* **parity** — bit-identical final board vs the default plan's output,
  and (small boards) vs the serial numpy oracle;
* **IR contract** — every ppermute halo slab in the winner's trace has
  a depth in ``expected_slab_depths(radius, comm_every, packed)``, the
  same contract the ir-collective check holds the matrix to;
* the bench-regression envelope (``tools/bench_gate.py``) stays the
  outer gate: tuned plans land in ``perf/tune_cache.json``, and the
  envelope judges the numbers the next capture produces.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from mpi_tpu.config import GolConfig, apply_plan
from mpi_tpu.tune.cache import TuneCache, platform_fingerprint
from mpi_tpu.tune.space import Candidate, candidates

# how forgiving the prune bound is: a candidate is only skipped when
# margin x the demonstrated ops-throughput still cannot reach the best
# measured cells/s at the candidate's ops/cell.  2x absorbs the usual
# gap between counted lane-ops and achieved throughput across engines.
PRUNE_MARGIN = 2.0

# serial-oracle budget: run evolve_np when cells * steps stays under
# this (beyond it, parity is judged against the default plan's output —
# itself oracle-verified by the test suite at small sizes)
ORACLE_CELL_STEPS = 1 << 26


@dataclass
class Probe:
    """One candidate's outcome."""

    label: str
    plan: dict
    status: str                  # "measured" | "pruned" | "failed"
    cells_per_s: float = 0.0
    wall_s: float = 0.0
    ops_per_cell: Optional[float] = None
    bound_cells_per_s: Optional[float] = None
    parity: Optional[bool] = None
    detail: str = ""

    def as_dict(self) -> dict:
        d = {"label": self.label, "plan": self.plan, "status": self.status,
             "cells_per_s": round(self.cells_per_s, 1),
             "wall_s": round(self.wall_s, 6)}
        if self.ops_per_cell is not None:
            d["ops_per_cell"] = round(self.ops_per_cell, 3)
        if self.bound_cells_per_s is not None:
            d["bound_cells_per_s"] = round(self.bound_cells_per_s, 1)
        if self.parity is not None:
            d["parity"] = self.parity
        if self.detail:
            d["detail"] = self.detail
        return d


@dataclass
class TuneResult:
    """The winner plus the full probe ledger."""

    config: GolConfig
    mesh_shape: Tuple[int, int]
    winner: dict = field(default_factory=dict)
    winner_label: str = "default"
    default_cells_per_s: float = 0.0
    tuned_cells_per_s: float = 0.0
    probes: List[Probe] = field(default_factory=list)
    pruned: int = 0
    oracle: str = "none"
    key: Optional[str] = None

    @property
    def speedup(self) -> float:
        if self.default_cells_per_s <= 0:
            return 1.0
        return self.tuned_cells_per_s / self.default_cells_per_s

    def as_dict(self) -> dict:
        return {
            "rows": self.config.rows, "cols": self.config.cols,
            "mesh": list(self.mesh_shape),
            "winner": self.winner, "winner_label": self.winner_label,
            "default_cells_per_s": round(self.default_cells_per_s, 1),
            "tuned_cells_per_s": round(self.tuned_cells_per_s, 1),
            "speedup": round(self.speedup, 3),
            "probed": sum(1 for p in self.probes
                          if p.status == "measured"),
            "pruned": self.pruned,
            "oracle": self.oracle,
            "probes": [p.as_dict() for p in self.probes],
            "key": self.key,
        }


def should_prune(ops_per_cell: float, demonstrated_ops_per_s: float,
                 best_cells_per_s: float,
                 margin: float = PRUNE_MARGIN) -> bool:
    """Skip a candidate whose optimistic bound cannot beat the best
    measurement.  ``margin`` is clamped to >= 1 so the bound stays an
    over-estimate: for the incumbent itself, bound >= demonstrated/opc
    >= its own measured cells/s — it can never be discarded."""
    if ops_per_cell <= 0 or demonstrated_ops_per_s <= 0:
        return False
    # compare as products (bound < best ⇔ margin·demonstrated <
    # best·opc): the division form can land one ulp under the
    # incumbent's own measurement and discard it on rounding alone
    return (max(margin, 1.0) * demonstrated_ops_per_s
            < best_cells_per_s * ops_per_cell)


def candidate_bound(ops_per_cell: Optional[float],
                    demonstrated_ops_per_s: float,
                    margin: float = PRUNE_MARGIN) -> Optional[float]:
    if ops_per_cell is None or ops_per_cell <= 0 \
            or demonstrated_ops_per_s <= 0:
        return None
    return max(margin, 1.0) * demonstrated_ops_per_s / ops_per_cell


def _trace_ops_per_cell(engine, grid, depth: int,
                        cells: int) -> Optional[float]:
    """Counted ALU lane-ops per cell-update of the candidate's evolve at
    ``depth`` — tracing only, no compile, no dispatch."""
    import jax

    from mpi_tpu.obs.opcount import count_ops

    try:
        closed = jax.make_jaxpr(
            lambda g: engine._evolve(g, depth))(grid)
        total = count_ops(closed)
    except Exception:  # noqa: BLE001 — a cost estimate, never fatal
        return None
    denom = float(cells) * max(depth, 1)
    return total / denom if denom and total else None


def _slab_depths_ok(engine, grid, depth: int) -> Tuple[bool, str]:
    """The winner-side ir-collective bless: every ppermute operand slab
    in the traced evolve must be one of the depths
    ``expected_slab_depths(radius, comm_every, packed)`` allows."""
    import jax

    from mpi_tpu.parallel.halo import expected_slab_depths

    cfg = engine.config
    allowed = expected_slab_depths(cfg.rule.radius, cfg.comm_every,
                                   engine.bitpacked)

    def walk(jaxpr, out):
        for eqn in jaxpr.eqns:
            for key in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    walk(sub.jaxpr if hasattr(sub, "jaxpr") else sub, out)
            if "branches" in eqn.params:
                for br in eqn.params["branches"]:
                    walk(br.jaxpr if hasattr(br, "jaxpr") else br, out)
            if eqn.primitive.name == "ppermute":
                shape = tuple(eqn.invars[0].aval.shape)
                out.append(shape)

    try:
        closed = jax.make_jaxpr(lambda g: engine._evolve(g, depth))(grid)
        slabs: List[tuple] = []
        walk(closed.jaxpr, slabs)
    except Exception as e:  # noqa: BLE001 — report, don't crash the tuner
        return False, f"slab trace failed: {type(e).__name__}: {e}"
    for shape in slabs:
        thin = min(shape) if shape else 0
        if thin not in allowed:
            return False, (f"halo slab {shape} depth {thin} not in "
                           f"{sorted(allowed)}")
    return True, ""


def _measure(engine, board: np.ndarray, steps: int, reps: int,
             batch: int = 0, settle: int = 0) -> Tuple[float, np.ndarray]:
    """(best wall seconds, fetched final board) for ``steps``
    generations — warm first (compile outside the timed window), then
    best-of-``reps`` fresh runs.  ``settle`` > 0 advances that many
    untimed generations after each re-init so state-carrying engines
    (the sparse dirty map starts all-dirty on a fresh grid) are timed in
    their steady regime; the returned board is then generation
    ``settle + steps``, identically for every candidate.  ``batch`` > 0
    times the vmapped batched stepper over B copies and reports
    per-board wall."""
    import jax

    def run():
        if batch:
            grids = engine.init_grids(initials=[board] * batch)
            if settle:
                grids = engine.step_batched(grids, settle)
                jax.block_until_ready(grids)
            t0 = time.perf_counter()
            grids = engine.step_batched(grids, steps)
            jax.block_until_ready(grids)
            return time.perf_counter() - t0, grids
        g = engine.init_grid(initial=board)
        if settle:
            g = engine.step(g, settle)
            jax.block_until_ready(engine.raw_grid(g))
        t0 = time.perf_counter()
        g = engine.step(g, steps)
        jax.block_until_ready(engine.raw_grid(g))
        return time.perf_counter() - t0, g

    _, out = run()                       # warm: compile both depths
    best = float("inf")
    for _ in range(max(reps, 1)):
        wall, out = run()
        best = min(best, wall)
    if batch:
        final = engine.fetch_batched(out)[0]
        best = best / batch              # per-board wall
    else:
        final = engine.fetch(out)
    return best, np.asarray(final)


def tune_plan(config: GolConfig, *, board: Optional[np.ndarray] = None,
              steps: int = 64, reps: int = 2, settle: int = 0,
              cache: Optional[TuneCache] = None,
              cands: Optional[List[Candidate]] = None,
              include_batch: bool = False,
              margin: float = PRUNE_MARGIN,
              min_speedup: float = 1.05,
              verbose: bool = False) -> TuneResult:
    """Search the plan space for ``config``; persist the blessed winner.

    ``board`` defaults to the config's seeded random grid fetched from
    the default engine (probing must compare identical initial states).
    ``settle`` advances untimed generations before each timed window so
    state-carrying engines are probed in steady state (parity then
    compares boards at generation ``settle + steps``).  ``cands``
    overrides the generated space (tests).  The winner is recorded in
    ``cache`` (when given) even when the default plan wins — an
    empty-plan entry tells the next run tuning already happened."""
    from mpi_tpu.backends.tpu import build_engine, device_count
    from mpi_tpu.parallel.mesh import choose_mesh_shape, make_mesh

    mesh_shape = config.mesh_shape or choose_mesh_shape(device_count())
    cells = config.cells

    def log(msg):
        if verbose:
            import sys

            print(f"tune: {msg}", file=sys.stderr)

    res = TuneResult(config=config, mesh_shape=mesh_shape)
    space = cands if cands is not None \
        else candidates(config, mesh_shape, include_batch=include_batch)
    # -- incumbent: the default plan, measured first -----------------------
    default_eng = build_engine(config, mesh=make_mesh(mesh_shape))
    if board is None:
        board = default_eng.fetch(default_eng.init_grid())
    wall, default_out = _measure(default_eng, board, steps, reps,
                                 settle=settle)
    res.default_cells_per_s = cells * steps / wall if wall > 0 else 0.0
    opc0 = _trace_ops_per_cell(default_eng, default_eng.init_grid(
        initial=board), config.comm_every, cells)
    res.probes.append(Probe("default", {}, "measured",
                            cells_per_s=res.default_cells_per_s,
                            wall_s=wall, ops_per_cell=opc0, parity=True))
    log(f"default: {res.default_cells_per_s:.3g} cells/s "
        f"(ops/cell {opc0 if opc0 is None else round(opc0, 2)})")
    # oracle: serial numpy when affordable, else the default plan output
    oracle_out = default_out
    res.oracle = "default-plan"
    if cells * (settle + steps) <= ORACLE_CELL_STEPS:
        from mpi_tpu.backends.serial_np import evolve_np

        oracle_out = evolve_np(board, settle + steps, config.rule,
                               config.boundary)
        res.oracle = "serial-numpy"
        if not np.array_equal(default_out, oracle_out):
            raise AssertionError(
                "default plan does not match the serial oracle — refusing "
                "to tune on top of a broken baseline")
    # demonstrated ops-throughput: what the hardware has actually been
    # seen to sustain, floored by an explicitly measured roof (never the
    # committed TPU constant — that would over-prune on other boxes)
    import os

    demonstrated = 0.0
    if opc0:
        demonstrated = res.default_cells_per_s * opc0
    env_roof = os.environ.get("MPI_TPU_ROOF_OPS_PER_S")
    if env_roof:
        try:
            demonstrated = max(demonstrated, float(env_roof))
        except ValueError:
            pass
    best_cells = res.default_cells_per_s
    best_plan: dict = {}
    best_label = "default"
    # -- the sweep ---------------------------------------------------------
    for cand in space:
        if cand.is_default:
            continue
        batch = int(cand.plan.get("batch", 0) or 0)
        try:
            tuned_cfg = apply_plan(config, cand.plan)
        except Exception as e:  # noqa: BLE001 — infeasible = skipped
            res.probes.append(Probe(cand.label, dict(cand.plan), "failed",
                                    detail=f"{type(e).__name__}: {e}"))
            continue
        try:
            eng = default_eng if batch and not cand.plan.get("blocks") \
                and tuned_cfg == config else build_engine(
                    tuned_cfg, mesh=make_mesh(mesh_shape),
                    blocks=cand.plan.get("blocks"))
            depth = tuned_cfg.comm_every
            opc = None
            if not cand.data_dependent and not batch:
                opc = _trace_ops_per_cell(
                    eng, eng.init_grid(initial=board), depth, cells)
                if opc is not None and should_prune(
                        opc, demonstrated, best_cells, margin):
                    res.pruned += 1
                    res.probes.append(Probe(
                        cand.label, dict(cand.plan), "pruned",
                        ops_per_cell=opc,
                        bound_cells_per_s=candidate_bound(
                            opc, demonstrated, margin)))
                    log(f"{cand.label}: pruned (ops/cell {opc:.2f})")
                    continue
            wall, out = _measure(eng, board, steps, reps, batch=batch,
                                 settle=settle)
            tput = cells * steps / wall if wall > 0 else 0.0
            parity = np.array_equal(out, oracle_out)
            probe = Probe(cand.label, dict(cand.plan), "measured",
                          cells_per_s=tput, wall_s=wall, ops_per_cell=opc,
                          parity=parity)
            if not parity:
                probe.status = "failed"
                probe.detail = "output differs from oracle"
                res.probes.append(probe)
                log(f"{cand.label}: PARITY FAILURE — discarded")
                continue
            if tuned_cfg.comm_every > 1 and mesh_shape != (1, 1):
                ok, why = _slab_depths_ok(
                    eng, eng.init_grid(initial=board), depth)
                if not ok:
                    probe.status = "failed"
                    probe.detail = why
                    res.probes.append(probe)
                    log(f"{cand.label}: IR contract failure ({why})")
                    continue
            res.probes.append(probe)
            if opc:
                demonstrated = max(demonstrated, tput * opc)
            log(f"{cand.label}: {tput:.3g} cells/s "
                f"({tput / max(res.default_cells_per_s, 1e-12):.2f}x)")
            if tput > best_cells:
                best_cells, best_plan, best_label = \
                    tput, dict(cand.plan), cand.label
        except Exception as e:  # noqa: BLE001 — one sick candidate must
            # not kill the sweep (Mosaic compile errors, OOM at big B)
            res.probes.append(Probe(cand.label, dict(cand.plan), "failed",
                                    detail=f"{type(e).__name__}: {e}"))
            log(f"{cand.label}: failed ({type(e).__name__}: {e})")
    # -- bless -------------------------------------------------------------
    if best_plan and best_cells < res.default_cells_per_s * min_speedup:
        # a winner inside the noise band is not a winner
        best_plan, best_label, best_cells = \
            {}, "default", res.default_cells_per_s
    res.winner, res.winner_label = best_plan, best_label
    res.tuned_cells_per_s = best_cells
    if cache is not None:
        measured = {
            "default_cells_per_s": round(res.default_cells_per_s, 1),
            "tuned_cells_per_s": round(res.tuned_cells_per_s, 1),
            "speedup": round(res.speedup, 3),
            "steps": steps, "reps": reps, "settle": settle,
            "probed": sum(1 for p in res.probes if p.status == "measured"),
            "pruned": res.pruned,
            "oracle": res.oracle,
        }
        res.key = cache.record(config, mesh_shape, best_plan, measured,
                               platform=platform_fingerprint())
        cache.save()
        log(f"winner {best_label} persisted to {cache.path}")
    return res
