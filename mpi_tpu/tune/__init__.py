"""``mpi_tpu.tune`` — the cost-card-guided plan autotuner (ISSUE 11).

The plan knobs (halo cadence ``comm_every``, sparse tile ``T``, Pallas
block shape, serving batch ``B``) have shipped with hand-picked defaults
since they landed; this package searches them with real timed probes,
prunes by the op-count cost model, blesses winners against the parity
oracle and the halo-depth IR contract, and persists them per (platform,
requested plan signature) in a JSON cache — consulted by
``build_engine(tune=...)`` and the serving ``EngineCache`` path so a
tuned plan applies to the one-shot CLI and live sessions alike with
zero extra recompiles on the second run.

Application is strictly OPT-IN (a ``tune=`` argument, the serve CLI's
``--tune-cache``, or ``bench.py --tune``): the default build path never
reads the cache, so IR baselines, ``--no-obs`` bit-identity, and every
existing test see exactly the pre-tuner program.

    python -m mpi_tpu.tune --rows 2048 --cols 2048        # tune one plan
    python -m mpi_tpu.tune --check                        # CI staleness gate
    python bench.py --tune                                # A/B + persistence
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

from mpi_tpu.config import GolConfig
from mpi_tpu.tune.cache import (
    TuneCache, default_cache_path, platform_fingerprint, tune_key,
)
from mpi_tpu.tune.space import Candidate, candidates
from mpi_tpu.tune.tuner import TuneResult, should_prune, tune_plan

__all__ = [
    "TuneCache", "TuneResult", "Candidate", "candidates",
    "default_cache_path", "platform_fingerprint", "resolve_tuned",
    "should_prune", "tune_key", "tune_plan",
]


def resolve_tuned(config: GolConfig, mesh_shape: Tuple[int, int],
                  tune: Union[TuneCache, str, None],
                  ) -> Tuple[GolConfig, Optional[dict]]:
    """(possibly-tuned config, applied plan or None) — the one seam
    ``build_engine`` calls.  ``tune`` may be a :class:`TuneCache`, a
    cache path, or None (untouched)."""
    if tune is None:
        return config, None
    cache = tune if isinstance(tune, TuneCache) else TuneCache(str(tune))
    return cache.resolve(config, mesh_shape)
