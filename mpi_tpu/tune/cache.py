"""The JSON tune cache: persisted autotuner winners keyed by plan.

One entry per (platform fingerprint, requested plan signature): the
*requested* signature, not the winner's — a user who explicitly asks for
``comm_every=4`` has a different key than one who took the defaults, so
explicit choices are never silently overridden; the tuner only rewrites
plans it was asked to tune.  The key deliberately drops the signature's
``segments`` field (snapshot cadence changes which depths compile, not
which plan wins) and canonicalizes the rule to its parseable string form
(``B3/S23`` / ``R2,B8-12,S9-14``) so semantically equal rules share one
winner regardless of their registry name.

Entries store a reconstructable ``base`` config dict, the winning
``plan`` override dict (``{}`` = the default plan won — still worth
persisting: the second run knows tuning already happened), and the
measured A/B stats.  The file is advisory state, never load-bearing: a
corrupt or missing file reads as an empty cache, a stale plan that no
longer validates under current :mod:`mpi_tpu.config` rules is skipped at
resolve time (and reported by ``python -m mpi_tpu.tune --check``).

Invalidation: the cache key embeds ``len(SIGNATURE_FIELDS)`` as a
schema version, so the MIGRATION.md signature-extension procedure
(add field → SIGNATURE_FIELDS → regenerate IR baseline) automatically
orphans every cached winner — re-run the tuner after extending the
signature (see MIGRATION.md).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

from mpi_tpu.config import (
    ConfigError, GolConfig, SIGNATURE_FIELDS, apply_plan, validate_mesh,
)
from mpi_tpu.models.rules import Rule, rule_from_name

FORMAT_VERSION = 1


def default_cache_path() -> str:
    """``perf/tune_cache.json`` at the repo root (next to the bench
    artifacts), unless ``MPI_TPU_TUNE_CACHE`` points elsewhere."""
    env = os.environ.get("MPI_TPU_TUNE_CACHE")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "perf", "tune_cache.json")


def platform_fingerprint() -> str:
    """``platform:device_kind:count`` of the devices this process would
    compile for — the hardware half of the tune key (a CPU winner must
    never apply to a TPU run and vice versa)."""
    import jax

    devs = jax.devices()
    d = devs[0]
    kind = (getattr(d, "device_kind", "") or "unknown").replace(" ", "_")
    return f"{d.platform}:{kind}:{len(devs)}"


def rule_canonical(rule: Rule) -> str:
    """A canonical rule string ``rule_from_name`` can reparse: the name
    is dropped (``life`` and ``B3/S23`` share one winner — tuning
    depends on semantics, not labels)."""
    if rule.radius == 1:
        b = "".join(str(c) for c in sorted(rule.birth))
        s = "".join(str(c) for c in sorted(rule.survive))
        return f"B{b}/S{s}"

    def ranges(counts) -> str:
        from mpi_tpu.models.rules import _intervals

        return "+".join(f"{lo}-{hi}" if lo != hi else str(lo)
                        for lo, hi in _intervals(counts))

    return (f"R{rule.radius},B{ranges(rule.birth)},"
            f"S{ranges(rule.survive)}")


def base_dict(config: GolConfig, mesh_shape: Tuple[int, int]) -> dict:
    """The reconstructable request-plan fields of one entry — the
    signature minus ``segments``, with the rule canonicalized."""
    return {
        "rows": config.rows,
        "cols": config.cols,
        "rule": rule_canonical(config.rule),
        "boundary": config.boundary,
        "backend": config.backend,
        "mesh": [int(mesh_shape[0]), int(mesh_shape[1])],
        "comm_every": config.comm_every,
        "overlap": bool(config.overlap),
        "sparse_tile": config.sparse_tile,
    }


def config_from_base(base: dict) -> Tuple[GolConfig, Tuple[int, int]]:
    """Rebuild (config, mesh_shape) from an entry's ``base`` dict —
    re-running every current validation rule (the ``--check`` path)."""
    mesh = tuple(int(x) for x in base["mesh"])
    cfg = GolConfig(
        rows=int(base["rows"]), cols=int(base["cols"]), steps=0,
        rule=rule_from_name(str(base["rule"])),
        boundary=str(base["boundary"]), backend=str(base["backend"]),
        mesh_shape=mesh, comm_every=int(base.get("comm_every", 1)),
        overlap=bool(base.get("overlap", False)),
        sparse_tile=int(base.get("sparse_tile", 0)),
    )
    return cfg, mesh


def tune_key(config: GolConfig, mesh_shape: Tuple[int, int],
             platform: Optional[str] = None) -> str:
    """The cache key for a *requested* config on a platform.  Embeds the
    signature arity as a schema tag: extending ``SIGNATURE_FIELDS``
    orphans (never mis-applies) every existing entry."""
    platform = platform if platform is not None else platform_fingerprint()
    b = base_dict(config, mesh_shape)
    return "|".join([
        f"sig{len(SIGNATURE_FIELDS)}", platform,
        f"{b['rows']}x{b['cols']}", b["rule"], b["boundary"], b["backend"],
        f"mesh{b['mesh'][0]}x{b['mesh'][1]}",
        f"k{b['comm_every']}", f"ov{int(b['overlap'])}",
        f"T{b['sparse_tile']}",
    ])


class TuneCache:
    """Thread-safe load/store of tune entries in one JSON file.

    A missing, unreadable, or corrupt file is an EMPTY cache (noted on
    ``load_error``), never an exception — serving must not die on bad
    advisory state.  Writes go tmp+fsync+replace (the recovery store's
    discipline) so a crash mid-save cannot corrupt a good file."""

    def __init__(self, path: Optional[str] = None):
        self.path = path if path is not None else default_cache_path()
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self.load_error: Optional[str] = None
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as fh:
                raw = json.load(fh)
            entries = raw.get("entries")
            if not isinstance(entries, dict):
                raise ValueError("no 'entries' object")
            self._entries = {str(k): dict(v) for k, v in entries.items()
                             if isinstance(v, dict)}
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — corrupt cache = empty cache
            self.load_error = f"{type(e).__name__}: {e}"
            self._entries = {}

    def save(self) -> None:
        with self._lock:
            payload = {"version": FORMAT_VERSION,
                       "entries": dict(sorted(self._entries.items()))}
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tune_cache.",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(payload, fh, indent=1, sort_keys=True)
                fh.write("\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._entries)

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(key)
            return dict(e) if e is not None else None

    def record(self, config: GolConfig, mesh_shape: Tuple[int, int],
               plan: dict, measured: dict,
               platform: Optional[str] = None) -> str:
        """Store one blessed winner (call :meth:`save` to persist)."""
        platform = (platform if platform is not None
                    else platform_fingerprint())
        key = tune_key(config, mesh_shape, platform)
        entry = {
            "platform": platform,
            "base": base_dict(config, mesh_shape),
            "plan": dict(plan),
            "measured": dict(measured),
        }
        with self._lock:
            self._entries[key] = entry
        return key

    # -- serving-path resolution ------------------------------------------

    def resolve(self, config: GolConfig, mesh_shape: Tuple[int, int],
                platform: Optional[str] = None,
                ) -> Tuple[GolConfig, Optional[dict]]:
        """(possibly-tuned config, applied plan dict or None).

        Best-effort by contract: no entry, an empty winning plan, or a
        stale plan that fails current validation all return the config
        untouched — a bad cache can cost the speedup, never the run."""
        try:
            key = tune_key(config, mesh_shape, platform)
        except Exception:  # noqa: BLE001 — advisory state, never fatal
            return config, None
        entry = self.get(key)
        if not entry:
            return config, None
        plan = entry.get("plan") or {}
        if not plan:
            return config, None
        try:
            tuned = apply_plan(config, plan)
            validate_mesh(tuned.rows, tuned.cols, tuple(mesh_shape),
                          tuned.rule.radius * tuned.comm_every)
        except ConfigError:
            return config, None
        return tuned, dict(plan)

    # -- staleness / validity audit ---------------------------------------

    def check(self) -> List[str]:
        """Findings for ``python -m mpi_tpu.tune --check``: every entry's
        base must reconstruct under current config rules, its key must
        still resolve (recompute to itself — signature arity drift
        orphans it), and its plan must still apply cleanly."""
        findings: List[str] = []
        if self.load_error is not None:
            findings.append(f"cache file {self.path}: unreadable "
                            f"({self.load_error}) — treated as empty")
        for key, entry in sorted(self.entries().items()):
            base = entry.get("base")
            if not isinstance(base, dict):
                findings.append(f"entry {key}: no base config dict")
                continue
            try:
                cfg, mesh = config_from_base(base)
            except Exception as e:  # noqa: BLE001 — each entry judged alone
                findings.append(
                    f"entry {key}: base config no longer validates "
                    f"({type(e).__name__}: {e})")
                continue
            expect = tune_key(cfg, mesh, str(entry.get("platform", "")))
            if expect != key:
                findings.append(
                    f"entry {key}: signature no longer resolves "
                    f"(recomputes to {expect}; SIGNATURE_FIELDS arity or "
                    f"key schema changed — re-run the tuner)")
            plan = entry.get("plan") or {}
            try:
                tuned = apply_plan(cfg, plan)
                validate_mesh(tuned.rows, tuned.cols, mesh,
                              tuned.rule.radius * tuned.comm_every)
            except ConfigError as e:
                findings.append(
                    f"entry {key}: plan {plan} no longer validates "
                    f"under current config rules ({e})")
        return findings
