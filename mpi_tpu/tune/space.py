"""Candidate plan generation for the autotuner.

The search space is the deep-halo / fused-generation trade (ROADMAP
item 3) over the knobs that already exist:

* ``comm_every`` k — generations per halo exchange / temporal-blocking
  depth (ghost ring widens to k·r; ``expected_slab_depths`` encodes the
  contract the ir-collective check verifies);
* ``sparse_tile`` T — the activity-gated engine's dirty-tile size;
* ``blocks`` (BM, CM) — the fused Pallas SWAR kernel's DMA-slab /
  compute-tile rows (single-device packed TPU runs only);
* ``batch`` B — a serving hint for the microbatcher, probed but never
  applied to the solo program.

Feasibility is judged by the SAME validation the production path runs
(:func:`mpi_tpu.config.apply_plan` → ``GolConfig.__post_init__`` →
``validate_mesh``): the space enumerates, config rules decide.  The
default plan is always candidate 0 — it is the incumbent every bound is
measured against, and the parity oracle every winner must match
bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Tuple

from mpi_tpu.config import ConfigError, GolConfig, apply_plan, validate_mesh

COMM_EVERY_CANDIDATES = (2, 4, 8)
SPARSE_TILE_CANDIDATES = (32, 64, 128, 256)
# rectangular Pallas block grid (rows); candidates are screened by the
# kernels' own alignment/VMEM predicates before being proposed
PALLAS_BLOCK_SIZES = (512, 256, 128, 64, 32)
MAX_BLOCK_CANDIDATES = 6   # per (plan-prefix) axis — keeps sweeps bounded


@dataclass(frozen=True)
class Candidate:
    """One point of the plan space: the override dict (empty = the
    default plan) plus a display label."""

    plan: dict = field(default_factory=dict)
    label: str = "default"

    @property
    def is_default(self) -> bool:
        return not self.plan

    @property
    def data_dependent(self) -> bool:
        """Whether this candidate's runtime cost depends on board
        content (the sparse engine's dirty map): such candidates are
        never pruned by the static ops bound — tracing counts both
        branches of the gate, so the bound would be meaningless."""
        return bool(self.plan.get("sparse_tile"))


def _feasible(config: GolConfig, mesh_shape: Tuple[int, int],
              plan: dict) -> bool:
    try:
        tuned = apply_plan(config, plan)
        validate_mesh(tuned.rows, tuned.cols, tuple(mesh_shape),
                      tuned.rule.radius * tuned.comm_every)
    except ConfigError:
        return False
    return True


def _block_candidates(config: GolConfig, mesh_shape: Tuple[int, int],
                      gens: int = None) -> Iterator[Candidate]:
    """Pallas block-shape overrides — only where a fused kernel actually
    serves the plan (single device, supported shape, real TPU lowering):
    elsewhere the override is dead weight.

    The grid is rectangular (BM × CM over ``PALLAS_BLOCK_SIZES``),
    screened by the kernel's own alignment/VMEM predicate
    (``pallas_bitlife.blocks_ok`` — the same screens ``_pick_blocks``
    applies to its auto-candidates) and capped at
    ``MAX_BLOCK_CANDIDATES``; the auto-picked shape is excluded (it IS
    the incumbent).  ``gens`` overrides the temporal-blocking depth the
    candidates are validated at (the (k, blocks) paired axis — see
    :func:`candidates`).

    Opcount-pruning soundness (tuner ``should_prune``): a ``blocks``
    override never changes the *traced* op count — the kernel's interior
    is opaque to the trace — so a blocks candidate's optimistic bound
    equals the incumbent's and it can never be wrongly pruned; paired
    (comm_every, blocks) candidates are traced at their own depth, so
    their bound is their own.  Radius > 1 dense-routed plans take the
    dense stencil kernel's (BM, SR) knob instead, screened by its
    ``_pick_block_rows``/``_pick_sub_rows`` budgets."""
    if mesh_shape != (1, 1):
        return
    from mpi_tpu.backends.tpu import _pallas_single_device_mode

    use, interpret = _pallas_single_device_mode()
    if not use or interpret:
        return
    tag = "" if gens is None else f"comm_every={gens},"
    base = {} if gens is None else {"comm_every": gens}
    if gens is None:
        gens = config.comm_every
    count = 0
    if config.rule.radius == 1:
        from mpi_tpu.ops.pallas_bitlife import blocks_ok, _pick_blocks, supports

        if not supports((config.rows, config.cols), config.rule, gens=gens):
            return
        H, NW = config.rows, config.cols // 32
        auto = _pick_blocks(H, NW, gens)
        if auto is None:
            return
        for bm in PALLAS_BLOCK_SIZES:
            for cm in PALLAS_BLOCK_SIZES:
                if cm > bm or (bm, cm) == auto:
                    continue
                if not blocks_ok(H, NW, bm, cm, gens):
                    continue
                yield Candidate({**base, "blocks": [bm, cm]},
                                f"{tag}blocks={bm}x{cm}")
                count += 1
                if count >= MAX_BLOCK_CANDIDATES:
                    return
        return
    # radius > 1: the dense fused stencil kernel's (BM, SR) plane —
    # only when the dense route will actually dispatch it (bit-sliced
    # modes own word-aligned shapes their kernel serves; blocks are dead
    # weight there)
    import dataclasses

    from mpi_tpu.backends.tpu import plan_pad_width, select_ltl_mode
    from mpi_tpu.ops.pallas_stencil import (
        _halo_rows, _pick_block_rows, _pick_sub_rows, supports,
    )

    if not supports((config.rows, config.cols), config.rule, gens=gens):
        return
    cfg_g = (dataclasses.replace(config, comm_every=gens)
             if gens != config.comm_every else config)
    cols_eff, pad_bits = plan_pad_width(cfg_g, 1, shard_rows=config.rows)
    if select_ltl_mode(cfg_g, 1, 1, cols=cols_eff,
                       pad_bits=pad_bits)[0] is not None:
        return
    H, W = config.rows, config.cols
    halo = _halo_rows(gens, config.rule.radius)
    auto_bm = _pick_block_rows(H, W, config.rule.radius, gens)
    auto = (auto_bm, _pick_sub_rows(auto_bm, W))
    for bm in PALLAS_BLOCK_SIZES:
        if H % bm or (halo > 8 and bm % halo):
            continue
        if (bm + 2 * halo) * W > (1 << 21):  # _pick_block_rows budget
            continue
        sr = _pick_sub_rows(bm, W)
        if (bm, sr) == auto:
            continue
        yield Candidate({**base, "blocks": [bm, sr]},
                        f"{tag}blocks={bm}x{sr}")
        count += 1
        if count >= MAX_BLOCK_CANDIDATES:
            return


def candidates(config: GolConfig, mesh_shape: Tuple[int, int],
               include_batch: bool = False) -> List[Candidate]:
    """The ordered candidate list for one requested config: the default
    plan first (the incumbent), then every feasible single-knob and
    paired variant.  Knob values already pinned by the request are not
    re-searched (a user asking for ``comm_every=4`` keeps it)."""
    out: List[Candidate] = [Candidate()]
    if config.backend != "tpu":
        return out
    deepest_k = None
    if config.comm_every == 1:
        for k in COMM_EVERY_CANDIDATES:
            plan = {"comm_every": k}
            if _feasible(config, mesh_shape, plan):
                out.append(Candidate(plan, f"comm_every={k}"))
                deepest_k = k
    if config.sparse_tile == 0 and mesh_shape == (1, 1):
        for T in SPARSE_TILE_CANDIDATES:
            plan = {"sparse_tile": T}
            if _feasible(config, mesh_shape, plan):
                out.append(Candidate(plan, f"sparse_tile={T}"))
    out.extend(_block_candidates(config, mesh_shape))
    if deepest_k is not None:
        # the (k, blocks) plane of the fused temporal-blocking kernels:
        # block shapes re-validated at the deepest feasible depth (VMEM
        # budgets shrink with gens, so depth-1 winners can be infeasible
        # there and vice versa)
        out.extend(
            c for c in _block_candidates(config, mesh_shape, gens=deepest_k)
            if _feasible(config, mesh_shape, c.plan)
        )
    if include_batch:
        for B in (2, 4, 8):
            out.append(Candidate({"batch": B}, f"batch={B}"))
    return out
