"""Candidate plan generation for the autotuner.

The search space is the deep-halo / fused-generation trade (ROADMAP
item 3) over the knobs that already exist:

* ``comm_every`` k — generations per halo exchange / temporal-blocking
  depth (ghost ring widens to k·r; ``expected_slab_depths`` encodes the
  contract the ir-collective check verifies);
* ``sparse_tile`` T — the activity-gated engine's dirty-tile size;
* ``blocks`` (BM, CM) — the fused Pallas SWAR kernel's DMA-slab /
  compute-tile rows (single-device packed TPU runs only);
* ``batch`` B — a serving hint for the microbatcher, probed but never
  applied to the solo program.

Feasibility is judged by the SAME validation the production path runs
(:func:`mpi_tpu.config.apply_plan` → ``GolConfig.__post_init__`` →
``validate_mesh``): the space enumerates, config rules decide.  The
default plan is always candidate 0 — it is the incumbent every bound is
measured against, and the parity oracle every winner must match
bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Tuple

from mpi_tpu.config import ConfigError, GolConfig, apply_plan, validate_mesh

COMM_EVERY_CANDIDATES = (2, 4, 8)
SPARSE_TILE_CANDIDATES = (32, 64, 128, 256)


@dataclass(frozen=True)
class Candidate:
    """One point of the plan space: the override dict (empty = the
    default plan) plus a display label."""

    plan: dict = field(default_factory=dict)
    label: str = "default"

    @property
    def is_default(self) -> bool:
        return not self.plan

    @property
    def data_dependent(self) -> bool:
        """Whether this candidate's runtime cost depends on board
        content (the sparse engine's dirty map): such candidates are
        never pruned by the static ops bound — tracing counts both
        branches of the gate, so the bound would be meaningless."""
        return bool(self.plan.get("sparse_tile"))


def _feasible(config: GolConfig, mesh_shape: Tuple[int, int],
              plan: dict) -> bool:
    try:
        tuned = apply_plan(config, plan)
        validate_mesh(tuned.rows, tuned.cols, tuple(mesh_shape),
                      tuned.rule.radius * tuned.comm_every)
    except ConfigError:
        return False
    return True


def _block_candidates(config: GolConfig,
                      mesh_shape: Tuple[int, int]) -> Iterator[Candidate]:
    """Pallas block-shape overrides — only where the fused SWAR kernel
    actually serves the plan (single device, radius 1, supported shape,
    real TPU lowering): elsewhere the override is dead weight."""
    if mesh_shape != (1, 1) or config.rule.radius != 1:
        return
    from mpi_tpu.backends.tpu import _pallas_single_device_mode
    from mpi_tpu.ops.pallas_bitlife import _pick_blocks, supports

    use, interpret = _pallas_single_device_mode()
    if not use or interpret:
        return
    gens = config.comm_every
    if not supports((config.rows, config.cols), config.rule, gens=gens):
        return
    H, NW = config.rows, config.cols // 32
    picked = _pick_blocks(H, NW, gens)
    if picked is None:
        return
    BM, _ = picked
    seen = {BM}
    for bm in (BM // 2, BM * 2):
        if bm and bm not in seen and H % bm == 0:
            seen.add(bm)
            yield Candidate({"blocks": [bm, min(bm, 8)]},
                            f"blocks={bm}x{min(bm, 8)}")


def candidates(config: GolConfig, mesh_shape: Tuple[int, int],
               include_batch: bool = False) -> List[Candidate]:
    """The ordered candidate list for one requested config: the default
    plan first (the incumbent), then every feasible single-knob and
    paired variant.  Knob values already pinned by the request are not
    re-searched (a user asking for ``comm_every=4`` keeps it)."""
    out: List[Candidate] = [Candidate()]
    if config.backend != "tpu":
        return out
    if config.comm_every == 1:
        for k in COMM_EVERY_CANDIDATES:
            plan = {"comm_every": k}
            if _feasible(config, mesh_shape, plan):
                out.append(Candidate(plan, f"comm_every={k}"))
    if config.sparse_tile == 0 and mesh_shape == (1, 1):
        for T in SPARSE_TILE_CANDIDATES:
            plan = {"sparse_tile": T}
            if _feasible(config, mesh_shape, plan):
                out.append(Candidate(plan, f"sparse_tile={T}"))
    out.extend(_block_candidates(config, mesh_shape))
    if include_batch:
        for B in (2, 4, 8):
            out.append(Candidate({"batch": B}, f"batch={B}"))
    return out
