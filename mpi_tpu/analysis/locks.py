"""``lock-discipline`` — shared attributes only under their lock.

The PR-2 bug class: session fields read outside ``session.lock`` tear
(generation from one step, grid from another).  The serve layer's
sharing contract lives in ``MANIFEST`` below — a per-class map of
*guarded attribute -> lock attribute* plus the alias names other
modules use for instances.  The rule flags:

* ``self.<attr>`` inside the owning class (``__init__`` exempt — the
  object is not yet shared) outside a guard region for the declared
  lock;
* ``<alias>.<attr>`` / ``<x>.<alias>.<attr>`` chains in the serve
  modules outside a guard on the *same base* (``with e.session.lock:``
  guards ``e.session.grid``, not ``other.grid``);
* a loop that acquires ``.lock`` on its elements without first sorting
  the iterable by ``.id`` (the PR-2 deadlock-freedom pattern:
  ``ordered.sort(key=lambda e: e.session.id)`` before the acquire
  loop);
* taking a session lock while already holding the dispatcher ``_cv``
  (the documented order is ``session.lock -> _cv``, never reversed).

Guard regions are ``with <base>.<lockattr>:`` blocks plus lexical
``<base>.<lockattr>.acquire()`` ... ``.release()`` intervals.  A
``Condition`` guards like a lock (``with self._cv:``).

New serve-layer shared attributes MUST be added here (see
MIGRATION.md); the fixture corpus pins the detection behavior.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mpi_tpu.analysis import Finding, Rule, SourceFile

RULE_NAME = "lock-discipline"


@dataclass(frozen=True)
class ClassSpec:
    """Sharing contract for one class: ``guarded`` maps attribute name
    to the lock attribute that must be held; ``aliases`` are the
    variable names other modules use for instances; ``any_base`` means
    the lock lives on another object (e.g. Ticket fields are guarded by
    the *dispatcher's* ``_cv``), so any held guard with that lock name
    counts."""

    guarded: Dict[str, str]
    aliases: Set[str] = field(default_factory=set)
    any_base: bool = False


MANIFEST: Dict[str, ClassSpec] = {
    # the torn-read quartet minus scrape-only fields: grid+generation
    # must move together, closed gates every mutation
    "Session": ClassSpec(
        guarded={"grid": "lock", "generation": "lock", "closed": "lock"},
        aliases={"session", "sess", "s"},
    ),
    "SessionManager": ClassSpec(
        guarded={"_sessions": "_lock", "_next": "_lock",
                 "_step_listeners": "_listeners_lock"},
    ),
    "MicroBatcher": ClassSpec(
        guarded={"_queues": "_lock"},
    ),
    "AsyncDispatcher": ClassSpec(
        guarded={"_inbox": "_cv", "_per_session": "_cv", "_tickets": "_cv",
                 "_done_order": "_cv", "_completed_by_sid": "_cv"},
    ),
    # Ticket state flips under the owning dispatcher's _cv — the lock
    # is on another object, so any held _cv guard satisfies the rule
    "Ticket": ClassSpec(
        guarded={"status": "_cv", "result": "_cv", "error": "_cv",
                 "callbacks": "_cv"},
        aliases={"ticket", "t"},
        any_base=True,
    ),
    "EngineCache": ClassSpec(
        guarded={"_entries": "_lock", "_batched": "_lock",
                 "_breakers": "_lock"},
    ),
    "AioServer": ClassSpec(
        guarded={"_actions": "_actions_lock"},
    ),
}

# alias-based checks only fire where the serve objects actually travel;
# elsewhere a stray `s.grid` is some other s
ALIAS_MODULES = (
    "mpi_tpu/serve/session.py", "mpi_tpu/serve/ticket.py",
    "mpi_tpu/serve/batch.py", "mpi_tpu/serve/cache.py",
    "mpi_tpu/serve/aio.py", "mpi_tpu/serve/transport.py",
)

_LOCK_ATTRS = {ln for spec in MANIFEST.values() for ln in spec.guarded.values()}


def _dump(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


@dataclass
class _Guard:
    start: int
    end: int
    base: str       # unparsed base expr: "self", "e.session", ...
    lock: str       # lock attribute name: "lock", "_cv", ...


def _guards_in(fn: ast.AST, nodes: Optional[List[ast.AST]] = None) \
        -> List[_Guard]:
    guards: List[_Guard] = []
    acquires: List[Tuple[int, str, str]] = []   # (line, base, lock)
    for node in (ast.walk(fn) if nodes is None else nodes):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Attribute) and ce.attr in _LOCK_ATTRS:
                    guards.append(_Guard(node.lineno,
                                         node.end_lineno or node.lineno,
                                         _dump(ce.value), ce.attr))
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            tgt = node.func.value
            if meth in ("acquire", "release") \
                    and isinstance(tgt, ast.Attribute) \
                    and tgt.attr in _LOCK_ATTRS:
                base, lock = _dump(tgt.value), tgt.attr
                if meth == "acquire":
                    acquires.append((node.lineno, base, lock))
                else:
                    for i, (ln, b, l) in enumerate(acquires):
                        if b == base and l == lock:
                            guards.append(_Guard(ln, node.lineno, base, lock))
                            acquires.pop(i)
                            break
    # unmatched acquire (released elsewhere / in a helper): guard to
    # end of function — conservative toward fewer false positives
    end = fn.end_lineno or fn.lineno
    for ln, base, lock in acquires:
        guards.append(_Guard(ln, end, base, lock))
    return guards


def _held(guards: Sequence[_Guard], line: int, base: str, lock: str,
          any_base: bool) -> bool:
    for g in guards:
        if g.start <= line <= g.end and g.lock == lock \
                and (any_base or g.base == base):
            return True
    return False


def _iter_method_scopes(sf: SourceFile):
    """(class_name_or_None, function_node) for every def in the file."""
    def rec(node: ast.AST, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from rec(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from rec(child, cls)
            else:
                yield from rec(child, cls)
    yield from rec(sf.tree, None)


def _check_attr_accesses(sf: SourceFile, scopes: List[tuple]) -> List[Finding]:
    findings: List[Finding] = []
    defined_here = {n.name for n in ast.walk(sf.tree)
                    if isinstance(n, ast.ClassDef)}
    alias_ok = sf.rel in ALIAS_MODULES or "lint_fixtures" in sf.rel

    for cls, fn, nodes, guards in scopes:
        if fn.name == "__init__":
            continue
        for node in nodes:
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            base = node.value
            # self.<attr> inside the owning class
            if isinstance(base, ast.Name) and base.id == "self" \
                    and cls in MANIFEST and cls in defined_here \
                    and attr in MANIFEST[cls].guarded:
                spec = MANIFEST[cls]
                lock = spec.guarded[attr]
                if not _held(guards, node.lineno, "self", lock, spec.any_base):
                    findings.append(sf.finding(
                        RULE_NAME, node,
                        f"{cls}.{attr} touched without holding "
                        f"self.{lock} (declared shared in the lock "
                        f"manifest)"))
                continue
            # <...>.<alias>.<attr> chains in serve modules (the unparse
            # is deferred here — most files and most attributes never
            # reach the alias path, and it dominates the lint budget)
            if not alias_ok:
                continue
            base_d = _dump(base)
            tail = base_d.rsplit(".", 1)[-1]
            for cname, spec in MANIFEST.items():
                if tail in spec.aliases and attr in spec.guarded:
                    lock = spec.guarded[attr]
                    if not _held(guards, node.lineno, base_d, lock,
                                 spec.any_base):
                        findings.append(sf.finding(
                            RULE_NAME, node,
                            f"{base_d}.{attr} ({cname}.{attr}) touched "
                            f"without holding {base_d}.{lock}" if not
                            spec.any_base else
                            f"{base_d}.{attr} ({cname}.{attr}) touched "
                            f"without holding the dispatcher {lock}"))
                    break
    return findings


def _check_multi_lock(sf: SourceFile, scopes: List[tuple]) -> List[Finding]:
    """Acquire loops must sort by .id first; no session lock under _cv."""
    findings: List[Finding] = []
    for _cls, fn, nodes, fn_guards in scopes:
        # (a) for-loop acquiring .lock on elements of an iterable
        sorted_names: Set[str] = set()
        for node in nodes:
            # name.sort(key=...".id"...) or name = sorted(..., key=...".id"...)
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "sort" \
                        and isinstance(node.func.value, ast.Name) \
                        and any(kw.arg == "key" and ".id" in _dump(kw.value)
                                for kw in node.keywords):
                    sorted_names.add(node.func.value.id)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                c = node.value
                if isinstance(c.func, ast.Name) and c.func.id == "sorted" \
                        and any(kw.arg == "key" and ".id" in _dump(kw.value)
                                for kw in c.keywords):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            sorted_names.add(t.id)
        for node in nodes:
            if not isinstance(node, ast.For):
                continue
            acquires_locks = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "acquire"
                and isinstance(c.func.value, ast.Attribute)
                and c.func.value.attr == "lock"
                for c in ast.walk(node))
            if not acquires_locks:
                continue
            it = node.iter
            if isinstance(it, ast.Name) and it.id in sorted_names:
                continue
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                    and it.func.id == "sorted" \
                    and any(kw.arg == "key" and ".id" in _dump(kw.value)
                            for kw in it.keywords):
                continue
            findings.append(sf.finding(
                RULE_NAME, node,
                "loop acquires per-element .lock without an id-ordered "
                "sort of the iterable first (deadlock hazard; sort by "
                ".id as in MicroBatcher._run_chunk)"))
        # (b) session lock taken while holding _cv: lock order is
        # session.lock -> _cv, never reversed
        cv_guards = [g for g in fn_guards if g.lock == "_cv"]
        for node in nodes:
            grabbing = None
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Attribute) and ce.attr == "lock":
                        grabbing = node
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire" \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr == "lock":
                grabbing = node
            if grabbing is None:
                continue
            for g in cv_guards:
                # strictly inside the _cv region (not the same statement)
                if g.start < grabbing.lineno <= g.end:
                    findings.append(sf.finding(
                        RULE_NAME, grabbing,
                        "session lock acquired while holding _cv — the "
                        "documented order is session.lock -> _cv, never "
                        "reversed"))
                    break
    return findings


def check(sf: SourceFile) -> List[Finding]:
    # one walk + one guard scan per scope, shared by both checkers —
    # re-walking every def for every sub-check dominated the lint budget
    scopes = []
    for cls, fn in _iter_method_scopes(sf):
        nodes = list(ast.walk(fn))
        scopes.append((cls, fn, nodes, _guards_in(fn, nodes)))
    return _check_attr_accesses(sf, scopes) + _check_multi_lock(sf, scopes)


RULE = Rule(
    name=RULE_NAME,
    doc="manifest-declared shared attributes only under their lock; "
        "multi-lock loops id-ordered; never session.lock under _cv",
    file_check=check,
)
