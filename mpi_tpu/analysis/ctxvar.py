"""``ctxvar-hop`` — no rid-reading code launched on a bare thread hop.

The request id travels in a ``ContextVar`` (``obs.trace.REQUEST_ID``);
``Thread(target=...)`` and ``executor.submit(...)`` start the callee in
an EMPTY context, so a callee that reads the rid gets ``None`` and its
spans/metrics silently detach from the request.  PR 4/5 fixed this two
ways, both of which this rule recognizes as safe:

* wrapping the hop with ``contextvars.copy_context()`` and launching
  ``ctx.run(...)`` (the watchdog pattern in ``serve/session.py``);
* stashing the rid eagerly and re-installing it in the callee with
  ``set_request_id(...)`` (the ``Ticket.rid`` / ``_Entry.rid``
  pattern in ``serve/ticket.py`` / ``serve/batch.py``).

Detection: for every ``X.submit(f, ...)`` / ``Thread(target=f)`` site,
resolve ``f`` to a same-module def/lambda/method by simple name.  If
the callee (transitively, intra-module) reads the contextvar —
``current_request_id()`` or ``REQUEST_ID.get()`` — and neither the
launch site's function mentions ``copy_context`` nor the callee chain
re-installs with ``set_request_id``, that hop drops the rid: finding.
Unresolvable callees (cross-module attributes) are out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from mpi_tpu.analysis import Finding, Rule, SourceFile

RULE_NAME = "ctxvar-hop"

_READS = ("current_request_id", "REQUEST_ID.get")
_RESTORES = ("set_request_id",)


def _dump(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


def _fn_index(tree: ast.AST) -> Dict[str, ast.AST]:
    """Simple-name index of every def (methods included, unqualified —
    launch sites resolve ``self.f`` and plain ``f`` alike)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """Fold a Name/Attribute chain to "a.b.c" without ast.unparse —
    the hot path (every Call in the tree goes through here); non-chain
    expressions (calls, subscripts) return None and never match."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _mentions(fn: ast.AST, needles) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None \
                    and any(d == n or d.endswith("." + n) for n in needles):
                return True
        elif isinstance(node, ast.Name) and node.id in needles:
            return True
    return False


def _reads_rid(fn: ast.AST, index: Dict[str, ast.AST],
               seen: Optional[Set[int]] = None) -> bool:
    """True if fn (or a same-module callee) reads the rid contextvar
    WITHOUT re-installing it first (set_request_id in the chain means
    the caller stashed the rid eagerly — the safe explicit pattern)."""
    seen = seen if seen is not None else set()
    if id(fn) in seen:
        return False
    seen.add(id(fn))
    if _mentions(fn, _RESTORES):
        return False
    if _mentions(fn, _READS):
        return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            callee = index.get(node.func.id)
            if callee is not None and _reads_rid(callee, index, seen):
                return True
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            callee = index.get(node.func.attr)
            if callee is not None and _reads_rid(callee, index, seen):
                return True
    return False


def _resolve_callee(arg: ast.AST, index: Dict[str, ast.AST],
                    local_lambdas: Dict[str, ast.Lambda]) -> Optional[ast.AST]:
    if isinstance(arg, ast.Name):
        if arg.id in local_lambdas:
            return local_lambdas[arg.id]
        return index.get(arg.id)
    if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name) \
            and arg.value.id == "self":
        return index.get(arg.attr)
    if isinstance(arg, ast.Lambda):
        return arg
    return None


def _hop_sites(fn: ast.AST):
    """(call_node, callee_expr) for every thread/executor hop in fn."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "submit":
            if node.args:
                yield node, node.args[0]
        elif (isinstance(node.func, ast.Name) and node.func.id == "Thread") \
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "threading"):
            for kw in node.keywords:
                if kw.arg == "target":
                    yield node, kw.value


def check(sf: SourceFile) -> List[Finding]:
    index = _fn_index(sf.tree)
    findings: List[Finding] = []
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sites = list(_hop_sites(fn))
        if not sites:
            continue                    # hop-free fn: skip the scans
        # copy_context anywhere in the launching function blesses its
        # hops: the watchdog builds ctx once and runs everything in it
        launcher_wraps = _mentions(fn, ("copy_context", "ctx.run"))
        local_lambdas: Dict[str, ast.Lambda] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Lambda):
                local_lambdas[node.targets[0].id] = node.value
        for call, callee_expr in sites:
            if launcher_wraps:
                continue
            callee = _resolve_callee(callee_expr, index, local_lambdas)
            if callee is None:
                continue
            if _reads_rid(callee, index):
                findings.append(sf.finding(
                    RULE_NAME, call,
                    f"thread hop launches '{_dump(callee_expr)}', which "
                    f"reads the rid contextvar — wrap with "
                    f"copy_context() or stash the rid and "
                    f"set_request_id() in the callee"))
    return findings


RULE = Rule(
    name=RULE_NAME,
    doc="Thread/submit hops into rid-reading code must copy_context or "
        "stash-and-set_request_id",
    file_check=check,
)
