"""CLI for the IR-level contract verifier.

::

    python -m mpi_tpu.analysis.ir                   # full matrix
    python -m mpi_tpu.analysis.ir --fast            # tier-1 subset
    python -m mpi_tpu.analysis.ir --cell seam_1x1   # one cell (repeatable)
    python -m mpi_tpu.analysis.ir --write-baseline  # bless current IR
    python -m mpi_tpu.analysis.ir --format json     # machine-readable
    python -m mpi_tpu.analysis.ir --list-cells

Exit codes match ``python -m mpi_tpu.analysis``: 0 clean, 1 any finding,
2 internal error (a cell failed to trace) — a broken verifier must never
read as a passing one.
"""

from __future__ import annotations

import argparse
import json
import sys

from mpi_tpu.analysis.ir import force_cpu_mesh, run_ir, write_baseline


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_tpu.analysis.ir",
        description="jaxpr-level contract verifier (donation aliasing, "
                    "collective validity, IR purity, plan_signature "
                    "soundness, IR drift)")
    parser.add_argument("--fast", action="store_true",
                        help="trace only the tier-1 fast subset of the "
                             "matrix")
    parser.add_argument("--cell", action="append", default=None,
                        metavar="ID", help="trace only this matrix cell "
                                           "(repeatable; see --list-cells)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="bless the current canonical fingerprints as "
                             "analysis/ir/baseline.json")
    parser.add_argument("--no-baseline", action="store_true",
                        help="skip the drift check")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human",
                        help="diagnostic format (default: human)")
    parser.add_argument("--list-cells", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no summary line")
    args = parser.parse_args(argv)

    if args.list_cells:
        from mpi_tpu.analysis.ir.matrix import CELLS

        for c in CELLS:
            batched = f" B={c.batch}" if c.batch else ""
            print(f"{c.id:24s} [{c.tier:4s}] {c.rows}x{c.cols} "
                  f"rule={c.rule} boundary={c.boundary} mesh={c.mesh} "
                  f"K={c.comm_every} sparse={c.sparse_tile} "
                  f"depth={c.depth}{batched}")
        return 0

    force_cpu_mesh()
    try:
        report = run_ir(
            fast_only=args.fast, cell_ids=args.cell,
            # a baseline run judges the *other* checks first; drift
            # against the stale baseline would be pure noise
            use_baseline=not (args.no_baseline or args.write_baseline))
    except KeyError as e:   # unknown --cell id
        print(str(e.args[0]) if e.args else str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        if report.errors:
            for e in report.errors:
                print(f"error: {e}", file=sys.stderr)
            print("refusing to write a baseline from a partial trace",
                  file=sys.stderr)
            return 2
        out = write_baseline(report.traced)
        print(f"wrote {len(report.traced)} cell fingerprint(s) to {out}")
        return 0

    if args.format == "json":
        json.dump(report.to_json(), sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        for f in report.findings:
            print(f.format())
        for e in report.errors:
            print(f"error: {e}", file=sys.stderr)
        if not args.quiet:
            print(f"{len(report.findings)} finding(s) over "
                  f"{len(report.traced)} traced cell(s)", file=sys.stderr)
    if report.errors:
        return 2
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
