"""Abstract tracing harness: matrix cell -> TracedCell facts.

Builds the real engine (``backends.tpu.build_engine`` — the exact
dispatch the serve stack uses), then extracts the verifier's facts with
NO device execution beyond the tiny ``init_grid`` placement:

* ``jax.make_jaxpr`` over the engine's evolve at the cell's depth — the
  canonical jaxpr, primitive set, and ppermute records (via
  :mod:`.canon`);
* ``evolve.lower(...)`` — the StableHLO text whose donor/aliasing
  markers say whether XLA was *actually* offered the input buffer
  (``args_info`` says what jit requested; the IR markers say what got
  lowered — the PR-3 class lives in the gap between intent and IR);
* ``plan_signature`` — the EngineCache key the soundness check judges.

The dispatch is pinned to the CPU/XLA path (``MPI_TPU_PALLAS_INTERPRET``
forced off for the duration) so fingerprints cannot depend on ambient
test-environment flags.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import List, Optional, Set

import jax

from mpi_tpu.analysis.ir.canon import CanonResult, CollectiveRecord, canonicalize
from mpi_tpu.analysis.ir.matrix import Cell
from mpi_tpu.config import GolConfig, plan_signature

# markers jax 0.4.x lowers donated/aliased buffers with (which one
# appears depends on program structure; either means XLA got the buffer)
DONOR_MARKERS = ("jax.buffer_donor", "tf.aliasing_output")


class HarnessError(RuntimeError):
    """A cell could not be traced (missing devices, engine build failed)
    — surfaced as a runner internal error (exit 2), never a silent pass."""


@dataclass
class TracedCell:
    """Everything the checks consume about one traced cell."""

    cell: Cell
    config: GolConfig
    engine: object
    signature: tuple
    canon: CanonResult
    donates_expected: bool
    donor_in_ir: bool
    args_donated: bool

    @property
    def fingerprint(self) -> str:
        return self.canon.fingerprint

    @property
    def prim_names(self) -> Set[str]:
        return self.canon.prim_names

    @property
    def collectives(self) -> List[CollectiveRecord]:
        return self.canon.collectives

    @property
    def group_key(self) -> tuple:
        """The executable-collision unit: the serve layer memoizes one
        engine per signature and one executable per (depth, B) inside
        it, so two traces may only be required to agree when signature,
        depth AND batch width all match."""
        return (self.signature, self.cell.depth, self.cell.batch)


@contextlib.contextmanager
def _pinned_dispatch():
    """Pin the engine dispatch to the plain XLA path for the trace:
    interpret-mode Pallas (a test-only env escape hatch) must not leak
    into baseline fingerprints."""
    old = os.environ.get("MPI_TPU_PALLAS_INTERPRET")
    os.environ["MPI_TPU_PALLAS_INTERPRET"] = "0"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("MPI_TPU_PALLAS_INTERPRET", None)
        else:
            os.environ["MPI_TPU_PALLAS_INTERPRET"] = old


def trace_engine(cell: Cell, engine, evolve, grid) -> TracedCell:
    """The fact-extraction half of :func:`trace_cell`, split out so
    tests can seed a *tampered* evolve (e.g. a donation re-enable on a
    seam engine) against the real engine contract."""
    closed = jax.make_jaxpr(lambda g: evolve(g, cell.depth))(grid)
    canon = canonicalize(closed)
    lowered = evolve.lower(grid, cell.depth)
    text = lowered.as_text()
    donor_in_ir = any(m in text for m in DONOR_MARKERS)
    args_donated = any(
        bool(getattr(a, "donated", False))
        for a in jax.tree_util.tree_leaves(lowered.args_info))
    mi, mj = engine.mi, engine.mj
    return TracedCell(
        cell=cell, config=engine.config, engine=engine,
        signature=plan_signature(engine.config, (mi, mj)),
        canon=canon,
        donates_expected=engine.donates_input,
        donor_in_ir=donor_in_ir, args_donated=args_donated,
    )


def trace_cell(cell: Cell) -> TracedCell:
    """Build the cell's engine and trace its stepper abstractly."""
    if cell.devices_needed > len(jax.devices()):
        raise HarnessError(
            f"cell {cell.id}: mesh {cell.mesh} needs {cell.devices_needed} "
            f"devices, have {len(jax.devices())} (run via "
            f"`python -m mpi_tpu.analysis.ir`, which forces the virtual "
            f"CPU mesh)")
    with _pinned_dispatch():
        from mpi_tpu.backends.tpu import build_engine

        try:
            config = cell.make_config()
            engine = build_engine(config)
            if cell.batch > 0:
                boards = [engine.init_grid(seed=cell.seed + i)
                          for i in range(cell.batch)]
                grid = engine.stack_grids(boards)
                evolve = engine._get_batched_evolve()
            else:
                grid = engine.init_grid()
                evolve = engine._evolve
            return trace_engine(cell, engine, evolve, grid)
        except HarnessError:
            raise
        except Exception as e:
            raise HarnessError(
                f"cell {cell.id}: {type(e).__name__}: {e}") from e
