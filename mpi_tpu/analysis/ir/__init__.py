"""``mpi_tpu.analysis.ir`` — jaxpr-level contract verifier.

The AST suite (:mod:`mpi_tpu.analysis`) judges *syntax*; the bug classes
this repo actually shipped (the PR-3 seam donation race, EngineCache
keying subtleties) live in the *traced program*.  This package traces
engine-built steppers abstractly — ``jax.make_jaxpr`` + ``.lower()``,
no device execution — over a config matrix (:mod:`.matrix`) and holds
the IR to five contracts (:mod:`.checks`):

* ``ir-donation``   — seam-stitched programs carry NO input/output
  aliasing; every other stepper MUST (both directions of the PR-3 class,
  read off the lowered IR's donor markers rather than the source).
* ``ir-collective`` — every ``ppermute`` is a (partial) bijection over
  its named mesh axis, closes the full ring on periodic boundaries, and
  ships slabs exactly one halo depth thick
  (:func:`mpi_tpu.parallel.halo.expected_slab_depths`).
* ``ir-purity``     — no callback/debug/io primitives reachable in a
  production stepper's trace.
* ``ir-signature``  — ``plan_signature`` soundness both ways: equal
  (signature, depth, B) ⇒ identical canonical jaxprs; matrix near-pairs
  differing in one signature-visible field ⇒ different signatures.
* ``ir-drift``      — canonical jaxpr fingerprints (:mod:`.canon`) per
  matrix cell against the checked-in ``baseline.json``; bless
  intentional changes with ``--write-baseline``.

Runner: ``python -m mpi_tpu.analysis.ir`` (exit 0 clean / 1 findings /
2 internal error, same contract as ``python -m mpi_tpu.analysis``).
``--fast`` runs the tier-1 subset; ``tests/test_ir_verify.py`` runs the
same subset inside tier-1.

Unlike the AST suite there are no inline suppressions here — a traced
program has no comment to hang one on.  The only accepted override is
the baseline (for drift) and fixing the engine (for everything else).

This module deliberately defers every jax import into function bodies:
``python -m mpi_tpu.analysis.ir`` must pin ``JAX_PLATFORMS=cpu`` and the
8-device virtual mesh *before* jax initializes, and ``python -m`` imports
this package ahead of ``__main__``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = [
    "BASELINE_PATH", "IRReport", "force_cpu_mesh", "load_baseline",
    "run_ir", "write_baseline",
]

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def force_cpu_mesh() -> None:
    """Pin jax to the 8-device virtual CPU mesh the matrix traces on.

    Must run before jax initializes a backend (the ambient axon
    sitecustomize pins ``jax_platforms`` to the real TPU at interpreter
    start, so the config update is needed on top of the env vars —
    same dance as tests/conftest.py)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# -- baseline -------------------------------------------------------------

def load_baseline(path: Optional[str] = None) -> Dict[str, dict]:
    """cell_id -> {"fingerprint": ...} from the checked-in baseline."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("cells", {})


def write_baseline(traced, path: Optional[str] = None) -> str:
    """Bless the traced cells' canonical fingerprints as the baseline."""
    path = path or BASELINE_PATH
    cells = {
        tc.cell.id: {"fingerprint": tc.fingerprint, "tier": tc.cell.tier}
        for tc in sorted(traced, key=lambda tc: tc.cell.id)
    }
    payload = {
        "comment": "Canonical jaxpr fingerprints per matrix cell "
                   "(mpi_tpu/analysis/ir/canon.py). Regenerate with "
                   "`python -m mpi_tpu.analysis.ir --write-baseline` "
                   "and justify the drift in the commit message.",
        "cells": cells,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# -- runner ---------------------------------------------------------------

@dataclass
class IRReport:
    findings: List = field(default_factory=list)   # List[IRFinding]
    errors: List[str] = field(default_factory=list)
    traced: List = field(default_factory=list)     # List[TracedCell]
    complete: bool = False   # full matrix (drift may judge staleness)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def to_json(self) -> dict:
        return {
            "tool": "mpi_tpu.analysis.ir",
            "findings": [
                {"check": f.check, "cell": f.cell, "message": f.message,
                 "fingerprint": f.fingerprint()}
                for f in self.findings
            ],
            "errors": list(self.errors),
            "summary": {
                "cells_traced": len(self.traced),
                "findings": len(self.findings),
                "errors": len(self.errors),
                "complete_matrix": self.complete,
            },
            "cells": {
                tc.cell.id: tc.fingerprint for tc in self.traced
            },
        }


def run_ir(fast_only: bool = False,
           cell_ids: Optional[Sequence[str]] = None,
           use_baseline: bool = True,
           baseline_path: Optional[str] = None,
           signature_fn=None) -> IRReport:
    """Trace the selected matrix cells and run every IR check.

    ``signature_fn`` overrides the plan-signature keying for the
    soundness check — the seeded-collision tests inject one with a field
    dropped and pin the resulting diagnostic."""
    from mpi_tpu.analysis.ir import checks
    from mpi_tpu.analysis.ir.harness import HarnessError, trace_cell
    from mpi_tpu.analysis.ir.matrix import cell_by_id, cells

    if cell_ids:
        selected = [cell_by_id(c) for c in cell_ids]
    else:
        selected = cells(fast_only=fast_only)

    report = IRReport(complete=not fast_only and not cell_ids)
    for cell in selected:
        try:
            report.traced.append(trace_cell(cell))
        except HarnessError as e:
            report.errors.append(str(e))

    for tc in report.traced:
        report.findings.extend(checks.check_donation(tc))
        report.findings.extend(checks.check_collectives(tc))
        report.findings.extend(checks.check_purity(tc))
    report.findings.extend(
        checks.check_signatures(report.traced, signature_fn=signature_fn))
    if use_baseline:
        report.findings.extend(checks.check_drift(
            report.traced, load_baseline(baseline_path),
            complete=report.complete))
    return report
