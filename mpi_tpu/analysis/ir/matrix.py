"""The config matrix the IR verifier traces.

Each :class:`Cell` pins one (engine family x boundary x mesh x
dense/sparse x solo/batched x depth) point; the harness builds the
engine off-device, traces its stepper abstractly, and the checks judge
the resulting jaxpr/lowering.  The ``fast`` tier is the tier-1 subset
(every engine family once, a few seconds total on the 1-core CPU box);
the ``full`` tier is the default for ``python -m mpi_tpu.analysis.ir``
and the checked-in drift baseline covers it.

Cells are traced on the **CPU dispatch path** (``JAX_PLATFORMS=cpu``,
Pallas interpret pinned off) — the path the serve stack actually
compiles on this box, and the only one whose fingerprints are
reproducible everywhere the gate runs.

``TWINS`` are cell pairs that differ only in a field ``plan_signature``
deliberately EXCLUDES (seed): their signatures must collide and their
traces must be identical — the cache-sharing contract, and a canary for
canonicalization instability.  ``NEAR_PAIRS`` differ in exactly one
field the signature must SEE: their signatures must differ (a signature
blind to the field would hand one config the other's executable), and —
when depth/batch agree — so must their canonical jaxprs (an inert pair
would mean the matrix stopped exercising that field).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from mpi_tpu.config import GolConfig
from mpi_tpu.models.rules import rule_from_name


@dataclass(frozen=True)
class Cell:
    """One matrix point.  ``batch`` = 0 traces the solo stepper;
    B > 0 traces the vmapped ``[B, ...]`` batched stepper.  ``depth``
    is the static step count handed to the traced evolve."""

    id: str
    rows: int
    cols: int
    rule: str = "life"
    boundary: str = "periodic"
    mesh: Tuple[int, int] = (1, 1)
    comm_every: int = 1
    sparse_tile: int = 0
    overlap: bool = False
    depth: int = 1
    batch: int = 0
    seed: int = 0
    tier: str = "full"            # "fast" cells also run in tier-1
    twin_of: Optional[str] = None  # seed-only twin (signature must match)

    def make_config(self) -> GolConfig:
        return GolConfig(
            rows=self.rows, cols=self.cols, steps=0, seed=self.seed,
            rule=rule_from_name(self.rule), boundary=self.boundary,
            backend="tpu", mesh_shape=self.mesh,
            comm_every=self.comm_every, overlap=self.overlap,
            sparse_tile=self.sparse_tile,
        )

    @property
    def devices_needed(self) -> int:
        return self.mesh[0] * self.mesh[1]


# a radius-2 Larger-than-Life rule (the bit-sliced engine's bread and
# butter); bosco (radius 5) lands on the dense stencil engine off-TPU
_R2 = "R2,B8-12,S9-14"

CELLS: List[Cell] = [
    # -- fast tier: every engine family once ----------------------------
    Cell("packed_1x1", 64, 64, depth=2, tier="fast"),
    Cell("packed_1x1_seed7", 64, 64, depth=2, seed=7, tier="fast",
         twin_of="packed_1x1"),
    Cell("packed_1x2_periodic", 64, 64, mesh=(1, 2), depth=2, tier="fast"),
    Cell("packed_1x2_dead", 64, 64, boundary="dead", mesh=(1, 2), depth=2,
         tier="fast"),
    Cell("packed_2x2_dead", 64, 64, boundary="dead", mesh=(2, 2), depth=2,
         tier="fast"),
    Cell("packed_k2_1x2", 64, 64, mesh=(1, 2), comm_every=2, depth=3,
         tier="fast"),
    # radius-2 deep halo: ir-collective holds the widened slab depths
    # {2, 4} the tuner's comm_every>1 winners rely on (ISSUE 11)
    Cell("ltl_r2_k2_1x2", 64, 64, rule=_R2, mesh=(1, 2), comm_every=2,
         depth=3, tier="fast"),
    Cell("seam_1x1", 64, 48, depth=2, tier="fast"),
    Cell("ltl_r2_1x2_dead", 64, 64, rule=_R2, boundary="dead", mesh=(1, 2),
         depth=1, tier="fast"),
    Cell("dense_bosco_1x1", 64, 64, rule="bosco", depth=1, tier="fast"),
    # dense deep-halo + stitched-band overlap (ISSUE 17): K·r = 32
    # exceeds the packed engines' one-ghost-word bound AND the periodic
    # seam gate, so the run genuinely lands on the dense engine; depth 17
    # traces segment depths {16, 1} → slab depths {32, 2}, which
    # ir-collective holds to expected_slab_depths, and the overlap twin
    # pins the halo-compute overlap program (interior from local data
    # while the ppermute is in flight, k·r-deep bands stitched after)
    Cell("dense_r2_k16_overlap_1x2", 64, 160, rule=_R2, mesh=(1, 2),
         comm_every=16, overlap=True, depth=17, tier="fast"),
    Cell("sparse_1x1", 64, 64, sparse_tile=32, depth=2, tier="fast"),
    Cell("batched_packed_1x2", 64, 64, mesh=(1, 2), depth=2, batch=2,
         tier="fast"),
    Cell("batched_seam_1x1", 64, 48, depth=2, batch=2, tier="fast"),
    # -- full tier: the wider sweep -------------------------------------
    Cell("packed_2x2_periodic", 64, 64, mesh=(2, 2), depth=2),
    Cell("packed_2x1_asym", 128, 64, mesh=(2, 1), depth=1),
    Cell("packed_k4_1x2", 64, 64, mesh=(1, 2), comm_every=4, depth=5),
    Cell("packed_w128_1x2", 64, 128, mesh=(1, 2), depth=2),
    Cell("packed_w128_overlap_1x2", 64, 128, mesh=(1, 2), overlap=True,
         depth=2),
    Cell("highlife_1x2", 64, 64, rule="highlife", mesh=(1, 2), depth=2),
    Cell("seam_1x2", 64, 80, mesh=(1, 2), depth=2),
    Cell("dense_r2_k16_1x2", 64, 160, rule=_R2, mesh=(1, 2),
         comm_every=16, depth=17),
    # seam-wrapped overlap: the stitched-band body under the seam
    # stitcher — ir-donation must keep holding the seam no-donate rule
    # on the overlap path
    Cell("seam_overlap_1x2", 64, 80, mesh=(1, 2), overlap=True, depth=2),
    Cell("ltl_r2_2x2_periodic", 64, 64, rule=_R2, mesh=(2, 2), depth=2),
    Cell("dense_bosco_1x1_dead", 64, 64, rule="bosco", boundary="dead",
         depth=1),
    Cell("sparse_ltl_1x1", 64, 64, rule=_R2, sparse_tile=32, depth=1),
    Cell("batched_sparse_1x1", 64, 64, sparse_tile=32, depth=1, batch=2),
    # -- 2-host virtual meshes (PR 12): all 8 virtual devices, the
    # decomposition a 2-host pod slice (2 hosts x 4 chips) would use.
    # The serve cluster proxies REQUESTS between processes; these cells
    # pin the collective program a session spanning the slice compiles
    Cell("packed_2x4_2host", 64, 128, mesh=(2, 4), depth=2, tier="fast"),
    Cell("packed_1x8_2host", 64, 256, mesh=(1, 8), comm_every=2, depth=3),
    Cell("ltl_r2_2x4_2host", 64, 128, rule=_R2, mesh=(2, 4), depth=2),
]

# (cell_a, cell_b, the one signature-visible field they differ in)
NEAR_PAIRS: List[Tuple[str, str, str]] = [
    ("packed_1x2_periodic", "packed_1x2_dead", "boundary"),
    ("packed_1x1", "packed_1x2_periodic", "mesh_shape"),
    ("packed_1x1", "sparse_1x1", "sparse_tile"),
    ("packed_1x2_periodic", "packed_k2_1x2", "comm_every"),
    ("packed_1x2_periodic", "highlife_1x2", "rule"),
    ("packed_2x2_dead", "packed_2x2_periodic", "boundary"),
    ("packed_w128_1x2", "packed_w128_overlap_1x2", "overlap"),
    ("dense_r2_k16_1x2", "dense_r2_k16_overlap_1x2", "overlap"),
    ("seam_1x2", "seam_overlap_1x2", "overlap"),
    # the 2-host shapes must be signature-distinct from each other (a
    # signature blind to the mesh would alias their executables)
    ("packed_2x4_2host", "ltl_r2_2x4_2host", "rule"),
]

_BY_ID = {c.id: c for c in CELLS}
assert len(_BY_ID) == len(CELLS), "duplicate cell ids"


def cell_by_id(cell_id: str) -> Cell:
    try:
        return _BY_ID[cell_id]
    except KeyError:
        raise KeyError(f"unknown matrix cell {cell_id!r} "
                       f"(see --list-cells)") from None


def cells(fast_only: bool = False) -> List[Cell]:
    if fast_only:
        return [c for c in CELLS if c.tier == "fast"]
    return list(CELLS)


def near_pairs(selected: List[Cell]) -> List[Tuple[Cell, Cell, str]]:
    """The NEAR_PAIRS whose both endpoints are in ``selected``."""
    ids = {c.id for c in selected}
    return [(_BY_ID[a], _BY_ID[b], f)
            for a, b, f in NEAR_PAIRS if a in ids and b in ids]
