"""The IR-level invariant checks.

Each check takes traced-cell facts and returns :class:`IRFinding`s.
They are deliberately pure functions over :class:`~.harness.TracedCell`
data (no tracing, no engines) so tests can seed violations — a
fabricated non-bijective permutation, a tampered donating seam stepper,
a signature function with a field dropped — and pin the exact
diagnostics.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mpi_tpu.analysis.ir.harness import TracedCell
from mpi_tpu.analysis.ir.matrix import Cell, near_pairs

# primitives that must never be reachable from a production stepper:
# host round-trips (callbacks), debug effects, and infeed/outfeed would
# all stall or desync the serving hot path and break replay determinism
IMPURE_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "host_callback", "outside_call", "infeed", "outfeed",
})


@dataclass(frozen=True)
class IRFinding:
    """One IR diagnostic: ``cell <id>: [<check>] message``."""

    check: str
    cell: str
    message: str

    def format(self) -> str:
        return f"cell {self.cell}: [{self.check}] {self.message}"

    def fingerprint(self) -> str:
        raw = f"{self.check}:{self.cell}:{self.message}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]


# -- donation-aliasing contracts ------------------------------------------

def check_donation(tc: TracedCell) -> List[IRFinding]:
    """Seam-stitched programs must carry NO input/output donation (the
    band extraction reads the pre-step grid the base step would alias in
    place — the PR-3 race); every other stepper must donate (losing the
    donation silently doubles peak HBM per session)."""
    out: List[IRFinding] = []
    got = tc.donor_in_ir or tc.args_donated
    if not tc.donates_expected and got:
        how = []
        if tc.donor_in_ir:
            how.append("donor/aliasing markers in the lowered IR")
        if tc.args_donated:
            how.append("donated args in args_info")
        out.append(IRFinding(
            "ir-donation", tc.cell.id,
            f"seam-stitched stepper lowered WITH input/output donation "
            f"({', '.join(how)}): the seam band reads the pre-step grid "
            f"— re-enabling donation here reintroduces the PR-3 "
            f"donation race (nondeterministic shard corruption on "
            f"multi-device meshes)"))
    elif tc.donates_expected and not got:
        out.append(IRFinding(
            "ir-donation", tc.cell.id,
            f"stepper expected to donate its input but the lowered IR "
            f"carries no donor/aliasing marker "
            f"({' / '.join(('jax.buffer_donor', 'tf.aliasing_output'))}): "
            f"the donation was silently lost and every step pays a "
            f"second grid buffer"))
    return out


# -- collective validity --------------------------------------------------

def check_collectives(tc: TracedCell) -> List[IRFinding]:
    """Every ``ppermute`` in the trace must be a valid (partial)
    permutation of the named mesh axis — full ring on periodic
    boundaries, injective chain on dead — and its operand slab must be
    exactly one halo depth (rule radius x comm cadence, or the packed
    engines' single ghost word column) thick."""
    from mpi_tpu.parallel.halo import expected_slab_depths
    from mpi_tpu.parallel.mesh import AXES

    out: List[IRFinding] = []
    axis_sizes = {AXES[0]: tc.engine.mi, AXES[1]: tc.engine.mj}
    periodic = tc.config.boundary == "periodic"
    allowed = expected_slab_depths(
        tc.config.rule.radius, tc.config.comm_every, tc.engine.bitpacked)
    for rec in tc.collectives:
        n = axis_sizes.get(rec.axis_name)
        if n is None:
            out.append(IRFinding(
                "ir-collective", tc.cell.id,
                f"ppermute over unknown mesh axis {rec.axis_name!r} "
                f"(mesh axes: {sorted(axis_sizes)})"))
            continue
        srcs = [s for s, _ in rec.perm]
        dsts = [d for _, d in rec.perm]
        bad_range = [p for p in srcs + dsts if not 0 <= p < n]
        if bad_range:
            out.append(IRFinding(
                "ir-collective", tc.cell.id,
                f"ppermute over axis {rec.axis_name!r} (size {n}) names "
                f"out-of-range devices {sorted(set(bad_range))}: "
                f"perm={rec.perm}"))
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            out.append(IRFinding(
                "ir-collective", tc.cell.id,
                f"ppermute permutation over axis {rec.axis_name!r} is "
                f"not a bijection: duplicate "
                f"{'source' if len(set(srcs)) != len(srcs) else 'destination'}"
                f" in perm={rec.perm} (a device would receive two halo "
                f"slabs, or its ghost ring garbage)"))
        elif periodic and len(rec.perm) != n:
            out.append(IRFinding(
                "ir-collective", tc.cell.id,
                f"periodic stepper's ppermute closes only "
                f"{len(rec.perm)} of {n} ring links over axis "
                f"{rec.axis_name!r} (perm={rec.perm}): an edge shard's "
                f"ghosts would arrive as zeros — dead-boundary "
                f"semantics on a periodic run"))
        thin = min(rec.shape) if rec.shape else 0
        if thin not in allowed:
            out.append(IRFinding(
                "ir-collective", tc.cell.id,
                f"halo slab shape {rec.shape} over axis "
                f"{rec.axis_name!r} has depth {thin}, expected one of "
                f"{sorted(allowed)} (rule radius "
                f"{tc.config.rule.radius} x comm_every "
                f"{tc.config.comm_every}"
                f"{', or one ghost word column' if tc.engine.bitpacked else ''})"))
    return out


# -- IR purity ------------------------------------------------------------

def check_purity(tc: TracedCell) -> List[IRFinding]:
    """No callback/debug/io primitives reachable in a production
    stepper's trace (complements the AST ``traced-purity`` rule, which
    sees syntax — this sees what actually got traced)."""
    return [
        IRFinding(
            "ir-purity", tc.cell.id,
            f"traced stepper reaches impure primitive '{p}': host "
            f"round-trips in the hot loop stall the device pipeline and "
            f"break checkpoint-replay determinism")
        for p in sorted(tc.prim_names & IMPURE_PRIMITIVES)
    ]


# -- plan_signature soundness ---------------------------------------------

SignatureFn = Callable[[object, Tuple[int, int]], tuple]


def check_signatures(traced: Sequence[TracedCell],
                     signature_fn: Optional[SignatureFn] = None
                     ) -> List[IRFinding]:
    """Both directions of the EngineCache keying contract.

    Soundness: cells agreeing in (signature, depth, batch) must trace to
    identical canonical jaxprs — a collision means ``EngineCache`` would
    silently serve one config the other's compiled executable.

    Completeness (via the matrix annotations): ``twin_of`` pairs differ
    only in signature-EXCLUDED fields, so their signatures must collide
    (cache sharing is the point) and their traces must match;
    ``NEAR_PAIRS`` differ in exactly one signature-visible field, so
    their signatures must differ — and when depth/batch agree, so must
    their fingerprints (else the pair stopped exercising the field).
    """
    out: List[IRFinding] = []
    if signature_fn is not None:
        def key_of(tc: TracedCell) -> tuple:
            return (signature_fn(tc.config, (tc.engine.mi, tc.engine.mj)),
                    tc.cell.depth, tc.cell.batch)
    else:
        def key_of(tc: TracedCell) -> tuple:
            return tc.group_key

    groups: Dict[tuple, List[TracedCell]] = {}
    for tc in traced:
        groups.setdefault(key_of(tc), []).append(tc)
    for key, members in groups.items():
        fps = {m.fingerprint for m in members}
        if len(fps) > 1:
            ids = ", ".join(sorted(m.cell.id for m in members))
            out.append(IRFinding(
                "ir-signature", sorted(m.cell.id for m in members)[0],
                f"plan_signature collision: cells {ids} share a plan "
                f"signature (at depth {key[1]}, B={key[2]}) but trace "
                f"to different canonical jaxprs (fingerprints "
                f"{sorted(fps)}): EngineCache would return the wrong "
                f"compiled executable for one of them"))

    by_id = {tc.cell.id: tc for tc in traced}
    for tc in traced:
        twin = by_id.get(tc.cell.twin_of) if tc.cell.twin_of else None
        if twin is None:
            continue
        if key_of(tc)[0] != key_of(twin)[0]:
            out.append(IRFinding(
                "ir-signature", tc.cell.id,
                f"cells {tc.cell.id} and {twin.cell.id} differ only in "
                f"signature-excluded fields (seed) but get distinct "
                f"plan signatures: engine sharing across sessions "
                f"regressed"))
        elif tc.fingerprint != twin.fingerprint:
            out.append(IRFinding(
                "ir-signature", tc.cell.id,
                f"seed-only twins {tc.cell.id} and {twin.cell.id} trace "
                f"to different canonical jaxprs ({tc.fingerprint} != "
                f"{twin.fingerprint}): either the seed leaked into the "
                f"traced program or canonicalization is unstable"))

    cells = [tc.cell for tc in traced]
    for a, b, fld in near_pairs(cells):
        ta, tb = by_id[a.id], by_id[b.id]
        if key_of(ta)[0] == key_of(tb)[0]:
            out.append(IRFinding(
                "ir-signature", a.id,
                f"plan_signature is blind to field '{fld}': cells "
                f"{a.id} and {b.id} differ in it but share a signature "
                f"— two different programs would hit one EngineCache "
                f"entry"))
        elif (a.depth, a.batch) == (b.depth, b.batch) \
                and ta.fingerprint == tb.fingerprint:
            out.append(IRFinding(
                "ir-signature", a.id,
                f"near-collision pair {a.id}/{b.id} (field '{fld}') "
                f"traced to identical jaxprs: the matrix pair is inert "
                f"and no longer exercises the field"))
    return out


# -- IR drift baselines ---------------------------------------------------

def check_drift(traced: Sequence[TracedCell], baseline: Dict[str, dict],
                complete: bool = False) -> List[IRFinding]:
    """Compare each cell's canonical fingerprint to the checked-in
    baseline.  ``complete=True`` (a full-matrix run) also flags stale
    baseline entries whose cell no longer exists."""
    out: List[IRFinding] = []
    for tc in traced:
        rec = baseline.get(tc.cell.id)
        if rec is None:
            out.append(IRFinding(
                "ir-drift", tc.cell.id,
                f"no IR baseline recorded for this cell (bless with "
                f"`python -m mpi_tpu.analysis.ir --write-baseline`)"))
        elif rec.get("fingerprint") != tc.fingerprint:
            out.append(IRFinding(
                "ir-drift", tc.cell.id,
                f"stepper trace drifted: canonical jaxpr fingerprint "
                f"{tc.fingerprint} != baselined "
                f"{rec.get('fingerprint')} — if the change is "
                f"intentional, bless it with `python -m "
                f"mpi_tpu.analysis.ir --write-baseline` (and say why in "
                f"the commit)"))
    if complete:
        live = {tc.cell.id for tc in traced}
        for stale in sorted(set(baseline) - live):
            out.append(IRFinding(
                "ir-drift", stale,
                f"baseline entry for unknown cell '{stale}' (removed "
                f"from the matrix?) — regenerate with --write-baseline"))
    return out
