"""Canonical jaxpr serialization + fingerprinting for the IR verifier.

The drift baseline (``analysis/ir/baseline.json``) stores one
fingerprint per matrix cell; for that to be useful the fingerprint must
be *stable* under everything that does not change the traced program:

* **var identity** — every trace mints fresh ``Var`` objects, and jax's
  pretty-printer names them by a global counter, so two identical traces
  print differently.  We rename vars ``v0, v1, ...`` per jaxpr in order
  of first appearance (invars, constvars, then eqn outputs).
* **source info** — jaxprs carry file/line provenance; none of it is
  serialized here, so moving a stepper ten lines down (or re-indenting
  it) cannot churn the baseline.
* **memory addresses** — params occasionally repr as
  ``<function f at 0x7f...>``; every ``0x...`` token is scrubbed.
* **the sparse cache salt** — ``ops/activity.py`` folds a per-process
  net-zero constant into the traced sparse evolve (its persistent-cache
  opt-out).  Any literal equal to the live salt serializes as ``SALT``
  so the sparse cells fingerprint identically across processes.

Constants/array literals serialize as ``dtype[shape]#sha1`` of their
bytes — value-exact (a changed pad mask IS drift) without embedding
megabytes into the canonical text.

``canonicalize`` also collects the facts the checks need in the same
walk: the set of primitive names reachable (purity check) and every
``ppermute``'s axis/permutation/operand-shape (collective check).
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

try:  # jax >= 0.4.16 keeps these under jax._src.core
    from jax._src.core import ClosedJaxpr, Jaxpr, Literal
except ImportError:  # pragma: no cover — jax internals moved
    from jax.core import ClosedJaxpr, Jaxpr, Literal  # type: ignore

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
_SALT_TOKEN = "SALT"


def _cache_salt() -> Optional[int]:
    """The live per-process sparse-cache salt (None if the module is
    unavailable — canonicalization must not hard-depend on it)."""
    try:
        from mpi_tpu.ops.activity import cache_salt
        return cache_salt()
    except Exception:  # pragma: no cover
        return None


@dataclass(frozen=True)
class CollectiveRecord:
    """One ``ppermute`` as seen in the trace: the named axis, the
    (src, dst) permutation, and the exchanged operand's shape."""

    axis_name: str
    perm: Tuple[Tuple[int, int], ...]
    shape: Tuple[int, ...]


@dataclass
class CanonResult:
    text: str
    fingerprint: str
    prim_names: Set[str] = field(default_factory=set)
    collectives: List[CollectiveRecord] = field(default_factory=list)


def _is_subjaxpr(v) -> bool:
    return isinstance(v, (ClosedJaxpr, Jaxpr))


def _array_token(arr, salt) -> str:
    a = np.asarray(arr)
    if a.ndim == 0 and salt is not None and a.dtype.kind in "iu" \
            and int(a) == salt:
        return f"{a.dtype}[]={_SALT_TOKEN}"
    if a.size <= 4:
        return f"{a.dtype}{list(a.shape)}={a.tolist()!r}"
    digest = hashlib.sha1(a.tobytes()).hexdigest()[:12]
    return f"{a.dtype}{list(a.shape)}#{digest}"


class _Canonicalizer:
    def __init__(self):
        self.salt = _cache_salt()
        self.prim_names: Set[str] = set()
        self.collectives: List[CollectiveRecord] = []

    # -- values ----------------------------------------------------------

    def _value(self, v, names: Dict[int, str]) -> str:
        if isinstance(v, ClosedJaxpr):
            consts = ",".join(_array_token(c, self.salt) if _is_arrayish(c)
                              else self._value(c, {}) for c in v.consts)
            return f"closed(consts=[{consts}]){self._jaxpr(v.jaxpr)}"
        if isinstance(v, Jaxpr):
            return self._jaxpr(v)
        if isinstance(v, (list, tuple)):
            inner = ",".join(self._value(w, names) for w in v)
            return f"({inner})" if isinstance(v, tuple) else f"[{inner}]"
        if isinstance(v, dict):
            items = ",".join(
                f"{k!r}:{self._value(v[k], names)}" for k in sorted(v, key=repr))
            return "{" + items + "}"
        if _is_arrayish(v):
            return _array_token(v, self.salt)
        if isinstance(v, (bool, int, float, complex, str, bytes,
                          type(None))):
            if isinstance(v, int) and self.salt is not None and v == self.salt:
                return _SALT_TOKEN
            return repr(v)
        # meshes, shardings, dtypes, effect sets, callables: repr with
        # memory addresses scrubbed (the rest of these reprs is stable)
        return _ADDR_RE.sub("0xX", repr(v))

    # -- atoms -----------------------------------------------------------

    def _atom(self, a, names: Dict[int, str]) -> str:
        if isinstance(a, Literal):
            return f"lit({_array_token(a.val, self.salt)})"
        key = id(a)
        if key not in names:
            names[key] = f"v{len(names)}"
        return f"{names[key]}:{a.aval.str_short()}"

    def _bind(self, a, names: Dict[int, str]) -> str:
        # an output var; DropVar has no binding identity worth naming
        if type(a).__name__ == "DropVar":
            return "_"
        return self._atom(a, names)

    # -- jaxprs ----------------------------------------------------------

    def _jaxpr(self, jx: Jaxpr, indent: int = 1) -> str:
        names: Dict[int, str] = {}
        pad = "  " * indent
        head_in = " ".join(self._atom(v, names) for v in jx.invars)
        head_const = " ".join(self._atom(v, names) for v in jx.constvars)
        lines = [f"jaxpr(in=[{head_in}] const=[{head_const}])"]
        for eq in jx.eqns:
            prim = eq.primitive.name
            self.prim_names.add(prim)
            if prim == "ppermute":
                self._record_ppermute(eq)
            params = ",".join(
                f"{k}={self._value(eq.params[k], names)}"
                for k in sorted(eq.params))
            outs = " ".join(self._bind(v, names) for v in eq.outvars)
            ins = " ".join(self._atom(v, names) for v in eq.invars)
            lines.append(f"{pad}{outs} = {prim}[{params}] {ins}")
        ret = " ".join(self._atom(v, names) for v in jx.outvars)
        lines.append(f"{pad}return {ret}")
        return "\n".join(lines)

    def _record_ppermute(self, eq) -> None:
        ax = eq.params.get("axis_name")
        if isinstance(ax, (tuple, list)):
            ax = ax[0] if len(ax) == 1 else tuple(ax)
        perm = tuple((int(s), int(d)) for s, d in eq.params.get("perm", ()))
        shape = tuple(int(s) for s in eq.invars[0].aval.shape)
        self.collectives.append(CollectiveRecord(str(ax), perm, shape))


def _is_arrayish(v) -> bool:
    return isinstance(v, np.ndarray) or np.isscalar(v) and not isinstance(
        v, (str, bytes)) or type(v).__module__.startswith("jax") and hasattr(
        v, "dtype") and hasattr(v, "shape")


def canonicalize(closed: ClosedJaxpr) -> CanonResult:
    """Canonical text + fingerprint of a ClosedJaxpr, plus the primitive
    set and ppermute records the IR checks consume."""
    c = _Canonicalizer()
    consts = ",".join(_array_token(v, c.salt) if _is_arrayish(v)
                      else c._value(v, {}) for v in closed.consts)
    text = f"consts=[{consts}]\n{c._jaxpr(closed.jaxpr)}"
    text = _ADDR_RE.sub("0xX", text)
    fp = hashlib.sha1(text.encode("utf-8")).hexdigest()[:16]
    return CanonResult(text=text, fingerprint=fp, prim_names=c.prim_names,
                       collectives=c.collectives)
