"""CLI for the invariant checker suite.

::

    python -m mpi_tpu.analysis                  # full suite, whole repo
    python -m mpi_tpu.analysis --rule lock-discipline mpi_tpu/serve
    python -m mpi_tpu.analysis --write-baseline # accept current findings
    python -m mpi_tpu.analysis --list-rules

Exit codes: 0 clean (suppressed/baselined findings don't fail), 1 any
actionable finding, 2 internal error (a rule crashed or a scanned file
does not parse) — a broken checker must never read as a passing one.
"""

from __future__ import annotations

import argparse
import os
import sys

from mpi_tpu.analysis import (
    all_rules, default_files, repo_root, run, write_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_tpu.analysis",
        description="AST-based invariant checkers (donation safety, lock "
                    "discipline, traced purity, ctxvar hops, obs drift)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to scan (default: the "
                             "repo's mpi_tpu/, tools/, bench.py)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record current findings as the baseline "
                             "(then edit in the mandatory reasons)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined findings too")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no summary line")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:18s} {r.doc}")
        return 0
    if args.rule:
        known = {r.name: r for r in rules}
        unknown = [n for n in args.rule if n not in known]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        rules = [known[n] for n in args.rule]

    root = repo_root()
    paths = None
    if args.paths:
        paths = []
        for p in args.paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                    paths.extend(os.path.join(dirpath, f)
                                 for f in sorted(filenames)
                                 if f.endswith(".py"))
            else:
                paths.append(p)

    report = run(root=root, rules=rules, paths=paths,
                 use_baseline=not args.no_baseline)

    if args.write_baseline:
        out = write_baseline(report.findings)
        print(f"wrote {len(report.findings)} fingerprint(s) to {out}; "
              f"fill in the 'reason' fields before committing")
        return 0

    for f in report.findings:
        print(f.format())
    for e in report.errors:
        print(f"error: {e}", file=sys.stderr)
    if not args.quiet:
        n_files = len(paths if paths is not None else default_files(root))
        print(f"{len(report.findings)} finding(s) over {n_files} file(s) "
              f"({len(report.suppressed)} suppressed, "
              f"{len(report.baselined)} baselined)",
              file=sys.stderr)
    if report.errors:
        return 2
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
