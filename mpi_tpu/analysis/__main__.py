"""CLI for the invariant checker suite.

::

    python -m mpi_tpu.analysis                  # full suite, whole repo
    python -m mpi_tpu.analysis --rule lock-discipline mpi_tpu/serve
    python -m mpi_tpu.analysis --changed-only   # git-dirty files only
    python -m mpi_tpu.analysis --format json    # machine-readable
    python -m mpi_tpu.analysis --write-baseline # accept current findings
    python -m mpi_tpu.analysis --list-rules

Exit codes: 0 clean (suppressed/baselined findings don't fail), 1 any
actionable finding, 2 internal error (a rule crashed or a scanned file
does not parse) — a broken checker must never read as a passing one.

Path-subset runs (explicit paths or ``--changed-only``) skip
project-wide rules (cross-file registry drift needs the whole tree to
judge — on a subset it would report every metric the subset doesn't
mention); name one explicitly via ``--rule`` to force it.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from mpi_tpu.analysis import (
    DEFAULT_SCOPE, all_rules, default_files, repo_root, run, write_baseline,
)


def _changed_paths(root):
    """Repo-relative git-dirty .py files under the lint scope, made
    absolute.  Covers modified, staged, and untracked (a brand-new
    module must not dodge the lint); deletions drop out naturally
    because the file no longer exists."""
    out = subprocess.run(
        ["git", "status", "--porcelain"], cwd=root,
        capture_output=True, text=True, check=True)
    scope_dirs = tuple(e + "/" for e in DEFAULT_SCOPE)
    paths = []
    for line in out.stdout.splitlines():
        rel = line[3:].strip()
        if " -> " in rel:           # rename: lint the new name
            rel = rel.split(" -> ", 1)[1]
        rel = rel.strip('"')
        if not rel.endswith(".py"):
            continue
        if not (rel in DEFAULT_SCOPE or rel.startswith(scope_dirs)):
            continue
        p = os.path.join(root, rel)
        if os.path.isfile(p):
            paths.append(p)
    return sorted(paths)


def _report_json(report, n_files: int) -> dict:
    def enc(f):
        return {"rule": f.rule, "path": f.rel, "line": f.line,
                "col": f.col, "scope": f.scope, "message": f.message,
                "fingerprint": f.fingerprint()}

    return {
        "tool": "mpi_tpu.analysis",
        "findings": [enc(f) for f in report.findings],
        "errors": list(report.errors),
        "summary": {
            "files": n_files,
            "findings": len(report.findings),
            "suppressed": len(report.suppressed),
            "baselined": len(report.baselined),
            "errors": len(report.errors),
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_tpu.analysis",
        description="AST-based invariant checkers (donation safety, lock "
                    "discipline, traced purity, ctxvar hops, obs drift)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to scan (default: the "
                             "repo's mpi_tpu/, tools/, bench.py)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--changed-only", action="store_true",
                        help="scan only git-dirty files under the lint "
                             "scope (incremental pre-commit runs)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record current findings as the baseline "
                             "(then edit in the mandatory reasons)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined findings too")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human",
                        help="diagnostic format (default: human)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no summary line")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:18s} {r.doc}")
        return 0
    forced = set(args.rule or ())
    if args.rule:
        known = {r.name: r for r in rules}
        unknown = [n for n in args.rule if n not in known]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        rules = [known[n] for n in args.rule]

    root = repo_root()
    paths = None
    if args.changed_only:
        if args.paths:
            print("--changed-only and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        try:
            paths = _changed_paths(root)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"--changed-only needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
        if not paths:
            if not args.quiet and args.format == "human":
                print("no changed files under the lint scope",
                      file=sys.stderr)
            if args.format == "json":
                from mpi_tpu.analysis import Report
                json.dump(_report_json(Report(), 0), sys.stdout, indent=2)
                sys.stdout.write("\n")
            return 0
    elif args.paths:
        paths = []
        for p in args.paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                    paths.extend(os.path.join(dirpath, f)
                                 for f in sorted(filenames)
                                 if f.endswith(".py"))
            else:
                paths.append(p)

    if paths is not None:
        # a project-wide rule judged against a file subset reports the
        # rest of the tree as missing; keep it only if explicitly forced
        dropped = [r.name for r in rules
                   if r.file_check is None and r.name not in forced]
        if dropped:
            rules = [r for r in rules if r.file_check is not None
                     or r.name in forced]
            if not args.quiet and args.format == "human":
                print(f"note: skipping project-wide rule(s) on a path "
                      f"subset: {', '.join(dropped)} (run without paths, "
                      f"or force with --rule)", file=sys.stderr)

    report = run(root=root, rules=rules, paths=paths,
                 use_baseline=not args.no_baseline)

    if args.write_baseline:
        out = write_baseline(report.findings)
        print(f"wrote {len(report.findings)} fingerprint(s) to {out}; "
              f"fill in the 'reason' fields before committing")
        return 0

    n_files = len(paths if paths is not None else default_files(root))
    if args.format == "json":
        json.dump(_report_json(report, n_files), sys.stdout, indent=2,
                  sort_keys=True)
        sys.stdout.write("\n")
    else:
        for f in report.findings:
            print(f.format())
        for e in report.errors:
            print(f"error: {e}", file=sys.stderr)
        if not args.quiet:
            print(f"{len(report.findings)} finding(s) over {n_files} "
                  f"file(s) ({len(report.suppressed)} suppressed, "
                  f"{len(report.baselined)} baselined)",
                  file=sys.stderr)
    if report.errors:
        return 2
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
