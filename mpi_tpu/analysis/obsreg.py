"""``obs-drift`` — the observability registry, extracted statically.

Dashboards parse ``/metrics`` and the trace JSONL, so the set of metric
families and span kinds is API.  Three artifacts describe it: the code
(the only authority), the README tables, and ``tools/obs_smoke.py``'s
runtime expectations.  This rule extracts the registry FROM THE CODE —
no import, pure ``ast`` — and cross-checks the other two in both
directions:

* every ``.span("name")`` / ``.event("name")`` literal emitted under
  ``mpi_tpu/`` must have a row in the README span table, and every row
  must correspond to a real emission site (``phase:*`` names are built
  dynamically by ``Obs.phase_sink``; the known expansions live in
  ``KNOWN_DYNAMIC_SPANS``);
* every backticked ``mpi_tpu_*`` token in the README (brace patterns
  like ``mpi_tpu_http_bytes_{in,out}_total`` and ``*`` wildcards
  expand) must resolve to registered families, and every registered
  family must be mentioned by some token;
* every ``mpi_tpu_*`` string literal in ``tools/obs_smoke.py`` must
  name a registered family (modulo ``_bucket``/``_count``/``_sum``
  sample suffixes), and every ``*SPAN_KINDS`` set element there must
  be an emitted span kind.

``extract_registry`` is also the runtime source for obs_smoke's
required-family lists — the static and runtime gates share one
extraction, so they cannot diverge from each other.
"""

from __future__ import annotations

import ast
import itertools
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mpi_tpu.analysis import (
    Finding, Rule, SourceFile, default_files, repo_root,
)

RULE_NAME = "obs-drift"

_REGISTER_KINDS = {
    "histogram": "histogram", "counter": "counter", "gauge": "gauge",
    "gauge_fn": "gauge", "counter_fn": "counter",
}
# span names assembled at runtime (Obs.phase_sink f-string) and the
# PhaseTimer phases that feed it
KNOWN_DYNAMIC_SPANS = {"phase:setup", "phase:steady"}
# trace-context keys every schema-v2 record may carry (obs/tracectx.py):
# the README span table must document them as columns and obs_smoke's
# TRACE_CTX_KEYS literal must match exactly — checked only when the
# scanned tree actually ships tracectx (fixture corpora predate it)
TRACE_CONTEXT_COLUMNS = ("trace_id", "span_id", "parent_span_id")

# families registered only when --telemetry-interval-s arms the sampler
# (ISSUE 15): present on an ARMED scrape, absent otherwise — like the
# cluster families, they belong to neither required list
SLO_MODULES = ("mpi_tpu/obs/slo.py", "mpi_tpu/obs/timeseries.py")
# families registered only when --admission/--tenants-file arms the
# admission layer (ISSUE 16) — same armed-only discipline as SLO_MODULES
ADMISSION_PREFIX = "mpi_tpu/admission/"
# families registered only when --flight-recorder/--anomaly-detect arm
# the flight plane (ISSUE 19) — same armed-only discipline
FLIGHT_MODULES = ("mpi_tpu/obs/flight.py", "mpi_tpu/obs/devmem.py",
                  "mpi_tpu/obs/anomaly.py")

_BACKTICK = re.compile(r"`([^`]+)`")
_FAMILY_TOKEN = re.compile(r"^mpi_tpu_[a-z0-9_{},*]+$")
_FAMILY_LIT = re.compile(r"^mpi_tpu_[a-z0-9_]*[a-z0-9]$")
_SAMPLE_SUFFIXES = ("_bucket", "_count", "_sum")


def extract_registry(root: Optional[str] = None,
                     files: Optional[Sequence[SourceFile]] = None) -> dict:
    """The statically-extracted observability registry of the tree:
    ``{"metrics": {family: {"kind", "module", "labels"}},
    "spans": {name: module}}``.  Scans ``mpi_tpu/`` only — that is
    where every registration and emission site lives."""
    root = os.path.abspath(root or repo_root())
    if files is None:
        files = []
        for p in default_files(root):
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            if rel.startswith("mpi_tpu/"):
                try:
                    files.append(SourceFile(p, root))
                except (SyntaxError, OSError):
                    continue
    metrics: Dict[str, dict] = {}
    spans: Dict[str, str] = {}
    for sf in files:
        if not sf.rel.startswith("mpi_tpu/"):
            continue
        attr_to_family: Dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            lit = _first_literal(node)
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth in _REGISTER_KINDS and lit and \
                        lit.startswith("mpi_tpu_"):
                    metrics.setdefault(lit, {
                        "kind": _REGISTER_KINDS[meth],
                        "module": sf.rel, "labels": set()})
                elif meth in ("span", "event") and lit:
                    spans.setdefault(lit, sf.rel)
            elif isinstance(node.func, ast.Name) and node.func.id == "_span" \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                # the serve layer's obs-optional helper: _span(obs, "name")
                spans.setdefault(node.args[1].value, sf.rel)
        # label keys ride on .series(...) calls against the bound handle
        # (self.wire_encode = m.histogram(...); self.wire_encode.series(
        # format=..., transport=...)) — map handles back to families,
        # then collect the kwarg names
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                lit = _first_literal(node.value)
                t = node.targets[0]
                if lit and lit in metrics:
                    if isinstance(t, ast.Attribute):
                        attr_to_family[t.attr] = lit
                    elif isinstance(t, ast.Name):
                        attr_to_family[t.id] = lit
        # re-walk series calls now that attr_to_family is complete
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "series":
                tgt = node.func.value
                fam = None
                if isinstance(tgt, ast.Attribute):
                    fam = attr_to_family.get(tgt.attr)
                elif isinstance(tgt, ast.Name):
                    fam = attr_to_family.get(tgt.id)
                if fam in metrics:
                    metrics[fam]["labels"].update(
                        kw.arg for kw in node.keywords if kw.arg)
    for fam in metrics.values():
        fam["labels"] = sorted(fam["labels"])
    return {"metrics": metrics, "spans": spans}


def _first_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def required_families(registry: Optional[dict] = None) -> Tuple[List[str],
                                                                List[str]]:
    """(core, aio) family lists for the runtime smoke: aio families are
    the ones ``serve/aio.py`` registers at construction; everything
    else must be present on any instrumented scrape.  Families
    registered by ``mpi_tpu/cluster/`` exist only when serving with
    ``--peers`` and belong to neither list (see
    :func:`cluster_families`); likewise the ``SLO_MODULES`` families
    exist only when ``--telemetry-interval-s`` arms the sampler (see
    :func:`slo_families`), the ``ADMISSION_PREFIX`` families only
    when ``--admission``/``--tenants-file`` arms admission control
    (see :func:`admission_families`), and the ``FLIGHT_MODULES``
    families only when ``--flight-recorder``/``--anomaly-detect`` arm
    the flight plane (see :func:`flight_families`)."""
    registry = registry or extract_registry()
    core, aio = [], []
    for name, info in sorted(registry["metrics"].items()):
        if info["module"].startswith("mpi_tpu/cluster/") \
                or info["module"].startswith(ADMISSION_PREFIX) \
                or info["module"] in SLO_MODULES \
                or info["module"] in FLIGHT_MODULES:
            continue
        (aio if info["module"] == "mpi_tpu/serve/aio.py" else core).append(name)
    return core, aio


def cluster_families(registry: Optional[dict] = None) -> List[str]:
    """Families registered by ``mpi_tpu/cluster/`` — present on a scrape
    only in cluster mode (``--peers``), so the runtime smoke checks them
    separately from the always-on core set."""
    registry = registry or extract_registry()
    return sorted(name for name, info in registry["metrics"].items()
                  if info["module"].startswith("mpi_tpu/cluster/"))


def slo_families(registry: Optional[dict] = None) -> List[str]:
    """Families registered by the telemetry/SLO modules — present on a
    scrape only when ``--telemetry-interval-s`` (or ``--slo-file``) arms
    the sampler.  The runtime smoke pins them ABSENT on an unarmed
    scrape (the default-off purity gate) and present on an armed one."""
    registry = registry or extract_registry()
    return sorted(name for name, info in registry["metrics"].items()
                  if info["module"] in SLO_MODULES)


def admission_families(registry: Optional[dict] = None) -> List[str]:
    """Families registered by ``mpi_tpu/admission/`` — present on a
    scrape only when ``--admission``/``--tenants-file`` arms admission
    control.  The runtime smoke pins them ABSENT on an unarmed scrape
    (the default-off purity gate) and present on an armed one."""
    registry = registry or extract_registry()
    return sorted(name for name, info in registry["metrics"].items()
                  if info["module"].startswith(ADMISSION_PREFIX))


def flight_families(registry: Optional[dict] = None) -> List[str]:
    """Families registered by the flight-plane modules — present on a
    scrape only when ``--flight-recorder``/``--anomaly-detect`` arm the
    recorder (devmem additionally needs telemetry armed).  The runtime
    smoke pins them ABSENT on an unarmed scrape (the default-off purity
    gate) and present on an armed one."""
    registry = registry or extract_registry()
    return sorted(name for name, info in registry["metrics"].items()
                  if info["module"] in FLIGHT_MODULES)


# -- README cross-check ---------------------------------------------------

def _expand_token(token: str) -> List[str]:
    """``a_{b,c}_d`` -> [a_b_d, a_c_d]; trailing ``*`` kept as wildcard."""
    parts: List[List[str]] = [[""]]
    for seg in re.split(r"(\{[^}]*\})", token):
        if seg.startswith("{") and seg.endswith("}"):
            alts = seg[1:-1].split(",")
        else:
            alts = [seg]
        parts = [p + [a] for p in parts for a in alts]
        parts = [["".join(p)] for p in parts]
    return [p[0] for p in parts]


def _readme_span_header(lines: Sequence[str]) -> Optional[Tuple[int,
                                                                List[str]]]:
    """(line_no, header cells) of the first table whose header's first
    column is ``span``, or None."""
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if cells and cells[0].strip("`* ").lower() == "span":
            return i, cells
    return None


def _readme_span_rows(lines: Sequence[str]) -> List[Tuple[int, List[str]]]:
    """(line_no, [span names]) per row of any table whose header's
    first column is ``span``."""
    rows: List[Tuple[int, List[str]]] = []
    in_table = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not in_table:
            if cells and cells[0].strip("`* ").lower() == "span":
                in_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue
        names = _BACKTICK.findall(cells[0])
        if names:
            rows.append((i, names))
    return rows


def check_tree(root: str, files: Sequence[SourceFile],
               readme_path: Optional[str] = None,
               smoke_path: Optional[str] = None) -> List[Finding]:
    readme_path = readme_path or os.path.join(root, "README.md")
    smoke_path = smoke_path or os.path.join(root, "tools", "obs_smoke.py")
    registry = extract_registry(root, [sf for sf in files
                                       if sf.rel.startswith("mpi_tpu/")])
    metrics, spans = registry["metrics"], registry["spans"]
    # the trace-context contract exists only where tracectx shipped —
    # fixture corpora without it must not be held to it
    has_tracectx = any(sf.rel == "mpi_tpu/obs/tracectx.py" for sf in files)
    findings: List[Finding] = []

    def mk(rel: str, line: int, msg: str) -> Finding:
        return Finding(RULE_NAME, rel, line, 0, msg)

    # -- README ----------------------------------------------------------
    if os.path.exists(readme_path):
        readme_rel = os.path.relpath(readme_path, root).replace(os.sep, "/")
        with open(readme_path, "r", encoding="utf-8") as f:
            readme = f.read()
        rlines = readme.splitlines()
        rows = _readme_span_rows(rlines)
        table_spans: Dict[str, int] = {}
        for line_no, names in rows:
            for n in names:
                table_spans.setdefault(n, line_no)
        for name, line_no in sorted(table_spans.items()):
            if name not in spans and name not in KNOWN_DYNAMIC_SPANS:
                findings.append(mk(
                    readme_rel, line_no,
                    f"README span table lists '{name}' but no call site "
                    f"under mpi_tpu/ emits it"))
        table_line = rows[0][0] if rows else 1
        for name, module in sorted(spans.items()):
            if name not in table_spans:
                findings.append(mk(
                    readme_rel, table_line,
                    f"span kind '{name}' (emitted by {module}) is missing "
                    f"from the README span table"))
        if not rows:
            findings.append(mk(readme_rel, 1,
                               "README has no span table (header row "
                               "starting with 'span')"))
        if has_tracectx and rows:
            header = _readme_span_header(rlines)
            if header is not None:
                hdr_line, hdr_cells = header
                cols = {c.strip("`* ").lower() for c in hdr_cells}
                missing_cols = [c for c in TRACE_CONTEXT_COLUMNS
                                if c not in cols]
                if missing_cols:
                    findings.append(mk(
                        readme_rel, hdr_line,
                        f"README span table lacks trace-context "
                        f"column(s) {missing_cols} — schema v2 "
                        f"(obs/tracectx.py) adds them to every span"))
        # metric-family mentions, both directions
        mentioned: Set[str] = set()
        for i, line in enumerate(rlines, start=1):
            for tok in _BACKTICK.findall(line):
                tok = tok.strip()
                if not _FAMILY_TOKEN.match(tok):
                    continue
                hit = False
                for name in _expand_token(tok):
                    if name.endswith("*"):
                        pref = name[:-1]
                        matches = [f for f in metrics if f.startswith(pref)]
                        mentioned.update(matches)
                        hit = hit or bool(matches)
                    elif name in metrics:
                        mentioned.add(name)
                        hit = True
                if not hit:
                    findings.append(mk(
                        readme_rel, i,
                        f"README mentions metric '{tok}' but no such "
                        f"family is registered under mpi_tpu/"))
        for name, info in sorted(metrics.items()):
            if name not in mentioned:
                findings.append(mk(
                    readme_rel, 1,
                    f"metric family '{name}' (registered by "
                    f"{info['module']}) is not mentioned anywhere in the "
                    f"README"))

    # -- obs_smoke -------------------------------------------------------
    if os.path.exists(smoke_path):
        smoke_rel = os.path.relpath(smoke_path, root).replace(os.sep, "/")
        with open(smoke_path, "r", encoding="utf-8") as f:
            smoke_src = f.read()
        smoke_tree = ast.parse(smoke_src, filename=smoke_path)
        for node in ast.walk(smoke_tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                    and _FAMILY_LIT.match(node.value):
                base = node.value
                for suf in _SAMPLE_SUFFIXES:
                    if base.endswith(suf) and base not in metrics:
                        base = base[: -len(suf)]
                        break
                if base not in metrics:
                    findings.append(mk(
                        smoke_rel, node.lineno,
                        f"obs_smoke expects metric '{node.value}' but no "
                        f"such family is registered under mpi_tpu/"))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("SPAN_KINDS"):
                for elt in ast.walk(node.value):
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str) \
                            and elt.value not in spans \
                            and elt.value not in KNOWN_DYNAMIC_SPANS:
                        findings.append(mk(
                            smoke_rel, elt.lineno,
                            f"obs_smoke requires span kind '{elt.value}' "
                            f"but no call site under mpi_tpu/ emits it"))
        if has_tracectx:
            ctx_keys: Optional[Set[str]] = None
            ctx_line = 1
            for node in ast.walk(smoke_tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "TRACE_CTX_KEYS":
                    ctx_line = node.lineno
                    ctx_keys = {elt.value for elt in ast.walk(node.value)
                                if isinstance(elt, ast.Constant)
                                and isinstance(elt.value, str)}
            if ctx_keys is None:
                findings.append(mk(
                    smoke_rel, 1,
                    "obs_smoke lacks a TRACE_CTX_KEYS literal naming the "
                    "schema-v2 trace-context keys "
                    f"{list(TRACE_CONTEXT_COLUMNS)}"))
            elif ctx_keys != set(TRACE_CONTEXT_COLUMNS):
                findings.append(mk(
                    smoke_rel, ctx_line,
                    f"obs_smoke TRACE_CTX_KEYS {sorted(ctx_keys)} drifted "
                    f"from the schema-v2 context keys "
                    f"{sorted(TRACE_CONTEXT_COLUMNS)}"))
    return findings


def check_project(root: str, files: Sequence[SourceFile]) -> List[Finding]:
    return check_tree(root, files)


RULE = Rule(
    name=RULE_NAME,
    doc="statically-extracted metric/span registry must match the README "
        "tables and tools/obs_smoke.py expectations, both directions",
    project_check=check_project,
)
