"""``traced-purity`` — no host side effects inside traced code.

``jax.jit`` / ``shard_map`` / ``pallas_call`` run a function ONCE at
trace time; anything impure inside it (wall-clock reads, host RNG,
file I/O) silently bakes a single stale value into the compiled
program — it does not "run every step" the way it reads.  Mutable
default arguments are the same trap one level up: state that survives
across traces.

Roots are collected per module:

* defs decorated ``@jax.jit`` / ``@jit`` /
  ``@(functools.)partial(jax.jit, ...)``;
* functions passed by name to ``jax.jit(f, ...)``, ``shard_map(f, ...)``
  or ``pallas_call(kernel, ...)`` call sites;
* local ``def``s nested inside a rooted function.

Reachability is an intra-module call graph on simple names (calls
through attributes/containers are invisible — the fixtures pin what is
and is not caught).  On every reachable function the rule flags:

* calls whose target dumps as ``time.*``, ``random.*``, ``np.random.*``
  / ``numpy.random.*``, or builtin ``open``/``print``/``input``;
* mutable default argument values (list/dict/set displays or
  ``list()``/``dict()``/``set()`` calls).

``jax.random`` is fine (functional, key-threaded) and is not matched.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from mpi_tpu.analysis import Finding, Rule, SourceFile

RULE_NAME = "traced-purity"

_TRACE_ENTRYPOINTS = ("jit", "shard_map", "pallas_call")
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")
_IMPURE_BUILTINS = {"open", "print", "input"}


def _dump(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


def _is_trace_entry(func: ast.AST) -> bool:
    d = _dump(func)
    last = d.rsplit(".", 1)[-1]
    return last in _TRACE_ENTRYPOINTS


def _is_traced_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        if _is_trace_entry(dec.func):
            return True
        fd = _dump(dec.func)
        if fd in ("partial", "functools.partial") and dec.args \
                and _is_trace_entry(dec.args[0]):
            return True
        return False
    return _is_trace_entry(dec)


def _all_defs(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _roots(tree: ast.AST) -> Set[str]:
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_traced_decorator(d) for d in node.decorator_list):
                roots.add(node.name)
        elif isinstance(node, ast.Call) and _is_trace_entry(node.func):
            # jax.jit(f) / shard_map(f, mesh=...) / pallas_call(kernel, ...)
            if node.args and isinstance(node.args[0], ast.Name):
                roots.add(node.args[0].id)
    return roots


def _calls_in(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            out.add(node.func.id)
    return out


def _reachable(tree: ast.AST) -> Dict[str, ast.AST]:
    defs = _all_defs(tree)
    seen: Set[str] = set()
    frontier = list(_roots(tree) & set(defs))
    reach: Dict[str, ast.AST] = {}
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for fn in defs[name]:
            reach[name] = fn
            # nested defs of a traced function trace with it
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn and node.name not in seen:
                    frontier.append(node.name)
            for callee in _calls_in(fn):
                if callee in defs and callee not in seen:
                    frontier.append(callee)
    return reach


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
        and node.func.id in ("list", "dict", "set")


def check(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    flagged: Set[int] = set()   # dedupe: a def reachable via two paths
    for name, fn in sorted(_reachable(sf.tree).items()):
        if fn.lineno in flagged:
            continue
        flagged.add(fn.lineno)
        args = fn.args
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            if _mutable_default(default):
                findings.append(sf.finding(
                    RULE_NAME, default,
                    f"mutable default argument on '{name}', which is "
                    f"reachable from a traced entry point — state "
                    f"survives across traces"))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dump(node.func)
            if any(d.startswith(p) for p in _IMPURE_PREFIXES) \
                    or d in _IMPURE_BUILTINS:
                findings.append(sf.finding(
                    RULE_NAME, node,
                    f"impure call '{d}' inside '{name}', which is "
                    f"reachable from a traced entry point — it runs "
                    f"once at trace time, not per step"))
    return findings


RULE = Rule(
    name=RULE_NAME,
    doc="no time/random/np.random/file-I/O calls or mutable defaults in "
        "functions reachable from jit/shard_map/pallas_call",
    file_check=check,
)
