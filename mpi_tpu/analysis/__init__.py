"""``mpi_tpu.analysis`` — AST-based invariant checkers for this repo.

The three worst bugs this repo has shipped were *invariant* violations
invisible to pytest until they corrupted state: the PR-3 donation race
(a seam stepper re-reading a donated buffer), the PR-2 torn generation
reads (session fields read outside ``session.lock``), and the rid
contextvar drops across thread hops that PR-4/5 had to hand-audit.
This package turns those code-review rules into machine checks over the
stdlib ``ast`` — no third-party linter, no runtime import of the code
under analysis.

Rules (see each module's docstring for the precise contract):

* ``donation-safety``   (:mod:`.donation`) — a function that calls a
  donating jit (``donate_argnums=`` / ``donate=True``) must not read
  the donated name afterwards; rebinding is the safe idiom.
* ``lock-discipline``   (:mod:`.locks`) — attributes declared shared in
  the per-class manifest may only be touched under their declared lock;
  multi-lock acquisition loops must sort by ``.id`` first.
* ``traced-purity``     (:mod:`.purity`) — no ``time.*`` / ``random.*``
  / ``np.random`` / file I/O / mutable defaults in functions reachable
  from ``jax.jit`` / ``shard_map`` / ``pallas_call`` roots.
* ``ctxvar-hop``        (:mod:`.ctxvar`) — thread/executor hops into
  code that reads the rid contextvar must ``copy_context`` (or stash
  the rid explicitly with ``set_request_id``).
* ``obs-drift``         (:mod:`.obsreg`) — the statically-extracted
  metric/span registry must agree with the README tables and
  ``tools/obs_smoke.py`` in both directions.

Suppressions are inline with a mandatory reason (``<rule>`` stands for
a real rule name; the literal form is ``lint: disable=`` + the name)::

    self.grid = g  # lint: disable=<rule> -- caller holds lock

A suppression on a ``def`` line scopes to the whole function.  A
suppression missing its ``-- reason`` is itself a finding, and so is a
*stale* one: a suppression naming a rule that produces no finding in
its scope is reported as unused (suppressions rot silently otherwise).
Findings that cannot carry a comment (e.g. in README.md) go in the
checked-in ``baseline.json`` next to this file, each with a written
reason.

Runner: ``python -m mpi_tpu.analysis [--rule R] [--write-baseline]``;
exit 0 clean, 1 findings, 2 internal error.  ``tests/test_lint.py``
runs the same suite inside tier-1.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "Rule", "SourceFile", "Report", "Suppression",
    "all_rules", "default_files", "load_baseline", "repo_root", "run",
    "write_baseline", "BASELINE_PATH",
]

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

# `# lint: disable=<rule-a>,<rule-b> -- why this is safe` (with real
# rule names in place of the angle-bracket placeholders)
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_\-, ]+?)\s*(?:--\s*(.*\S))?\s*$")


def repo_root() -> str:
    """The checkout root (the directory holding the ``mpi_tpu`` package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclass
class Suppression:
    """One parsed ``# lint: disable=`` comment: the line it sits on, the
    [start, end] line range it applies to, the rules it names, and —
    filled in during :func:`run` — which of those rules it actually
    suppressed a finding for.  Rules never hit are stale and reported."""

    line: int                 # where the comment lives (for diagnostics)
    start: int                # first line it covers
    end: int                  # last line it covers (== start for one line)
    rules: Set[str]
    used: Set[str] = field(default_factory=set)

    def covers(self, rule: str, line: int) -> bool:
        return rule in self.rules and self.start <= line <= self.end


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``rel:line:col: [rule] message``.

    ``scope`` is the enclosing def's qualname (or ``<module>``) — the
    baseline fingerprint hashes rule/rel/scope/message but NOT the line
    number, so unrelated edits above a baselined finding don't churn it.
    """

    rule: str
    rel: str          # repo-relative path, '/'-separated
    line: int
    col: int
    message: str
    scope: str = "<module>"

    def format(self) -> str:
        return f"{self.rel}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def fingerprint(self) -> str:
        raw = f"{self.rule}:{self.rel}:{self.scope}:{self.message}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]


class SourceFile:
    """A parsed file plus the lint metadata every rule needs: the AST,
    enclosing-def spans for scope attribution, and parsed suppressions."""

    def __init__(self, path: str, root: str):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.path)
        # (start, end, qualname) per def, in source order (innermost =
        # smallest containing span)
        self._defs: List[Tuple[int, int, str]] = []
        self._collect_defs(self.tree, "")
        self.suppressions: List[Suppression] = []
        self.bad_suppress_lines: List[int] = []
        self._parse_suppressions()

    # -- structure -------------------------------------------------------

    def _collect_defs(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}{child.name}"
                if not isinstance(child, ast.ClassDef):
                    self._defs.append(
                        (child.lineno, child.end_lineno or child.lineno, qual))
                self._collect_defs(child, qual + ".")
            else:
                self._collect_defs(child, prefix)

    def enclosing_scope(self, line: int) -> str:
        best: Optional[Tuple[int, int, str]] = None
        for start, end, qual in self._defs:
            if start <= line <= end:
                if best is None or (end - start) < (best[1] - best[0]):
                    best = (start, end, qual)
        return best[2] if best else "<module>"

    # -- suppressions ----------------------------------------------------

    def _def_span_at(self, line: int) -> Optional[Tuple[int, int]]:
        for start, end, _qual in self._defs:
            if start == line:
                return (start, end)
        return None

    def _parse_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            reason = m.group(2)
            if not reason:
                # an unjustified suppression neither suppresses nor
                # passes: it is a finding in its own right
                self.bad_suppress_lines.append(i)
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            span = self._def_span_at(i)
            if span is not None:
                self.suppressions.append(
                    Suppression(i, span[0], span[1], rules))
            elif text.lstrip().startswith("#"):
                # standalone comment: applies to the next non-blank line
                j = i + 1
                while j <= len(self.lines) and not self.lines[j - 1].strip():
                    j += 1
                span2 = self._def_span_at(j)
                if span2 is not None:
                    self.suppressions.append(
                        Suppression(i, span2[0], span2[1], rules))
                else:
                    self.suppressions.append(Suppression(i, j, j, rules))
            else:
                self.suppressions.append(Suppression(i, i, i, rules))

    def is_suppressed(self, rule: str, line: int) -> bool:
        """Whether (rule, line) is covered — and mark every covering
        suppression as *used* for that rule (the unused-suppression
        check reads the leftovers)."""
        hit = False
        for sup in self.suppressions:
            if sup.covers(rule, line):
                sup.used.add(rule)
                hit = True
        return hit

    # -- diagnostics -----------------------------------------------------

    def finding(self, rule: str, node, message: str) -> Finding:
        if isinstance(node, int):
            line, col = node, 0
        else:
            line, col = node.lineno, node.col_offset
        return Finding(rule, self.rel, line, col, message,
                       self.enclosing_scope(line))


@dataclass(frozen=True)
class Rule:
    """A named analyzer.  ``file_check(sf)`` runs once per SourceFile;
    ``project_check(root, files)`` runs once over the whole tree (for
    cross-file invariants like registry drift)."""

    name: str
    doc: str
    file_check: Optional[Callable[[SourceFile], List[Finding]]] = None
    project_check: Optional[
        Callable[[str, Sequence[SourceFile]], List[Finding]]] = None


def all_rules() -> List[Rule]:
    from mpi_tpu.analysis import ctxvar, donation, locks, obsreg, purity
    return [donation.RULE, locks.RULE, purity.RULE, ctxvar.RULE, obsreg.RULE]


# -- file walker ----------------------------------------------------------

# tests/ are deliberately out of the default scope: fixtures there are
# known-bad on purpose and tests poke internals lock-free by design.
DEFAULT_SCOPE = ("mpi_tpu", "tools", "bench.py")


def default_files(root: str) -> List[str]:
    out: List[str] = []
    for entry in DEFAULT_SCOPE:
        p = os.path.join(root, entry)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__" and
                                 not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


# -- baseline -------------------------------------------------------------

def load_baseline(path: Optional[str] = None) -> Dict[str, dict]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("fingerprints", {})


def write_baseline(findings: Iterable[Finding],
                   path: Optional[str] = None) -> str:
    path = path or BASELINE_PATH
    fps = {
        f.fingerprint(): {
            "rule": f.rule, "path": f.rel, "scope": f.scope,
            "message": f.message,
            "reason": "TODO: justify this baseline entry",
        }
        for f in sorted(findings, key=lambda f: (f.rel, f.line, f.rule))
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"fingerprints": fps}, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# -- runner ---------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # actionable
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


def run(root: Optional[str] = None,
        rules: Optional[Sequence[Rule]] = None,
        paths: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = None,
        use_baseline: bool = True) -> Report:
    """Run ``rules`` (default: all) over ``paths`` (default: the repo
    scope) and fold in suppressions and the baseline."""
    root = os.path.abspath(root or repo_root())
    rules = list(rules) if rules is not None else all_rules()
    paths = list(paths) if paths is not None else default_files(root)

    report = Report()
    files: List[SourceFile] = []
    by_rel: Dict[str, SourceFile] = {}
    for p in paths:
        try:
            sf = SourceFile(p, root)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            report.errors.append(f"{p}: {type(e).__name__}: {e}")
            continue
        files.append(sf)
        by_rel[sf.rel] = sf

    raw: List[Finding] = []
    for sf in files:
        for line in sf.bad_suppress_lines:
            raw.append(sf.finding(
                "suppression", line,
                "lint suppression is missing its '-- reason'"))
    for rule in rules:
        try:
            if rule.file_check is not None:
                for sf in files:
                    raw.extend(rule.file_check(sf))
            if rule.project_check is not None:
                raw.extend(rule.project_check(root, files))
        except Exception as e:  # a crashing rule must fail loudly, not pass
            report.errors.append(f"rule {rule.name}: {type(e).__name__}: {e}")

    baseline = load_baseline(baseline_path) if use_baseline else {}
    for f in sorted(raw, key=lambda f: (f.rel, f.line, f.col, f.rule)):
        sf = by_rel.get(f.rel)
        if sf is not None and sf.is_suppressed(f.rule, f.line):
            report.suppressed.append(f)
        elif f.fingerprint() in baseline:
            report.baselined.append(f)
        else:
            report.findings.append(f)

    # stale suppressions: every parsed suppression has now seen every
    # finding of this run; a named rule it never suppressed is rot (or a
    # typo — an unknown rule name can never match).  Only rules that
    # actually ran are judged: `--rule lock-discipline` must not flag
    # the tree's justified traced-purity suppressions as unused.
    active = {r.name for r in rules}
    known = {r.name for r in all_rules()}
    for sf in files:
        for sup in sf.suppressions:
            for rule_name in sorted((sup.rules & active) - sup.used):
                f = sf.finding(
                    "unused-suppression", sup.line,
                    f"suppression for '{rule_name}' matches no finding "
                    f"— remove it (stale suppressions hide future "
                    f"regressions)")
                (report.findings if f.fingerprint() not in baseline
                 else report.baselined).append(f)
            for rule_name in sorted(sup.rules - known):
                f = sf.finding(
                    "unused-suppression", sup.line,
                    f"suppression names unknown rule '{rule_name}' "
                    f"(typo? see --list-rules)")
                (report.findings if f.fingerprint() not in baseline
                 else report.baselined).append(f)
    return report
