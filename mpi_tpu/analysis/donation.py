"""``donation-safety`` — no reads of a buffer after donating it.

The PR-3 bug class: a jitted program built with ``donate_argnums`` (or
a repo helper called with ``donate=True``) may alias its input buffer
into the output, so the *caller* must not touch the donated name after
the call.  The safe idiom is rebinding (``grid = evolve(grid, n)``);
the bug idiom is keeping a second reference alive::

    out = evolve(grid, n)
    band = grid[0:2, :]      # reads a buffer XLA may already have clobbered

The rule is purely lexical within one function scope:

1. Collect *donating callables* visible in the module —

   * ``f = jax.jit(g, donate_argnums=...)`` assignments,
   * defs decorated ``@jax.jit(donate_argnums=...)`` or
     ``@(functools.)partial(jax.jit, ..., donate_argnums=...)``,
   * ``f = helper(..., donate=True)`` (the ``utils.segmenting`` /
     ``parallel.seam`` convention: argument 0 of the result donates).

   Decorated *bodies* are exempt: inside the traced function the names
   are tracer values, not buffers.

2. In every other function, walk statements in source order.  A call
   to a donating callable marks the plain-``Name`` arguments at the
   donated positions as dead; any later load of a dead name is a
   finding.  Assigning to the name (including the rebind in the same
   statement) resurrects it.

Attribute-resolved callables (``engine.step``) are out of scope — the
engine API documents its own donation contract and the serve layer
already rebinds everywhere; this rule guards the raw-jit seams where
PR 3 actually bit.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mpi_tpu.analysis import Finding, Rule, SourceFile

RULE_NAME = "donation-safety"


def _dump(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ast.dump(node)


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Positions named by a literal ``donate_argnums=`` keyword, or
    ``(0,)`` for a literal ``donate=True``; None if the call donates
    nothing we can see statically."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        out.append(elt.value)
                return tuple(out) if out else None
            return None
        if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return (0,)
    return None


def _is_jit_name(node: ast.AST) -> bool:
    d = _dump(node)
    return d in ("jax.jit", "jit") or d.endswith(".jit")


def _decorator_donations(dec: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donated positions declared by a def's decorator, if any."""
    if not isinstance(dec, ast.Call):
        return None
    pos = _donated_positions(dec)
    if pos is None:
        return None
    # @jax.jit(donate_argnums=...) or @partial(jax.jit, donate_argnums=...)
    if _is_jit_name(dec.func):
        return pos
    fd = _dump(dec.func)
    if fd in ("partial", "functools.partial") and dec.args \
            and _is_jit_name(dec.args[0]):
        return pos
    return None


def _collect_donating(tree: ast.Module) -> Tuple[Dict[str, Tuple[int, ...]],
                                                 Set[int]]:
    """Map of callable-name -> donated positions, plus the line spans of
    decorated-donating defs (their bodies are exempt from the rule)."""
    donating: Dict[str, Tuple[int, ...]] = {}
    exempt_defs: Set[int] = set()

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                pos = _decorator_donations(dec)
                if pos is not None:
                    donating[node.name] = pos
                    exempt_defs.add(node.lineno)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            call = node.value
            pos = _donated_positions(call)
            if pos is not None:
                # f = jax.jit(g, donate_argnums=...) / f = helper(donate=True)
                donating[node.targets[0].id] = pos
    return donating, exempt_defs


class _ScopeWalker:
    """Statement-order walk of one function body tracking dead names."""

    def __init__(self, sf: SourceFile, donating: Dict[str, Tuple[int, ...]]):
        self.sf = sf
        self.donating = donating
        self.findings: List[Finding] = []

    def walk(self, fn: ast.AST) -> None:
        self._block(list(fn.body), {})

    def _block(self, stmts: Sequence[ast.stmt], dead: Dict[str, int]) -> None:
        for st in stmts:
            self._stmt(st, dead)

    def _stmt(self, st: ast.stmt, dead: Dict[str, int]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs get their own walk
        if isinstance(st, ast.Assign):
            self._check_expr(st.value, dead)
            dead.update(self._donations_in(st.value))
            # targets bind after the call: `grid = evolve(grid, 1)` is
            # the safe rebind, so clearing comes second
            for t in st.targets:
                self._clear_target(t, dead)
            return
        if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                self._check_expr(st.value, dead)
                dead.update(self._donations_in(st.value))
            self._clear_target(st.target, dead)
            return
        if isinstance(st, (ast.If, ast.While)):
            self._check_expr(st.test, dead)
            dead.update(self._donations_in(st.test))
            # branches see the current dead set; their kills propagate
            # (over-approximate: a name donated in either branch stays
            # dead after — exactly the conservative direction we want)
            self._block(st.body, dead)
            self._block(st.orelse, dead)
            return
        if isinstance(st, ast.For):
            self._check_expr(st.iter, dead)
            dead.update(self._donations_in(st.iter))
            self._clear_target(st.target, dead)
            self._block(st.body, dead)
            self._block(st.orelse, dead)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._check_expr(item.context_expr, dead)
                dead.update(self._donations_in(item.context_expr))
                if item.optional_vars is not None:
                    self._clear_target(item.optional_vars, dead)
            self._block(st.body, dead)
            return
        if isinstance(st, ast.Try):
            self._block(st.body, dead)
            for h in st.handlers:
                self._block(h.body, dead)
            self._block(st.orelse, dead)
            self._block(st.finalbody, dead)
            return
        # expression statements, return, raise, assert, ...
        for expr in ast.iter_child_nodes(st):
            if isinstance(expr, ast.expr):
                self._check_expr(expr, dead)
                dead.update(self._donations_in(expr))

    def _clear_target(self, target: ast.AST, dead: Dict[str, int]) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                dead.pop(node.id, None)

    def _donations_in(self, expr: ast.expr) -> Dict[str, int]:
        """Names this expression donates (plain-Name args at donated
        positions of calls to known donating callables)."""
        out: Dict[str, int] = {}
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            pos = self.donating.get(node.func.id)
            if pos is None:
                continue
            for p in pos:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    out[node.args[p].id] = node.lineno
        return out

    def _check_expr(self, expr: ast.expr, dead: Dict[str, int]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in dead:
                self.findings.append(self.sf.finding(
                    RULE_NAME, node,
                    f"'{node.id}' was donated on line {dead[node.id]} and "
                    f"may alias the output buffer; rebind instead of "
                    f"re-reading it"))


def check(sf: SourceFile) -> List[Finding]:
    donating, exempt = _collect_donating(sf.tree)
    if not donating:
        return []
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.lineno not in exempt:
            w = _ScopeWalker(sf, donating)
            w.walk(node)
            findings.extend(w.findings)
    return findings


RULE = Rule(
    name=RULE_NAME,
    doc="no reads of a name after passing it to a donating jit "
        "(donate_argnums / donate=True); rebind instead",
    file_check=check,
)
