"""Sharded evolution: shard_map(halo exchange + local stencil) under a
jitted scan — the driver loop of the reference (``main.cpp:291-305``)
re-expressed as one compiled program.

The reference's per-step ``MPI_Barrier`` (``main.cpp:297``) has no
equivalent here: inside jit, data dependence between the ppermute and the
stencil orders everything (SURVEY.md §5.8 barrier row).  The double-buffer
pointer swap (``main.cpp:294-296``) is buffer donation on the scan carry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax.shard_map is the public name on recent JAX
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from mpi_tpu.models.rules import Rule
from mpi_tpu.ops.stencil import counts_from_padded, apply_rule
from mpi_tpu.parallel.halo import exchange_halo
from mpi_tpu.parallel.mesh import AXES
from mpi_tpu.utils.hashinit import init_tile_jnp


def grid_sharding(mesh: Mesh, axes=AXES) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*axes))


def make_sharded_stepper(mesh: Mesh, rule: Rule, boundary: str, axes=AXES):
    """Returns evolve(grid, steps) running shard-parallel over the mesh.

    grid must be (rows, cols) uint8, rows % mesh[axes[0]] == 0 and
    cols % mesh[axes[1]] == 0; output keeps the same sharding.
    """
    spec = PartitionSpec(*axes)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec
    )
    def local_step(local):
        padded = exchange_halo(local, rule.radius, boundary, axes)
        counts = counts_from_padded(padded, rule.radius)
        return apply_rule(local, counts, rule)

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=0)
    def evolve(grid, steps: int):
        def body(g, _):
            return local_step(g), None

        out, _ = lax.scan(body, grid, None, length=steps)
        return out

    return evolve


def make_sharded_bit_stepper(mesh: Mesh, rule: Rule, boundary: str, axes=AXES):
    """Bitpacked (SWAR) shard-parallel evolution: grids are (rows, cols/32)
    uint32, 32 cells per lane.  The ghost ring is exchanged on packed words
    — one word column per side carries the cross-shard neighbor bits, the
    same ``ppermute`` pattern as the dense path but 32x fewer bytes per
    cell.  Radius-1 rules only (the packed adder tree is radius-1)."""
    from mpi_tpu.ops.bitlife import bit_next, column_sums

    if rule.radius != 1:
        raise ValueError("bitpacked sharded stepper supports radius-1 rules only")
    spec = PartitionSpec(*axes)

    @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
    def local_step(local):
        h, nw = local.shape
        p = exchange_halo(local, 1, boundary, axes)  # (h+2, nw+2) words
        # vertical column sums over the full padded width, once; the
        # left/right neighbor-word sums are then just column slices
        f0, f1, c0, c1 = column_sums(p[0:h], p[1 : h + 1], p[2 : h + 2])
        return bit_next(
            f0[:, 1:-1], f1[:, 1:-1], c0[:, 1:-1], c1[:, 1:-1],
            f0[:, 0:nw], f1[:, 0:nw], f0[:, 2:], f1[:, 2:],
            p[1 : h + 1, 1:-1], rule,
        )

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=0)
    def evolve(packed, steps: int):
        def body(g, _):
            return local_step(g), None

        out, _ = lax.scan(body, packed, None, length=steps)
        return out

    return evolve


def sharded_bit_init(mesh: Mesh, rows: int, cols: int, seed: int, axes=AXES):
    """Initialize the packed grid on-device, each shard hashing and packing
    its own global coordinates blockwise (no giant intermediates)."""
    from mpi_tpu.ops.bitlife import WORD, init_packed

    mi = mesh.shape[axes[0]]
    mj = mesh.shape[axes[1]]
    if rows % mi or cols % mj or (cols // mj) % WORD:
        raise ValueError(
            f"mesh {dict(mesh.shape)} incompatible with packed grid {rows}x{cols} "
            f"(per-shard cols must be a multiple of {WORD})"
        )
    lr, lc = rows // mi, cols // mj
    spec = PartitionSpec(*axes)

    @functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=spec)
    def init():
        ti = lax.axis_index(axes[0])
        tj = lax.axis_index(axes[1])
        return init_packed(
            lr, lc, seed,
            row_offset=ti.astype(jnp.uint32) * jnp.uint32(lr),
            col_offset=tj.astype(jnp.uint32) * jnp.uint32(lc),
        )

    return jax.jit(init, out_shardings=grid_sharding(mesh, axes))()


@functools.lru_cache(maxsize=None)
def make_sharded_unpacker(mesh: Mesh, axes=AXES):
    """Returns unpack(packed) -> uint8 grid, per-shard, same mesh sharding
    (for snapshot dumps); cached per (mesh, axes) so repeated snapshot
    calls reuse one compilation."""
    from mpi_tpu.ops.bitlife import unpack

    spec = PartitionSpec(*axes)
    f = shard_map(unpack, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(f, out_shardings=grid_sharding(mesh, axes))


def sharded_unpack(mesh: Mesh, packed, axes=AXES):
    return make_sharded_unpacker(mesh, axes)(packed)


def sharded_init(mesh: Mesh, rows: int, cols: int, seed: int, axes=AXES):
    """Initialize the grid directly on-device, each shard hashing its own
    global coordinates — no host-side global array, no scatter.  This is
    how a 65536² grid comes up without ever existing on one host."""
    mi = mesh.shape[axes[0]]
    mj = mesh.shape[axes[1]]
    if rows % mi or cols % mj:
        raise ValueError(f"mesh {dict(mesh.shape)} does not divide grid {rows}x{cols}")
    lr, lc = rows // mi, cols // mj
    spec = PartitionSpec(*axes)

    @functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=spec)
    def init():
        ti = lax.axis_index(axes[0])
        tj = lax.axis_index(axes[1])
        return init_tile_jnp(
            lr, lc, seed,
            row_offset=ti.astype(jnp.uint32) * jnp.uint32(lr),
            col_offset=tj.astype(jnp.uint32) * jnp.uint32(lc),
        )

    return jax.jit(init, out_shardings=grid_sharding(mesh, axes))()
