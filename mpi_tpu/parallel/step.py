"""Sharded evolution: shard_map(halo exchange + local stencil) under a
jitted scan — the driver loop of the reference (``main.cpp:291-305``)
re-expressed as one compiled program.

The reference's per-step ``MPI_Barrier`` (``main.cpp:297``) has no
equivalent here: inside jit, data dependence between the ppermute and the
stencil orders everything (SURVEY.md §5.8 barrier row).  The double-buffer
pointer swap (``main.cpp:294-296``) is buffer donation on the scan carry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax.shard_map is the public name on recent JAX
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_rep" in __import__("inspect").signature(_shard_map).parameters:
    # JAX 0.4.x's replication checker has no rule for pallas_call, so it
    # rejects the fused tile interiors outright; every stepper here pins
    # explicit out_specs, so the checker buys nothing and is disabled.
    # (psum(1, axis) — the axis_size shim — still constant-folds to a
    # Python int with the checker off; verified on 0.4.37.)
    def shard_map(f, **kwargs):
        kwargs.setdefault("check_rep", False)
        return _shard_map(f, **kwargs)
else:  # pragma: no cover — newer JAX dropped the flag
    shard_map = _shard_map

from mpi_tpu.models.rules import Rule
from mpi_tpu.ops.stencil import counts_from_padded, apply_rule
from mpi_tpu.parallel.halo import exchange_halo
from mpi_tpu.parallel.mesh import AXES, axis_size
from mpi_tpu.utils.hashinit import init_tile_jnp
from mpi_tpu.utils.segmenting import segmented_evolve


def grid_sharding(mesh: Mesh, axes=AXES) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*axes))


def _kill_outside_global(x, axes, margins):
    """Zero cells of x that lie outside the global grid: the (top, bottom,
    left, right) ``margins`` are ghost-deep fringes that only extend past the
    grid on the shards at the corresponding mesh edge (dead boundary)."""
    top, bottom, left, right = margins
    h, w = x.shape[0], x.shape[1]
    zero = jnp.zeros((), dtype=x.dtype)
    ri = lax.broadcasted_iota(jnp.int32, x.shape, 0)
    ci = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    i0 = lax.axis_index(axes[0])
    j0 = lax.axis_index(axes[1])
    ni = axis_size(axes[0])
    nj = axis_size(axes[1])
    if top:
        x = jnp.where((i0 == 0) & (ri < top), zero, x)
    if bottom:
        x = jnp.where((i0 == ni - 1) & (ri >= h - bottom), zero, x)
    if left:
        x = jnp.where((j0 == 0) & (ci < left), zero, x)
    if right:
        x = jnp.where((j0 == nj - 1) & (ci >= w - right), zero, x)
    return x


def dense_local_pallas_ok(local_shape, rule: Rule, k: int) -> bool:
    """Can the fused dense stencil kernel (``ops/pallas_stencil.py``) serve
    a (h, w) local tile's interior at k generations per exchange?  The
    kernel runs on the *unpadded* tile (lane-aligned width, slab-divisible
    rows — the alignment contract cannot hold on the ghost-padded shape),
    so the stitched-band structure supplies the cross-shard edges and
    needs ≥ 2·k·r rows and columns left over."""
    from mpi_tpu.ops.pallas_stencil import supports

    h, w = local_shape
    d = k * rule.radius
    return h >= 2 * d and w >= 2 * d and supports((h, w), rule, gens=k)


def make_sharded_stepper(
    mesh: Mesh, rule: Rule, boundary: str, axes=AXES, gens_per_exchange: int = 1,
    overlap: bool = False, use_pallas: bool = False,
    pallas_interpret: bool = False,
):
    """Returns evolve(grid, steps) running shard-parallel over the mesh.

    grid must be (rows, cols) uint8, rows % mesh[axes[0]] == 0 and
    cols % mesh[axes[1]] == 0; output keeps the same sharding.

    ``gens_per_exchange`` = K > 1 turns on communication-avoiding deep
    halos: one K·r-deep ghost exchange feeds K local generations, shrinking
    the valid fringe by r each generation (the redundant fringe compute is
    the price for 1/K as many collectives — the right trade when the
    ppermute rides DCN or the per-collective latency dominates, exactly the
    overlap the reference leaves on the table with its per-step barrier,
    ``/root/reference/main.cpp:297``).

    ``overlap=True``: the tile interior evolves its K generations from
    local data alone while the ppermute is in flight (no data dependency →
    XLA overlaps them); only the K·r-deep edge bands are recomputed from
    the exchanged halo and stitched in.  Dead boundary: the bands'
    outside-global fringe cells are re-killed each generation (the same
    discipline as the non-overlap path), masked per band side so a band's
    interior-facing side is never touched.

    ``use_pallas=True``: the tile *interior* runs through the fused dense
    temporal-blocking kernel (``ops.pallas_stencil.pallas_step`` at
    ``gens=K``) with dead tile-edge fill — bitwise identical to the XLA
    trapezoid on the kept region, because both evolve with zeros past the
    tile and every kept cell's K-generation dependence cone stays inside
    it — while the stitched edge bands stay on the XLA path (thin,
    misaligned slices the kernel's DMA contract cannot serve).  One
    kernel dispatch replaces the K per-generation stencil passes of a
    segment.  Taken per shard shape (:func:`dense_local_pallas_ok`);
    tiles the kernel cannot serve fall back to the XLA bodies.
    ``pallas_interpret`` runs the kernel in interpret mode (CPU-mesh
    tests).
    """
    K = gens_per_exchange
    r = rule.radius
    if K < 1:
        raise ValueError(f"gens_per_exchange must be >= 1, got {K}")
    if K > 1 and 0 in rule.birth:
        raise ValueError("gens_per_exchange > 1 requires a rule without birth-on-0")
    spec = PartitionSpec(*axes)
    dead = boundary != "periodic"

    def evolve_trapezoid(band, k, kill_sides=(0, 0, 0, 0)):
        """k generations, each trimming r cells per side (zeros beyond).
        ``kill_sides`` (top, bottom, left, right booleans): band sides whose
        still-remaining fringe lies outside the global grid on the mesh-edge
        shards — re-kill it each generation (dead boundary), so "births"
        in ghost space never feed back into real cells."""
        for g in range(k):
            counts = counts_from_padded(band, r)
            band = apply_rule(band[r:-r, r:-r], counts, rule)
            m = (k - 1 - g) * r
            if dead and m and any(kill_sides):
                t, b, l, ri = kill_sides
                band = _kill_outside_global(
                    band, axes, (m * t, m * b, m * l, m * ri)
                )
        return band

    def make_local(k):
        def body_exchange_all(local):
            padded = exchange_halo(local, k * r, boundary, axes)
            for g in range(k):
                mid = padded[r:-r, r:-r]
                counts = counts_from_padded(padded, r)
                padded = apply_rule(mid, counts, rule)
                fringe = (k - 1 - g) * r
                if dead and fringe:
                    # fringe cells outside the global grid are not real
                    # cells; re-kill any "born" from live grid neighbors
                    padded = _kill_outside_global(
                        padded, axes, (fringe,) * 4
                    )
            return padded

        def interior_xla(local):
            # interior from local data alone: trapezoid over the
            # zero-padded tile, keeping rows [d, h-d) (full width; the
            # invalid outer-d columns are replaced by lb/rb in the stitch)
            d = k * r
            return evolve_trapezoid(jnp.pad(local, d), k)[d:-d, :]

        def interior_pallas(local):
            # fused temporal-blocking kernel, dead tile-edge fill == the
            # zero-pad semantics of interior_xla, so the kept region
            # matches bit-for-bit (the ≤ d-deep corrupt fringe from the
            # tile edge lies entirely in the replaced rows/columns)
            from mpi_tpu.ops.pallas_stencil import pallas_step

            h = local.shape[0]
            d = k * r
            return pallas_step(
                local, rule, "dead", interpret=pallas_interpret, gens=k
            )[d : h - d, :]

        def body_overlap(local, interior):
            h, w = local.shape
            d = k * r  # ghost/band depth
            padded = exchange_halo(local, d, boundary, axes)  # (h+2d, w+2d)
            # interior (rows/cols [d, size-d)) from local data alone —
            # independent of the ppermute, so the two overlap; the
            # invalid outer-d columns are replaced by lb/rb below.  (No
            # dead-boundary kill needed: every kept cell is >= d from the
            # tile edge, out of reach of the zero-pad fringe.)
            q = interior(local)
            # edge bands from the exchanged halo, full cross dimension so
            # corners are exact; band output coord i = input coord i + d.
            # kill_sides: each band's outward + lateral sides can lie
            # outside the global grid on edge shards; its inward side is
            # always tile interior and must never be killed.
            tb = evolve_trapezoid(padded[: 4 * d], k, (1, 0, 1, 1))[:d]
            bb = evolve_trapezoid(padded[h - 2 * d :], k, (0, 1, 1, 1))[d:]
            lb = evolve_trapezoid(padded[:, : 4 * d], k, (1, 1, 1, 0))[:, :d]
            rb = evolve_trapezoid(padded[:, w - 2 * d :], k, (1, 1, 0, 1))[:, d:]
            core = jnp.concatenate([tb, q, bb], axis=0)          # (h, w)
            return jnp.concatenate(
                [lb, core[:, d : w - d], rb], axis=1
            )

        @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
        def local_step(local):
            h, w = local.shape
            if use_pallas and dense_local_pallas_ok((h, w), rule, k):
                # fused interior + stitched bands: also the overlap
                # structure, so a requested overlap is inherently honored
                return body_overlap(local, interior_pallas)
            if overlap and min(h, w) >= 2 * k * r:
                return body_overlap(local, interior_xla)
            return body_exchange_all(local)

        return local_step

    return segmented_evolve(make_local, K)


def make_halo_probe(mesh: Mesh, boundary: str, radius: int = 1, axes=AXES):
    """A jitted program that performs ONE ghost-ring exchange and nothing
    else — the observability layer's probe for the halo seam
    (``obs/devmem.py``).  The real exchanges run inside the jitted
    steppers where host-side timing cannot see them; this isolates the
    same ``exchange_halo`` collective so its wall can be sampled on the
    telemetry cadence.  Output keeps each shard's ghost-extended tile
    (no reduction: nothing but the exchange is timed)."""
    spec = PartitionSpec(*axes)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
    def probe(local):
        return exchange_halo(local, radius, boundary, axes)

    return probe


WORD_BITS = 32  # cells per packed uint32 word (ops.bitlife.WORD)


def _mask_pad_cols(x, axes, ghost_words: int, tile_words: int, pad_bits: int):
    """Zero the trailing ``pad_bits`` GLOBAL cell columns of a padded
    packed grid (pad-to-32 routing, VERDICT r3 item 3): the pad region
    lies outside the global grid, so — exactly like the ghost-fringe kill
    discipline — any "births" the rule writes there must die before they
    can feed back into real cells.  Masking is by global column (the
    word-aligned shard boundaries of the padded grid can land the
    real/pad edge inside any shard when tiles are narrow), computed from
    this shard's column position.  ``x`` is packed (rows, ghost_words +
    tile_words + ghost_words); ghost word columns are masked by the SAME
    global-column rule — a neighbor's word that overlaps the pad region
    carries pad cells (an interior shard's ghost is not covered by the
    mesh-edge ghost-kill, and unmasked pad births there would re-enter
    real cells within a multi-generation segment).  LSB-first packing:
    word w's bit b is shard cell 32·w + b."""
    if pad_bits <= 0:
        return x
    j = lax.axis_index(axes[1])
    nj = axis_size(axes[1])
    col_limit = nj * tile_words * WORD_BITS - pad_bits  # real global cols
    nw = x.shape[1]
    w_iota = jnp.arange(nw, dtype=jnp.int32) - ghost_words
    gbit = (j.astype(jnp.int32) * tile_words + w_iota) * WORD_BITS
    v = jnp.clip(col_limit - gbit, 0, WORD_BITS)
    full = jnp.uint32(0xFFFFFFFF)
    mask = jnp.where(
        v >= WORD_BITS, full,
        (jnp.uint32(1) << v.astype(jnp.uint32)) - jnp.uint32(1),
    )
    return x & mask[None, :]


def bit_local_pallas_ok(local_packed_shape, rule: Rule, k: int) -> bool:
    """Can the fused SWAR kernel (``ops/pallas_bitlife.py``) serve a
    (h, nw)-packed local tile's interior at k generations per exchange?
    The kernel runs on the *unpadded* tile (its alignment contract —
    lane-aligned width, slab-divisible rows — cannot hold on the
    ghost-padded shape), so the stitched-band structure supplies the
    cross-shard edges and needs h ≥ 2k rows and ≥ 2 word columns."""
    from mpi_tpu.ops.bitlife import WORD
    from mpi_tpu.ops.pallas_bitlife import supports

    h, nw = local_packed_shape
    return h >= 2 * k and nw >= 2 and supports((h, nw * WORD), rule, gens=k)


def ltl_local_pallas_ok(local_packed_shape, rule: Rule, k: int) -> bool:
    """LtL analog of :func:`bit_local_pallas_ok`: the fused bit-sliced
    kernel serves the tile interior in chunks of ≤ ``max_gens(r)``
    generations per HBM pass, so any k with k·r ≤ 31 is reachable."""
    from mpi_tpu.ops.bitlife import WORD
    from mpi_tpu.ops.pallas_bitltl import max_gens, supports

    h, nw = local_packed_shape
    d = k * rule.radius
    return (
        h >= 2 * d
        and nw >= 2
        and supports((h, nw * WORD), rule,
                     gens=min(k, max_gens(rule.radius)))
    )


def make_sharded_bit_stepper(
    mesh: Mesh, rule: Rule, boundary: str, axes=AXES, gens_per_exchange: int = 1,
    overlap: bool = False, use_pallas: bool = False,
    pallas_interpret: bool = False, pad_bits: int = 0, seam_pad: bool = False,
):
    """Bitpacked (SWAR) shard-parallel evolution: grids are (rows, cols/32)
    uint32, 32 cells per lane.  The ghost ring is exchanged on packed words
    — one word column per side carries the cross-shard neighbor bits, the
    same ``ppermute`` pattern as the dense path but 32x fewer bytes per
    cell.  Radius-1 rules only (the packed adder tree is radius-1).

    ``gens_per_exchange`` = K > 1: one exchange of K ghost rows (and still
    a single ghost word column — 32 halo bits cover any K ≤ 16) feeds K
    local generations.  The ghost word columns are recomputed each
    generation with zeros past the padding, which corrupts them one bit
    per generation inward from the far edge — harmless while K ≤ 31 — and
    the vertical fringe shrinks one row per generation, reaching exactly
    the local tile after K.  Collective count drops K×.

    ``overlap=True`` removes the data dependency between
    the ppermute and the bulk of the stencil — the optimization the
    reference's barrier-then-exchange loop forgoes entirely
    (``/root/reference/main.cpp:297-299``): the tile interior evolves K
    generations from local data alone (valid rows shrink to [K, h-K)
    under the trapezoid rule) while the collective is in flight, and only
    the K edge rows per side plus the outermost word columns are
    recomputed from the exchanged halo and stitched in.  XLA's async
    collectives + latency-hiding scheduler overlap the two automatically
    once the dependency is gone.

    ``use_pallas=True`` (VERDICT r3 item 1): the tile *interior* runs
    through the fused single-chip SWAR kernel
    (``ops.pallas_bitlife.pallas_bit_step``) with dead tile-edge fill —
    bitwise identical to the XLA trapezoid on the kept rows [K, h-K),
    because both evolve with zeros past the tile and every kept cell's
    dependence cone stays inside it — while the stitched edge bands stay
    on the XLA path (they are thin, misaligned slices the kernel's DMA
    contract cannot serve).  This keeps multi-chip runs on the ~6.5×
    faster fused compute instead of dropping to the XLA SWAR path the
    moment a mesh appears (the hot loop the reference splits into
    ``updateBoard`` + ``distr_borders``, ``/root/reference/main.cpp:
    93-103,36-65``).  Taken per shard shape (:func:`bit_local_pallas_ok`);
    tiles the kernel cannot serve fall back to the XLA bodies.
    ``pallas_interpret`` runs the kernel in interpret mode (CPU-mesh
    tests).

    ``pad_bits`` > 0 (pad-to-32 routing, VERDICT r3 item 3): the grid was
    padded with that many trailing dead cell columns to reach word
    alignment; they are re-killed after every generation on the last
    column shard.  K > 1 forces the exchange-all body (its
    per-generation loop is where the mask lives); at K = 1 every body —
    including the fused Pallas interior — is masked once per step, which
    is every generation.

    ``seam_pad`` (VERDICT r4 item 5): permits ``pad_bits`` with the
    PERIODIC boundary, for use under ``parallel.seam.make_seam_stepper``
    only.  The column wrap then reads the (always re-killed) pad — i.e.
    zeros — so cells within K·r real columns of the wrap seam are
    computed with dead-wrap semantics; the seam wrapper recomputes
    exactly those columns with a true-periodic dense band and stitches
    them over this stepper's output.  Standalone padded-periodic use
    stays rejected: without the wrapper the seam columns are wrong.
    """
    from mpi_tpu.ops.bitlife import bit_next, column_sums
    from mpi_tpu.parallel.halo import exchange_halo_rc

    K = gens_per_exchange
    if rule.radius != 1:
        raise ValueError("bitpacked sharded stepper supports radius-1 rules only")
    if not 1 <= K <= 16:
        raise ValueError(f"gens_per_exchange must be in 1..16, got {K}")
    if K > 1 and 0 in rule.birth:
        raise ValueError("gens_per_exchange > 1 requires a rule without birth-on-0")
    if pad_bits and boundary == "periodic" and not seam_pad:
        raise ValueError(
            "pad_bits with the periodic boundary is only correct under "
            "the seam-stitching wrapper (parallel.seam); pass seam_pad=True"
        )
    spec = PartitionSpec(*axes)
    periodic = boundary == "periodic"

    def one_gen(p, rule):
        """Next state of rows [1, n-1) of p, over the full word width with
        zeros past the array (callers mask/trim the edges)."""
        n, w = p.shape
        zcol = jnp.zeros((n - 2, 1), dtype=p.dtype)
        f0, f1, c0, c1 = column_sums(p[0 : n - 2], p[1 : n - 1], p[2:n])
        f0p = jnp.concatenate([zcol, f0[:, :-1]], axis=1)
        f1p = jnp.concatenate([zcol, f1[:, :-1]], axis=1)
        f0n = jnp.concatenate([f0[:, 1:], zcol], axis=1)
        f1n = jnp.concatenate([f1[:, 1:], zcol], axis=1)
        return bit_next(f0, f1, c0, c1, f0p, f1p, f0n, f1n, p[1 : n - 1], rule)

    def evolve_band(band, k, kill_sides=(0, 0, 0, 0)):
        """k generations over a row band (zeros assumed past every edge);
        each generation trims one row per side — trapezoid validity.
        ``kill_sides`` (top, bottom, left, right): band sides that lie
        outside the global grid on mesh-edge shards, re-killed each
        generation (dead boundary).  Row margins shrink with the trapezoid
        ((k-1-g) rows); lateral margins are whole ghost word columns."""
        for g in range(k):
            band = one_gen(band, rule)
            if not periodic and any(kill_sides):
                m = k - 1 - g
                t, b, l, ri = kill_sides
                margins = (m * t, m * b, l, ri)
                if any(margins):
                    band = _kill_outside_global(band, axes, margins)
        return band

    def make_local(k):
        def body_exchange_all(local):
            # k-deep ghost rows, one ghost word column: (h+2k, nw+2)
            p = exchange_halo_rc(local, k, 1, boundary, axes)
            for g in range(k):
                p = one_gen(p, rule)
                fringe = k - 1 - g
                if not periodic and fringe:
                    # fringe rows / the ghost word columns lie outside the
                    # global grid on the edge shards — re-kill them (margins
                    # in packed units: rows are rows, columns are words)
                    p = _kill_outside_global(p, axes, (fringe, fringe, 1, 1))
                if pad_bits and g < k - 1:
                    # intermediate generations: pad columns are outside the
                    # global grid too (the final generation is masked once
                    # for all bodies in local_step)
                    p = _mask_pad_cols(p, axes, 1, p.shape[1] - 2, pad_bits)
            return p[:, 1:-1]

        def interior_pallas(local):
            # fused kernel, dead tile-edge fill == the zero-past-array
            # semantics of one_gen, so rows [k, h-k) match evolve_band
            # bit-for-bit (corrupt outer word columns are replaced below)
            from mpi_tpu.ops.pallas_bitlife import pallas_bit_step

            h = local.shape[0]
            return pallas_bit_step(
                local, rule, "dead", interpret=pallas_interpret, gens=k
            )[k : h - k, :]

        def body_overlap(local, interior):
            h, nw = local.shape
            p = exchange_halo_rc(local, k, 1, boundary, axes)  # (h+2k, nw+2)
            # Interior: k generations from `local` alone — independent of
            # the ppermute above, so the scheduler can overlap them.
            # Trapezoid validity: rows [k, h-k) of the tile; edge-word bit
            # corruption (< k bits from the zero-assumed sides) lies in
            # the word columns replaced below.
            q = interior(local)  # (h-2k, nw)
            # Edge bands from the exchanged halo (full padded width, so
            # their corners are exact): output row i = input row i+k.
            # kill_sides: outward + lateral sides only — a band's
            # interior-facing side is tile interior even on edge shards.
            tb = evolve_band(p[: 4 * k], k, (1, 0, 1, 1))[:k, 1:-1]
            bb = evolve_band(p[h - 2 * k :], k, (0, 1, 1, 1))[k:, 1:-1]
            lb = evolve_band(p[:, :3], k, (1, 1, 1, 0))[:, 1:2]
            rb = evolve_band(p[:, nw - 1 :], k, (1, 1, 0, 1))[:, 1:2]
            core = jnp.concatenate([tb, q, bb], axis=0)      # (h, nw)
            return jnp.concatenate([lb, core[:, 1 : nw - 1], rb], axis=1)

        @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
        def local_step(local):
            h, nw = local.shape
            if pad_bits and k > 1:
                # multi-generation bodies need the pad re-killed between
                # generations — only exchange-all carries that loop
                out = body_exchange_all(local)
            elif use_pallas and bit_local_pallas_ok((h, nw), rule, k):
                # fused interior + stitched bands: also the overlap
                # structure, so a requested overlap is inherently honored
                out = body_overlap(local, interior_pallas)
            elif overlap and h >= 2 * k and nw >= 2:
                out = body_overlap(local, lambda t: evolve_band(t, k))
            else:
                out = body_exchange_all(local)
            if pad_bits:
                out = _mask_pad_cols(out, axes, 0, nw, pad_bits)
            return out

        return local_step

    # seam_pad steppers run nested under make_seam_stepper's jit, which
    # still reads the pre-step grid for the band — they must not donate
    # (see segmented_evolve: the aliasing hint races the band read on
    # multi-device meshes); the outer seam jit carries the donation
    return segmented_evolve(make_local, K, donate=not seam_pad)


def make_sharded_ltl_stepper(
    mesh: Mesh, rule: Rule, boundary: str, axes=AXES, gens_per_exchange: int = 1,
    overlap: bool = False, use_pallas: bool = False,
    pallas_interpret: bool = False, pad_bits: int = 0, seam_pad: bool = False,
):
    """Bit-sliced radius-r shard-parallel evolution: packed (rows,
    cols/32) uint32 grids, the LtL generalization of
    ``make_sharded_bit_stepper``.  One exchange ships K·r ghost rows and
    a single ghost word column (32 halo bits cover K·r ≤ 31), then
    ``ops.bitltl.ltl_step`` runs K generations on the padded tile with
    its *dead* (zero-fill) tile-edge semantics — correct regardless of
    the global boundary, because the cropped interior's dependence cone
    only ever touches ghost data, and every cell the zero fill can reach
    is cropped.  Dead global boundary: the ghost fringe is re-killed on
    mesh-edge shards after every generation so ghost-space "births"
    never feed back (same discipline as the radius-1 stepper).

    ``overlap=True``: stitched-band comm/compute overlap, the LtL
    generalization of ``make_sharded_bit_stepper``'s ``body_overlap``
    (VERDICT r2 item 2 — a radius>1 ``--overlap`` run must not fall off
    the bit-sliced engine).  The tile interior evolves its K generations
    from local data alone (no dependence on the ppermute, so XLA's
    latency-hiding scheduler overlaps them); only the d = K·r edge rows
    per side and the outermost word columns are recomputed from the
    exchanged halo and stitched in.  Unlike the radius-1 bands,
    ``ltl_step`` is shape-preserving (no trapezoid trimming), so band
    validity is by *cropping*: after k generations the zero-fill
    corruption has crept d ≤ 31 bits/rows in from each artificial band
    cut, and every kept cell is at least d away from one.  The lateral
    bands are 4 word columns wide — 3 (as in the radius-1 stepper) only
    works while corruption depth + dependence depth ≤ 32, i.e. d ≤ 16.

    ``use_pallas=True`` (VERDICT r3 item 1): the tile interior runs
    through the fused bit-sliced LtL kernel
    (``ops.pallas_bitltl.pallas_ltl_step``) with dead tile-edge fill, in
    chunks of ≤ ``max_gens(r)`` generations per HBM pass — each chunk is
    bitwise identical to the same count of ``ltl_step(·, "dead")``
    applications, so the composition is too and the cropped interior
    matches the XLA path exactly.  Stitched bands stay on the XLA path;
    per-shard dispatch via :func:`ltl_local_pallas_ok` with XLA
    fallback.  ``pallas_interpret`` for CPU-mesh tests.

    ``pad_bits``: trailing dead pad columns re-killed every generation on
    the last column shard (pad-to-32 routing; K > 1 forces the
    exchange-all body — see ``make_sharded_bit_stepper``).  ``seam_pad``
    permits pad_bits with the periodic boundary for use under
    ``parallel.seam.make_seam_stepper`` only (same contract as the
    radius-1 stepper: the wrapper owns the seam columns)."""
    from mpi_tpu.ops.bitltl import ltl_step
    from mpi_tpu.parallel.halo import exchange_halo_rc

    K = gens_per_exchange
    r = rule.radius
    if K < 1 or K * r > 31:
        raise ValueError(
            f"gens_per_exchange must satisfy 1 <= K and K*r <= 31 "
            f"(one ghost word column), got K={K}, r={r}"
        )
    if K > 1 and 0 in rule.birth:
        raise ValueError("gens_per_exchange > 1 requires a rule without birth-on-0")
    if pad_bits and boundary == "periodic" and not seam_pad:
        raise ValueError(
            "pad_bits with the periodic boundary is only correct under "
            "the seam-stitching wrapper (parallel.seam); pass seam_pad=True"
        )
    spec = PartitionSpec(*axes)
    periodic = boundary == "periodic"

    def make_local(k):
        d = k * r

        def step_gens(band, kill=None, pad_ghost_words=None):
            """k generations with dead tile-edge fill; ``kill`` gives the
            (top, bottom, left-words, right-words) outside-global margins
            re-killed on mesh-edge shards between generations (the final
            generation's corrupt fringe is cropped by the caller).
            ``pad_ghost_words``: when set, the trailing ``pad_bits`` pad
            columns (offset by that many ghost word columns) are also
            re-killed between generations."""
            for g in range(k):
                band = ltl_step(band, rule, "dead")
                if g < k - 1:
                    if not periodic and kill is not None:
                        band = _kill_outside_global(band, axes, kill)
                    if pad_bits and pad_ghost_words is not None:
                        band = _mask_pad_cols(
                            band, axes, pad_ghost_words,
                            band.shape[1] - 2 * pad_ghost_words, pad_bits,
                        )
            return band

        def body_exchange_all(local):
            p = exchange_halo_rc(local, d, 1, boundary, axes)
            # every ghost row / ghost word column on a mesh-edge shard
            # lies outside the global grid — dead cells by definition
            return step_gens(p, (d, d, 1, 1),
                             pad_ghost_words=1 if pad_bits else None)[d:-d, 1:-1]

        def interior_pallas(local):
            # fused kernel in ≤ max_gens(r) chunks; each chunk ==
            # the same count of ltl_step(·, "dead") applications, so
            # the composition matches step_gens bit-for-bit
            from mpi_tpu.ops.pallas_bitltl import max_gens, pallas_ltl_step

            out = local
            left = k
            while left > 0:
                g = min(left, max_gens(r))
                out = pallas_ltl_step(
                    out, rule, "dead", interpret=pallas_interpret, gens=g
                )
                left -= g
            return out

        def body_overlap(local, interior):
            h, nw = local.shape
            p = exchange_halo_rc(local, d, 1, boundary, axes)  # (h+2d, nw+2)
            # Interior: k gens from `local` alone — independent of the
            # ppermute, so the two overlap.  Kept rows [d, h-d) and word
            # cols [1, nw-1): every kept cell's cone stays d rows / ≤ 31
            # bits inside the tile, beyond reach of the zero-fill at the
            # tile edge (and of ghost-space births — no kill needed).
            q = interior(local)[d : h - d, :]
            # Edge bands from the exchanged halo, full cross dimension so
            # corners are exact; band coords = padded coords (shifted for
            # bb/rb).  Kill margins match body_exchange_all's where the
            # padded margin lies inside the band.
            tb = step_gens(p[: 4 * d], (d, 0, 1, 1))[d : 2 * d, 1:-1]
            bb = step_gens(p[h - 2 * d :], (0, d, 1, 1))[2 * d : 3 * d, 1:-1]
            lb = step_gens(p[:, :4], (d, d, 1, 0))[d : h + d, 1:2]
            rb = step_gens(p[:, nw - 2 :], (d, d, 0, 1))[d : h + d, 2:3]
            core = jnp.concatenate([tb, q, bb], axis=0)      # (h, nw)
            return jnp.concatenate([lb, core[:, 1 : nw - 1], rb], axis=1)

        @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
        def local_step(local):
            h, nw = local.shape
            if pad_bits and k > 1:
                out = body_exchange_all(local)
            elif use_pallas and ltl_local_pallas_ok((h, nw), rule, k):
                out = body_overlap(local, interior_pallas)
            elif overlap and h >= 2 * d and nw >= 2:
                out = body_overlap(local, step_gens)
            else:
                out = body_exchange_all(local)
            if pad_bits:
                out = _mask_pad_cols(out, axes, 0, nw, pad_bits)
            return out

        return local_step

    # seam_pad steppers run nested under make_seam_stepper's jit, which
    # still reads the pre-step grid for the band — they must not donate
    # (see segmented_evolve: the aliasing hint races the band read on
    # multi-device meshes); the outer seam jit carries the donation
    return segmented_evolve(make_local, K, donate=not seam_pad)


def sharded_bit_init(mesh: Mesh, rows: int, cols: int, seed: int, axes=AXES,
                     col_limit=None):
    """Initialize the packed grid on-device, each shard hashing and packing
    its own global coordinates blockwise (no giant intermediates).
    ``col_limit``: global columns ≥ this start dead (pad-to-32 routing —
    the hash stays decomposition-invariant for the real cells)."""
    from mpi_tpu.ops.bitlife import WORD, init_packed

    mi = mesh.shape[axes[0]]
    mj = mesh.shape[axes[1]]
    if rows % mi or cols % mj or (cols // mj) % WORD:
        raise ValueError(
            f"mesh {dict(mesh.shape)} incompatible with packed grid {rows}x{cols} "
            f"(per-shard cols must be a multiple of {WORD})"
        )
    lr, lc = rows // mi, cols // mj
    spec = PartitionSpec(*axes)

    @functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=spec)
    def init():
        ti = lax.axis_index(axes[0])
        tj = lax.axis_index(axes[1])
        return init_packed(
            lr, lc, seed,
            row_offset=ti.astype(jnp.uint32) * jnp.uint32(lr),
            col_offset=tj.astype(jnp.uint32) * jnp.uint32(lc),
            col_limit=col_limit,
        )

    return jax.jit(init, out_shardings=grid_sharding(mesh, axes))()


@functools.lru_cache(maxsize=None)
def make_sharded_unpacker(mesh: Mesh, axes=AXES):
    """Returns unpack(packed) -> uint8 grid, per-shard, same mesh sharding
    (for snapshot dumps); cached per (mesh, axes) so repeated snapshot
    calls reuse one compilation."""
    from mpi_tpu.ops.bitlife import unpack

    spec = PartitionSpec(*axes)
    f = shard_map(unpack, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(f, out_shardings=grid_sharding(mesh, axes))


def sharded_unpack(mesh: Mesh, packed, axes=AXES):
    return make_sharded_unpacker(mesh, axes)(packed)


def sharded_init(mesh: Mesh, rows: int, cols: int, seed: int, axes=AXES):
    """Initialize the grid directly on-device, each shard hashing its own
    global coordinates — no host-side global array, no scatter.  This is
    how a 65536² grid comes up without ever existing on one host."""
    mi = mesh.shape[axes[0]]
    mj = mesh.shape[axes[1]]
    if rows % mi or cols % mj:
        raise ValueError(f"mesh {dict(mesh.shape)} does not divide grid {rows}x{cols}")
    lr, lc = rows // mi, cols // mj
    spec = PartitionSpec(*axes)

    @functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=spec)
    def init():
        ti = lax.axis_index(axes[0])
        tj = lax.axis_index(axes[1])
        return init_tile_jnp(
            lr, lc, seed,
            row_offset=ti.astype(jnp.uint32) * jnp.uint32(lr),
            col_offset=tj.astype(jnp.uint32) * jnp.uint32(lc),
        )

    return jax.jit(init, out_shardings=grid_sharding(mesh, axes))()
