"""Distributed execution: device mesh, halo exchange, sharded stepper."""

from mpi_tpu.parallel.mesh import make_mesh
from mpi_tpu.parallel.halo import exchange_halo
from mpi_tpu.parallel.policy import choose_comm_policy
from mpi_tpu.parallel.step import (
    make_sharded_stepper,
    sharded_init,
    make_sharded_bit_stepper,
    make_sharded_ltl_stepper,
    sharded_bit_init,
    sharded_unpack,
)

__all__ = [
    "make_mesh",
    "exchange_halo",
    "choose_comm_policy",
    "make_sharded_stepper",
    "sharded_init",
    "make_sharded_bit_stepper",
    "make_sharded_ltl_stepper",
    "sharded_bit_init",
    "sharded_unpack",
]
