"""Device-mesh construction — the TPU-native equivalent of the reference's
2D Cartesian MPI topology (``MPI_Dims_create`` + ``MPI_Cart_create`` with
``reorder=1``, ``/root/reference/main.cpp:242-250``).

``mesh_utils.create_device_mesh`` plays the role of ``reorder=1``: it
permutes devices so that mesh-adjacent shards are ICI-adjacent chips, which
is what keeps the halo ``ppermute`` traffic on nearest-neighbor links.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

AXIS_ROWS = "gi"   # mesh axis sharding grid rows
AXIS_COLS = "gj"   # mesh axis sharding grid cols
AXES: Tuple[str, str] = (AXIS_ROWS, AXIS_COLS)


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis, from inside ``shard_map``.

    ``jax.lax.axis_size`` only exists in newer JAX; on older versions the
    classic ``psum(1, axis)`` idiom constant-folds to a Python int (the
    callers use the result in ``range()``/``if``, so it must be static
    either way)."""
    import jax.lax as lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def choose_mesh_shape(n_devices: int) -> Tuple[int, int]:
    """Most-square 2D factorization of n (the ``MPI_Dims_create`` analog).

    Prefers shapes like (2,4) over (1,8): a squarer mesh halves halo bytes
    per shard at large grids (perimeter vs area).
    """
    best = (1, n_devices)
    for a in range(1, int(np.sqrt(n_devices)) + 1):
        if n_devices % a == 0:
            best = (a, n_devices // a)
    return best


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = AXES,
) -> Mesh:
    """A 2D Mesh over the given (default: all) devices.  shape=None picks
    the most-square factorization; (n, 1) / (1, n) give 1D row / column
    decomposition."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = choose_mesh_shape(n)
    want = shape[0] * shape[1]
    if want < n and devices[0].platform == "cpu" and jax.process_count() == 1:
        # On virtual CPU devices (tests) an explicit smaller mesh runs on
        # a device prefix, like the reference's mpirun -np < cores.  Real
        # TPU devices keep the strict check: an arbitrary chip subset is
        # not a torus (create_device_mesh can fail to find an assignment),
        # and under multihost a subset could exclude all of some host's
        # addressable devices.
        devices = devices[:want]
        n = want
    if want != n:
        raise ValueError(f"mesh shape {shape} needs {want} devices, have {n}")
    if devices[0].platform == "cpu":
        # Virtual CPU devices (tests) have no ICI topology to optimize over.
        dev_array = np.asarray(devices).reshape(shape)
    else:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(dev_array, axis_names)
