"""Periodic wrap-seam stitching for padded packed grids (VERDICT r4
item 5): periodic boundaries on non-word-aligned widths ride the packed
engines instead of falling to the dense path.

The obstruction: the SWAR/bit-sliced engines shift whole uint32 words,
so a periodic wrap that lands mid-word (real width C not a multiple of
32 per shard) cannot be expressed in word arithmetic — rounds 2-4 kept
such runs on the dense engine (~6-25x slower; the reference's serial
oracle defines the semantics, ``/root/reference/main_serial.cpp:57``).

The fix reuses the stitched-band idea the overlap path already proves
out (``parallel/step.py body_overlap``): pad the grid with trailing
dead columns to word alignment and run the PERIODIC padded stepper as
the base — its row wrap is exact, and its column wrap reads the
(re-killed every generation) pad columns, i.e. zeros, so the only wrong
cells are those whose dependence cone crosses the seam: the ``d = K·r``
real columns on each side of it.  Those are recomputed exactly by a
thin dense band — the 4d real columns centered on the seam, extracted
from the pre-step grid, evolved K generations with true periodic row
wrap and zero column fill (valid middle 2d by the trapezoid argument) —
and stitched over the base output by word masking.  The band is
O(rows · 8·K·r) cells per segment against O(rows · C) for the base: the
seam costs a sliver of dense compute, not the whole grid.

All band/stitch ops are static-shape global-index slices; under a mesh
they touch only the word columns at the grid's left and right edges, so
XLA lowers them to work on the edge shards plus one tiny
collective-permute pair per segment (the same wrap neighbors the
ppermute halo already talks to).
"""

from __future__ import annotations

import jax.numpy as jnp

from mpi_tpu.models.rules import Rule
from mpi_tpu.ops.bitlife import WORD, unpack
from mpi_tpu.ops.stencil import apply_rule, counts_from_padded
from mpi_tpu.utils.segmenting import segmented_evolve


def seam_serves(C: int, d: int) -> bool:
    """THE seam-eligibility predicate — the single source of truth for
    routing (``plan_pad_width``) and construction (``band_cols``), so
    the two can never drift: depth d = K·r must fit the word-mask/ghost
    bound (≤ 31) and the 4d strip must not wrap onto itself (C ≥ 4d)."""
    return 1 <= d <= 31 and C >= 4 * d


def band_cols(C: int, d: int):
    """The band geometry: input strip = real cols [C-2d, C) ++ [0, 2d)
    (the 4d real columns centered on the wrap seam, contiguous in
    periodic space); valid output after k gens = the middle 2d = real
    cols [C-d, C) ++ [0, d)."""
    if not 1 <= d <= 31:
        raise ValueError(f"seam band depth must be in 1..31, got {d}")
    if not seam_serves(C, d):
        raise ValueError(
            f"seam stitching needs width >= {4 * d} (got {C}); tiny "
            f"grids keep the dense engine"
        )
    return 4 * d


def extract_band(packed, C: int, d: int):
    """(rows, 4d) uint8 strip of real cols [C-2d, C) ++ [0, 2d) from the
    padded packed grid (real cols occupy padded cols [0, C) contiguously
    — the pad is all trailing)."""
    band_cols(C, d)
    lw1 = (2 * d - 1) // WORD
    left = unpack(packed[:, : lw1 + 1])[:, : 2 * d]
    rw0, rw1 = (C - 2 * d) // WORD, (C - 1) // WORD
    roff = (C - 2 * d) - rw0 * WORD
    right = unpack(packed[:, rw0 : rw1 + 1])[:, roff : roff + 2 * d]
    return jnp.concatenate([right, left], axis=1)


def evolve_band(band, rule: Rule, k: int):
    """k generations of the dense strip: exact periodic row wrap each
    generation, zero column fill — column-edge corruption creeps r
    cells/generation inward, so the middle 2d columns are exact after
    k gens (trapezoid validity, same argument as the overlap bands)."""
    r = rule.radius
    for _ in range(k):
        x = jnp.concatenate([band[-r:], band, band[:r]], axis=0)
        x = jnp.pad(x, ((0, 0), (r, r)))
        counts = counts_from_padded(x, r)
        band = apply_rule(x[r:-r, r:-r], counts, rule)
    return band


def _blend_cols(packed, dense, g0: int, L: int):
    """Overwrite global padded cell columns [g0, g0+L) of the packed grid
    with the (rows, L) uint8 ``dense`` block, by word masking (L <= 31,
    so at most two word columns are touched; all indices static)."""
    w0, w1 = g0 // WORD, (g0 + L - 1) // WORD
    out = packed
    for w in range(w0, w1 + 1):
        c0 = max(g0, w * WORD)
        c1 = min(g0 + L, (w + 1) * WORD)
        mask = jnp.uint32(0)
        val = jnp.zeros(packed.shape[0], dtype=jnp.uint32)
        for c in range(c0, c1):
            b = jnp.uint32(c - w * WORD)
            mask = mask | (jnp.uint32(1) << b)
            val = val | (dense[:, c - g0].astype(jnp.uint32) << b)
        out = out.at[:, w].set((out[:, w] & ~mask) | val)
    return out


def stitch_band(packed, band, C: int, d: int):
    """Write the band's valid middle back over the seam: real cols
    [C-d, C) (strip cols [d, 2d)) and [0, d) (strip cols [2d, 3d))."""
    packed = _blend_cols(packed, band[:, d : 2 * d], C - d, d)
    packed = _blend_cols(packed, band[:, 2 * d : 3 * d], 0, d)
    return packed


def make_seam_stepper(inner, rule: Rule, C: int, K: int):
    """evolve(grid, steps) wrapping a padded PERIODIC packed stepper
    ``inner`` (built with ``seam_pad`` pad_bits — see
    ``make_sharded_bit_stepper``): each k-generation segment runs the
    base step and the dense seam band concurrently (no data dependence
    between them — the band reads the pre-step grid, so XLA can overlap
    the tiny dense stencil with the big packed one) and stitches the
    band's exact seam columns over the base output.

    ``C`` is the REAL width (the padded width is whatever ``inner``'s
    grids carry); ``K`` the gens-per-exchange the segments honor."""
    r = rule.radius
    band_cols(C, K * r)  # validate up front at the deepest segment

    def make_local(k):
        d = k * r

        def step_k(grid):
            band = extract_band(grid, C, d)
            out = inner(grid, k)
            return stitch_band(out, evolve_band(band, rule, k), C, d)

        return step_k

    # donate=False: the seam program reads the input grid twice — the
    # shard_map'd base step and the band extraction — and input/output
    # aliasing under that structure races on multi-device meshes (a
    # shard's input word clobbered while the band slice still reads it;
    # observed as nondeterministic whole-shard corruption on the
    # 8-virtual-device CPU mesh).  Seam runs pay one extra grid buffer;
    # the un-wrapped steppers keep their donation.  The IR verifier
    # (python -m mpi_tpu.analysis.ir, ir-donation check) holds the
    # lowered IR to this in both directions: re-enabling donation here
    # fails the gate and tests/test_ir_verify.py.
    return segmented_evolve(make_local, K, donate=False)
