"""Communication-policy auto-tune: ``--comm-every auto`` (VERDICT r2
item 8).

The deep-halo (``comm_every`` = K) and stitched-band (``overlap``)
optimizations trade redundant fringe compute for fewer/hidden
collectives — the right K depends on the per-collective latency of the
interconnect relative to a generation's local compute, which the user
cannot be expected to know per deployment.  ``auto`` resolves the flags
from (a) the mesh/tile geometry and (b) a one-shot measured collective
latency, via the policy table in :func:`choose_comm_policy`.

The latency thresholds are PLACEHOLDERS pending real multi-chip
hardware (this environment has one chip + a virtual CPU mesh, where
collectives are memcpys and every K measures slower — PERF.md's
honest-measurement caveat).  The shape of the policy — more latency →
deeper halos, bounded by engine limits and tile fringe budget — is the
part under test; the numbers are meant to be recalibrated with
``probe_collective_latency_us`` output on ICI/DCN once a slice is
available.  Single-device runs have no collective to avoid or hide, but
the fused radius-1 kernel reinterprets K as its temporal-blocking depth,
so ``auto`` picks the measured winner (``SINGLE_DEVICE_PALLAS_GENS``)
when that kernel will serve the run, K=1 otherwise (VERDICT r3 item 4).

Reference anchor: the reference hardcodes the opposite extreme — one
exchange and one barrier per generation, always
(``/root/reference/main.cpp:291-305``).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from mpi_tpu.models.rules import Rule

# policy table: (latency ceiling in µs, K) — first row whose ceiling
# exceeds the measured latency wins; deliberately coarse (see docstring)
LATENCY_TABLE = ((30.0, 1), (150.0, 2), (600.0, 4), (float("inf"), 8))

# a band deeper than tile_min/8 spends >~25% of compute on redundant
# fringe (both sides, both axes) — cap K there
FRINGE_DIVISOR = 8

# Single-device radius-1 runs served by the fused SWAR kernel reinterpret
# comm_every as the kernel's temporal-blocking depth (generations per HBM
# round-trip); gens=8 is the measured winner on hardware
# (perf/engine_ladder.json: +5% over gens=1 at 65536², PERF.md's
# gens-ladder row) and what bench.py runs the flagship at.  LtL keeps
# gens=1 until the ltl_gens_ladder hardware row lands (queued).
SINGLE_DEVICE_PALLAS_GENS = 8


def probe_collective_latency_us(mesh, reps: int = 5) -> float:
    """One-shot measured per-collective latency (µs) on the mesh: a
    compiled scalar ``psum`` over both mesh axes, warmed once, median of
    ``reps`` timed calls closed with a host fetch (block_until_ready is
    unreliable on the tunneled platform — utils/platform.force_fetch)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec

    try:
        shard_map = jax.shard_map
    except AttributeError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    from mpi_tpu.parallel.mesh import AXES

    def allsum(x):
        return lax.psum(lax.psum(x, AXES[0]), AXES[1])

    f = jax.jit(shard_map(
        allsum, mesh=mesh,
        in_specs=PartitionSpec(), out_specs=PartitionSpec(),
    ))
    x = jnp.float32(1.0)
    float(jax.device_get(f(x)))  # compile + warm
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jax.device_get(f(x)))  # the fetch is the barrier
        samples.append((time.perf_counter() - t0) * 1e6)
    samples.sort()
    return samples[len(samples) // 2]


def choose_comm_policy(
    n_devices: int,
    rule: Rule,
    tile_rows: int,
    tile_cols: int,
    latency_us: float,
    overlap_requested: bool = False,
    single_device_pallas: bool = False,
    single_device_pallas_gens: Optional[int] = None,
) -> Tuple[int, bool]:
    """(comm_every, overlap) for ``--comm-every auto``.

    Single device: ``(SINGLE_DEVICE_PALLAS_GENS, overlap_requested)``
    when the fused radius-1 kernel will serve the run
    (``single_device_pallas`` — the caller has checked the platform gate
    and kernel ``supports()``; VERDICT r3 item 4: the measured winner,
    not the un-blocked kernel); ``single_device_pallas_gens`` is the
    dense analog — the caller-validated temporal-blocking depth of the
    fused dense stencil kernel (ops/pallas_stencil.py, any radius with
    gens·r ≤ 16), taken when the SWAR route does not apply.  Else
    (1, overlap_requested): off the fused kernels there is no collective
    to avoid and no temporal blocking to engage.  Multi-device: K from
    the latency table, clamped by the engine's halo bounds (K ≤ 16 at
    radius 1, K·r ≤ 31 beyond) and the fringe budget (K·r ≤ tile_min/8);
    rules that give birth on 0 neighbors cannot run deep halos at all.
    ``overlap`` turns on whenever the stitched bands fit the tile
    (hiding the exchange costs nothing but the fringe recompute that K
    already budgeted)."""
    if n_devices <= 1:
        if single_device_pallas and rule.radius == 1 and 0 not in rule.birth:
            return SINGLE_DEVICE_PALLAS_GENS, overlap_requested
        if (single_device_pallas_gens and single_device_pallas_gens > 1
                and 0 not in rule.birth):
            return single_device_pallas_gens, overlap_requested
        return 1, overlap_requested
    r = rule.radius
    if 0 in rule.birth:
        return 1, overlap_requested
    for ceiling, k in LATENCY_TABLE:
        if latency_us < ceiling:
            break
    kmax_engine = 16 if r == 1 else 31 // r
    tile_min = min(tile_rows, tile_cols)
    kmax_fringe = max(1, tile_min // (FRINGE_DIVISOR * r))
    k = max(1, min(k, kmax_engine, kmax_fringe))
    # stitched bands need 2·K·r rows and (packed engines) 2 words of cols
    overlap = tile_rows >= 2 * k * r and tile_cols >= 64
    return k, overlap


def resolve_auto(
    config, effective_mesh: Tuple[int, int], mesh=None,
    latency_us: Optional[float] = None,
):
    """The resolved (comm_every, overlap) for a run on ``effective_mesh``,
    probing the collective latency when not supplied (requires ``mesh``
    for multi-device runs)."""
    mi, mj = effective_mesh
    n = mi * mj
    single_pallas = False
    if n == 1 and config.rule.radius == 1 and 0 not in config.rule.birth:
        # will the fused SWAR kernel serve this run at the measured-best
        # temporal blocking depth?  Mirrors _pick_packed_evolve's
        # single-device dispatch (backends/tpu.py) so auto's choice is
        # what actually runs.
        from mpi_tpu.backends.tpu import _pallas_single_device_mode
        from mpi_tpu.ops.pallas_bitlife import supports

        use, _ = _pallas_single_device_mode()
        single_pallas = use and supports(
            (config.rows, config.cols), config.rule,
            gens=SINGLE_DEVICE_PALLAS_GENS,
        )
    dense_gens = None
    if n == 1 and not single_pallas and 0 not in config.rule.birth:
        # will the run route to the DENSE engine, and can the fused dense
        # stencil kernel (ops/pallas_stencil.py) temporally block it?
        # Mirrors build_engine's routing (plan_pad_width -> packed,
        # select_ltl_mode -> bit-sliced, else dense) — evaluated at each
        # candidate depth, since routing itself depends on comm_every.
        import dataclasses

        from mpi_tpu.backends.tpu import (
            _pallas_single_device_mode, plan_pad_width, select_ltl_mode,
        )
        from mpi_tpu.ops.pallas_stencil import supports as dense_supports

        use, _ = _pallas_single_device_mode()
        if use:
            for g in (SINGLE_DEVICE_PALLAS_GENS, 4, 2):
                if g * config.rule.radius > 16:
                    continue  # deeper than the kernel's halo slab
                cfg_g = dataclasses.replace(config, comm_every=g)
                cols_eff, pad_bits = plan_pad_width(
                    cfg_g, 1, shard_rows=config.rows)
                if config.rule.radius == 1 and cols_eff % 32 == 0:
                    continue  # packed SWAR engine serves this run
                if select_ltl_mode(cfg_g, 1, 1, cols=cols_eff,
                                   pad_bits=pad_bits)[0] is not None:
                    continue  # bit-sliced LtL engine serves this run
                if dense_supports((config.rows, config.cols),
                                  config.rule, gens=g):
                    dense_gens = g
                    break
    if n > 1 and latency_us is None:
        latency_us = probe_collective_latency_us(mesh)
        import jax

        if jax.process_count() > 1:
            # every process MUST resolve the same policy: differing K
            # across hosts would compile mismatched collective programs
            # (unpaired ppermutes → hang).  Per-host medians can straddle
            # a table threshold, so process 0's measurement is broadcast
            # and used by all.
            import numpy as np
            from jax.experimental import multihost_utils

            latency_us = float(multihost_utils.broadcast_one_to_all(
                np.float64(latency_us)))
    return choose_comm_policy(
        n, config.rule, config.rows // mi, config.cols // mj,
        latency_us if latency_us is not None else 0.0,
        overlap_requested=config.overlap,
        single_device_pallas=single_pallas,
        single_device_pallas_gens=dense_gens,
    )
