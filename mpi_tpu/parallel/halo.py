"""Ghost-cell (halo) exchange via ``jax.lax.ppermute`` — the TPU-native
replacement for the reference's MPI point-to-point exchange
(``distr_borders``, ``/root/reference/main.cpp:36-65``).

Mechanism mapping (SURVEY.md §5.8):

* ``MPI_Isend/Irecv`` of strided column types / contiguous rows →
  ``lax.ppermute`` ring shifts along a mesh axis (ICI nearest-neighbor
  transfers on real hardware);
* the reference's two-phase ordering — columns first, then rows *including
  the just-received ghost columns* so corners propagate diagonally — is
  kept, but phase order flipped (rows first, then width-extended columns);
  either order transfers the corner blocks in two phases;
* ``MPI_PROC_NULL`` no-op sends at non-periodic edges → ``ppermute``'s
  semantics of delivering **zeros** to devices that appear in no
  (src, dst) pair: for ``boundary="dead"`` we simply omit the wraparound
  pairs and the edge ghosts arrive as zeros, which is exactly the dead
  boundary condition.  Periodic closes the ring instead.

Unlike the reference, the pairing is correct: the reference sends its left
edge to its *right* neighbor's right ghost (mirrored halos, SURVEY.md §5.8
quirk #1); here ghosts always hold the geometrically adjacent neighbor's
edge, and the parity tests vs the serial oracle prove it.

Halo width = rule radius (r-deep ghost rings for Larger-than-Life), the
generalization the reference's 1-cell halo hardcodes away.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from mpi_tpu.parallel.mesh import AXES, axis_size


def _axis_exchange(x, axis_name: str, spatial_axis: int, radius: int, periodic: bool):
    """Extend x by radius ghost slices on both ends of spatial_axis, filled
    from the previous/next shard along mesh axis axis_name."""
    n = axis_size(axis_name)
    size = x.shape[spatial_axis]
    first = lax.slice_in_dim(x, 0, radius, axis=spatial_axis)
    last = lax.slice_in_dim(x, size - radius, size, axis=spatial_axis)
    if n == 1:
        if periodic:
            before, after = last, first          # wrap onto itself
        else:
            before, after = jnp.zeros_like(last), jnp.zeros_like(first)
    else:
        fwd = [(k, k + 1) for k in range(n - 1)]
        bwd = [(k, k - 1) for k in range(1, n)]
        if periodic:
            fwd.append((n - 1, 0))
            bwd.append((0, n - 1))
        # before-ghost = previous shard's last rows; after-ghost = next
        # shard's first rows.  Missing pairs (dead boundary) yield zeros.
        before = lax.ppermute(last, axis_name, fwd)
        after = lax.ppermute(first, axis_name, bwd)
    return jnp.concatenate([before, x, after], axis=spatial_axis)


def expected_slab_depths(radius: int, comm_every: int, packed: bool):
    """The legal thin-extents of a halo slab exchanged by one ppermute.

    A stepper that communicates every k-th step (k ≤ comm_every, since
    segment tails exchange at their own shorter cadence) ships a
    ``k * radius``-deep slab; bitpacked engines additionally exchange a
    single ghost *word* column (depth 1 — 32 halo bits cover any
    K·r ≤ 31, see exchange_halo_rc).  This is the single source of truth
    the IR verifier's collective check
    (``python -m mpi_tpu.analysis.ir``) holds traced slab shapes to —
    widen it if the exchange protocol legitimately changes.
    """
    depths = {k * radius for k in range(1, comm_every + 1)}
    if packed:
        depths.add(1)
    return depths


def exchange_halo(local, radius: int, boundary: str, axes=AXES):
    """(h, w) shard → (h+2r, w+2r) with ghost ring filled.  Must be called
    inside ``shard_map`` over a mesh with the given axis names.  Rows phase
    then columns phase on the row-extended array → corners correct."""
    return exchange_halo_rc(local, radius, radius, boundary, axes)


def exchange_halo_rc(local, radius_rows: int, radius_cols: int, boundary: str,
                     axes=AXES):
    """``exchange_halo`` with independent row/column ghost depths — the
    bitpacked stepper exchanges K ghost rows but a single ghost *word*
    column (32 halo bits cover any K ≤ 16)."""
    periodic = boundary == "periodic"
    x = _axis_exchange(local, axes[0], 0, radius_rows, periodic)
    return _axis_exchange(x, axes[1], 1, radius_cols, periodic)
