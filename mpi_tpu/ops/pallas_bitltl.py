"""Fused Pallas kernel for the bit-sliced radius-r (LtL) engine.

``ops/bitltl.py``'s XLA path materializes every bit plane in HBM (the
rolls defeat fusion): measured 73 Gcell/s for Bosco at 4096² but 13 at
16384² — bandwidth-bound.  This kernel streams row slabs through VMEM
with the same double-buffered halo-slab DMA scaffold as
``ops/pallas_bitlife.py`` (the 8-row DMA-alignment halo happens to
cover every radius the rule system allows, r ≤ 7), so each step costs
one packed HBM read + one packed write and the plane arithmetic runs
out of VMEM:

* vertical sums are *slab row slices* at static offsets — free, where
  the XLA path paid a materialized roll per shift;
* horizontal cross-word bits come from ``pltpu.roll`` lane rotation of
  each plane (one prev/next rotation per plane, reused across shift
  distances), exactly the ``bitlife`` convention;
* the per-generation compute is ``bitltl``'s shared plane arithmetic
  (``bs_add`` ripple adders, ``bs_ge`` comparators, +1-shifted survive
  intervals) applied to CM-row sub-tiles to bound live VMEM.

Temporal blocking (``gens`` > 1, VERDICT r2 item 4): the radius-r
dependence cone consumes r rows per side per generation, so the 8-row
DMA halo admits ⌊8/r⌋ in-VMEM generations per HBM round-trip — 4 for
r=2, 2 for r=3..4, nothing for r ≥ 5.  Same trapezoid machinery as
``ops/pallas_bitlife.py``: each generation shrinks the valid row window
by r per side, sub-tiles update the slab in place carrying the r
overwritten neighbor rows in ``saved``, and dead-boundary edge slabs
are re-killed between generations.  Whether it pays is an empirical
question per radius (the kernel is near the compute roof at r=5, but
shallower radii have fewer ops/cell and more bandwidth headroom) —
measured on hardware via tools/ltl_gens_ladder.py, see PERF.md.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_tpu.models.rules import Rule
from mpi_tpu.ops.bitlife import WORD
from mpi_tpu.ops.bitltl import Plane, bs_sum, make_hshift, _in_intervals

HALO = 8  # DMA row slices must be 8-sublane aligned; covers r <= 7


def _nplanes(radius: int) -> int:
    """Bit planes needed for the neighborhood total (2r+1)²."""
    total = (2 * radius + 1) ** 2
    return max(1, total.bit_length())


def max_gens(radius: int) -> int:
    """Deepest temporal blocking the 8-row DMA halo admits."""
    return max(1, HALO // radius)


def _pick_blocks(H: int, NW: int, radius: int) -> Tuple[int, int] | None:
    """(BM, CM) slab/compute-tile rows.  The live working set is the
    double-buffered slab plus ~11 (CM, NW) u32 temporaries *per bit
    plane* of the neighborhood total (the v/prev/next/shifted/total
    plane families plus comparator masks all scale with the plane
    count) — calibrated on hardware 2026-07-30: Mosaic reported 20.33M
    for (BM=256, CM=256, NW=256, r=5), i.e. ~75 per sub-tile row ≈ 10.7
    per plane at r=5's 7 planes; 11 is the safety-rounded coefficient.

    Wide rows carry the sibling pallas_bitlife calibration's hard rail:
    every 512-row slab at NW=2048 is measured VMEM OOM there despite
    passing a similar coefficient screen, and this kernel's screen
    cannot predict those OOMs either — so bm is capped at 256 when
    NW > 512 rather than trusting an unmeasured shape to compile
    (ADVICE r2: pallas_bitltl.py:60)."""
    limit = int(15.25 * (1 << 20))
    coeff = 11 * _nplanes(radius)
    for bm in (512, 256, 128, 64, 32, 16, 8):
        if H % bm:
            continue
        if bm > 256 and NW > 512:
            continue  # measured-OOM regime in the sibling kernel
        dbuf = 2 * (bm + 2 * HALO) * NW * 4
        for cm in (256, 128, 64, 32, 16, 8):
            if cm > bm:
                continue
            temps = coeff * (cm + 2) * NW * 4
            if dbuf + temps <= limit:
                return bm, cm
    return None


def supports(shape: Tuple[int, int], rule: Rule, gens: int = 1) -> bool:
    H, W = shape
    return (
        W % WORD == 0
        and (W // WORD) % 128 == 0  # packed width must stay lane-aligned
        and 1 <= rule.radius <= 7
        and 1 <= gens <= max_gens(rule.radius)
        # dead-boundary halo rows must stay dead across in-VMEM
        # generations — mirror pallas_ltl_step's own rejection so the
        # capability check matches what the step accepts
        and not (gens > 1 and 0 in rule.birth)
        and H >= HALO
        and _pick_blocks(H, W // WORD, rule.radius) is not None
    )


def _make_kernel(rule: Rule, boundary: str, H: int, NW: int, BM: int, CM: int,
                 gens: int = 1):
    periodic = boundary == "periodic"
    r = rule.radius
    nblocks = H // BM
    if not 1 <= gens <= max_gens(r):
        raise ValueError(
            f"gens must be in 1..{max_gens(r)} for radius {r}, got {gens}"
        )

    def _block_dmas(in_hbm, dbuf, sems, blk, slot):
        base = blk * BM
        top = pl.multiple_of(lax.rem(base - HALO + H, H), HALO)
        bot = pl.multiple_of(lax.rem(base + BM, H), HALO)
        return (
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, HALO), :],
                dbuf.at[slot, pl.ds(0, HALO), :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(base, BM), :],
                dbuf.at[slot, pl.ds(HALO, BM), :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, HALO), :],
                dbuf.at[slot, pl.ds(HALO + BM, HALO), :],
                sems.at[slot, 2],
            ),
        )

    def kernel(in_hbm, out_ref, dbuf, sems):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        next_slot = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, 0, 0):
                d.start()

        @pl.when(i + 1 < nblocks)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, i + 1, next_slot):
                d.start()

        for d in _block_dmas(in_hbm, dbuf, sems, i, slot):
            d.wait()

        scratch = dbuf.at[slot]

        if not periodic:
            # rows beyond the grid are dead cells
            @pl.when(i == 0)
            def _():
                scratch[0:HALO, :] = jnp.zeros((HALO, NW), dtype=jnp.uint32)

            @pl.when(i == nblocks - 1)
            def _():
                scratch[HALO + BM : HALO + BM + HALO, :] = jnp.zeros(
                    (HALO, NW), dtype=jnp.uint32
                )

        def next_state(row_slice, rows):
            """Next state of ``rows`` rows; ``row_slice(d)`` yields their
            vertical neighbors at offset d ∈ [-r, r]."""
            # vertical sums: carry-save sum of the 2r+1 neighbor rows
            v: List[Plane] = bs_sum(
                [[row_slice(0)]]
                + [[row_slice(d)] for d in range(1, r + 1)]
                + [[row_slice(-d)] for d in range(1, r + 1)]
            )

            lane = (
                None if periodic
                else lax.broadcasted_iota(jnp.int32, (rows, NW), dimension=1)
            )

            def word_roll(x, d):
                rolled = pltpu.roll(x, d % NW, axis=1)
                if periodic:
                    return rolled
                # dead boundary: words rolled across the grid edge are 0
                valid = (lane - d >= 0) & (lane - d < NW)
                return jnp.where(valid, rolled, jnp.uint32(0))

            hshift = make_hshift(v, word_roll)

            total: List[Plane] = bs_sum(
                [list(v)]
                + [hshift(d) for d in range(1, r + 1)]
                + [hshift(-d) for d in range(1, r + 1)]
            )

            mid = row_slice(0)
            zero = jnp.zeros((rows, NW), dtype=jnp.uint32)
            born = _in_intervals(total, rule.birth_intervals, 0, zero)
            stay = _in_intervals(total, rule.survive_intervals, 1, zero)
            return (~mid & born) | (mid & stay)

        # Trapezoid over ``gens`` in-VMEM generations, each consuming r
        # valid rows per side (pallas_bitlife's machinery at stride r).
        # Intermediate generations update the slab in place in CM-row
        # sub-tiles; the r rows above a sub-tile were overwritten by its
        # predecessor, so their OLD values ride in ``saved`` (CM ≥ 8 > r
        # guarantees the predecessor covered them).  The final generation
        # reads scratch untouched-this-generation and writes out_ref.
        lo, hi = 0, BM + 2 * HALO
        for g in range(gens):
            rem = gens - 1 - g
            glo = max(lo + r, HALO - rem * r)
            ghi = min(hi - r, HALO + BM + rem * r)
            saved = None
            a = glo
            while a < ghi:
                b = min(a + CM, ghi)
                rows = b - a
                if rem == 0:
                    new = next_state(
                        lambda d: scratch[a + d : b + d, :], rows
                    )
                    out_ref[a - HALO : b - HALO, :] = new
                else:
                    top = scratch[a - r : a, :] if saved is None else saved
                    saved = scratch[b - r : b, :]  # old rows, read pre-write
                    win = jnp.concatenate([top, scratch[a : b + r, :]], axis=0)
                    new = next_state(
                        lambda d: win[r + d : r + d + rows, :], rows
                    )
                    scratch[a:b, :] = new
                a = b
            if rem:
                if not periodic:
                    # rows beyond the grid edge are not real cells: re-kill
                    # any "births" there after every in-VMEM generation
                    if glo < HALO:
                        @pl.when(i == 0)
                        def _():
                            scratch[glo:HALO, :] = jnp.zeros(
                                (HALO - glo, NW), dtype=jnp.uint32
                            )

                    if ghi > HALO + BM:
                        @pl.when(i == nblocks - 1)
                        def _():
                            scratch[HALO + BM : ghi, :] = jnp.zeros(
                                (ghi - HALO - BM, NW), dtype=jnp.uint32
                            )
                lo, hi = glo, ghi

    return kernel


def pallas_ltl_step(
    packed: jax.Array,
    rule: Rule,
    boundary: str = "periodic",
    interpret: bool = False,
    blocks: Tuple[int, int] | None = None,
    gens: int = 1,
) -> jax.Array:
    """``gens`` radius-r generations on a packed (H, W/32) uint32 grid in
    one HBM round-trip via the fused bit-sliced kernel.  Requires
    ``supports((H, W), rule, gens)``."""
    H, NW = packed.shape
    picked = blocks or _pick_blocks(H, NW, rule.radius)
    if picked is None or rule.radius > 7:
        raise ValueError(
            f"pallas_ltl_step cannot handle packed shape {packed.shape}"
        )
    BM, CM = picked
    # explicit blocks= bypasses supports(): re-check the invariants that
    # would otherwise surface as opaque Mosaic errors on real hardware
    # (ADVICE r2: pallas_bitltl.py:196)
    if H % BM or NW % 128:
        raise ValueError(
            f"blocks {picked} invalid for packed shape {packed.shape}: "
            f"need H % BM == 0 and (W/32) % 128 == 0"
        )
    if gens > 1 and 0 in rule.birth:
        # dead-boundary halo rows must stay dead across in-VMEM generations
        raise ValueError("gens > 1 requires a rule without birth-on-0")
    kernel = _make_kernel(rule, boundary, H, NW, BM, CM, gens)
    from mpi_tpu.ops.pallas_bitlife import _out_struct

    return pl.pallas_call(
        kernel,
        grid=(H // BM,),
        out_shape=_out_struct(packed, H, NW),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((BM, NW), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, BM + 2 * HALO, NW), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )(packed)


def make_pallas_ltl_stepper(
    rule: Rule, boundary: str = "periodic", interpret: bool = False,
    gens: int = 1,
):
    """evolve(packed, steps) running ``gens`` generations per kernel pass
    (temporal blocking); jitted with donated carry, remainder steps served
    by shallower passes (the segmenting contract shared with
    pallas_bitlife's stepper)."""
    from mpi_tpu.utils.segmenting import segmented_evolve

    def make_local(k):
        def local(p):
            return pallas_ltl_step(p, rule, boundary, interpret=interpret,
                                   gens=k)

        return local

    return segmented_evolve(make_local, gens)
