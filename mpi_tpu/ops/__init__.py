"""Compute ops: dense stencil (XLA shift-add) and Pallas TPU kernels."""

from mpi_tpu.ops.stencil import (
    pad_grid,
    counts_from_padded,
    neighbor_counts,
    apply_rule,
    step,
    make_stepper,
)

__all__ = [
    "pad_grid",
    "counts_from_padded",
    "neighbor_counts",
    "apply_rule",
    "step",
    "make_stepper",
]
