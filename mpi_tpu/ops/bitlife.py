"""Bitpacked (SWAR) engine for radius-1 B/S rules — 32 cells per uint32
lane, bit-parallel neighbor counting.

The dense paths (``ops/stencil.py``, ``ops/pallas_stencil.py``) spend ~15
vector ops per *cell*; at one uint8 cell per lane-byte the VPU becomes the
bottleneck around ~70 G cell-updates/s/chip.  Packing 32 cells into each
uint32 lane turns the same VPU ops into 32-cell-wide bitwise arithmetic:
~35 ops per *word* ≈ 1 op/cell, and HBM traffic drops 8x.  This is the
classic bit-parallel Game-of-Life technique re-expressed for the TPU VPU,
and it is how the framework beats the north-star throughput target per
chip instead of merely meeting it.

Scheme (exact neighbor counts, center excluded, so any radius-1 B/S rule
works — Life, HighLife, Seeds, Day & Night):

* column sums via a carry-save adder over the three row words
  (up/mid/down): full 2-bit column ``f = u + m + d`` for the side columns,
  2-bit ``c = u + d`` for the center column (center cell excluded — this
  avoids a 4-bit subtraction later); computed ONCE and reused for the
  left/right columns, since the sums of a shifted word are the shifted
  sums;
* horizontal gather via word shifts with cross-word carries
  (LSB = lowest column index): ``L = (f << 1) | (f_prev >> 31)``,
  ``R = (f >> 1) | (f_next << 31)``;
* the count decomposes as ``count = s0 + 2k`` with ``s0`` the weight-1
  parity and ``k = L1 + c1 + R1 + carry`` in 0..4 — the rule is a
  *symmetric* function of those four addends, so ``bit_next`` compiles it
  into threshold indicators ``k >= v`` (cheap elementary AND/OR pairs)
  times a minimal 2-variable function of (s0, alive), with the impossible
  counts > 8 exploited as don't-cares.  Life compiles to
  ``(k == 1) & (s0 | mid)``: ~40 vector ops per 32-cell word, ~2.5x fewer
  than the exact-count-bits scheme.

Everything is uint32 elementwise — XLA fuses the whole step into one pass
on any backend, and the identical code runs inside ``shard_map`` (the
halo exchange just also shifts the packed edge words, ``parallel``
integration) and under ``lax.scan``.

Layout: (H, W) cells → (H, W/32) uint32; bit ``j`` of word ``w`` is the
cell at column ``w*32 + j``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_tpu.models.rules import Rule, LIFE

WORD = 32


def packable(shape: Tuple[int, int], rule: Rule) -> bool:
    return rule.radius == 1 and shape[1] % WORD == 0


def pack(grid: jax.Array) -> jax.Array:
    """(H, W) uint8 0/1 → (H, W/32) uint32, LSB = lowest column."""
    H, W = grid.shape
    if W % WORD:
        raise ValueError(f"width {W} not a multiple of {WORD}")
    bits = grid.reshape(H, W // WORD, WORD).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    return (bits * weights).sum(axis=-1, dtype=jnp.uint32)


def unpack(packed: jax.Array) -> jax.Array:
    """(H, W/32) uint32 → (H, W) uint8 0/1."""
    H, nw = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(H, nw * WORD).astype(jnp.uint8)


def init_packed(
    rows: int,
    cols: int,
    seed: int,
    row_offset=0,
    col_offset=0,
    block_rows: int = 1024,
    col_limit=None,
) -> jax.Array:
    """Hash-init a grid tile directly in packed form, streaming over row
    blocks — a 65536² grid (512 MiB packed) initializes without ever
    materializing the 4 GiB unpacked uint8 array or the 16 GiB pack()
    intermediate.  Offsets make it decomposition-invariant like
    ``init_tile_jnp`` (traceable, usable inside shard_map).

    ``col_limit``: cells whose GLOBAL column (col_offset + local) is ≥
    this are initialized dead — the pad region of a pad-to-32 grid; the
    hash of every real cell is untouched, so padded and exact-width runs
    agree bit-for-bit on the real columns."""
    if cols % WORD:
        raise ValueError(f"cols {cols} not a multiple of {WORD}")
    from mpi_tpu.utils.hashinit import init_tile_jnp

    block_rows = min(block_rows, rows)
    while rows % block_rows:
        block_rows //= 2

    def one_block(r0):
        p = pack(init_tile_jnp(block_rows, cols, seed, row_offset=r0,
                               col_offset=col_offset))
        if col_limit is not None:
            # valid bits per word: clamp(col_limit - col_offset - 32w, 0, 32)
            w = jnp.arange(cols // WORD, dtype=jnp.int32)
            v = jnp.clip(
                jnp.int32(col_limit)
                - jnp.asarray(col_offset, jnp.int32)[None]
                - w * WORD, 0, WORD,
            )
            mask = jnp.where(
                v >= WORD, jnp.uint32(0xFFFFFFFF),
                (jnp.uint32(1) << v.astype(jnp.uint32)) - jnp.uint32(1),
            )
            p = p & mask[None, :]
        return p

    starts = jnp.uint32(row_offset) + jnp.arange(0, rows, block_rows, dtype=jnp.uint32)
    blocks = lax.map(one_block, starts)
    return blocks.reshape(rows, cols // WORD)


def pack_np(grid) -> "np.ndarray":
    """Host-side pack (numpy, blockwise to bound intermediates)."""
    import numpy as np

    grid = np.asarray(grid, dtype=np.uint8)
    H, W = grid.shape
    if W % WORD:
        raise ValueError(f"width {W} not a multiple of {WORD}")
    out = np.empty((H, W // WORD), dtype=np.uint32)
    weights = (np.uint32(1) << np.arange(WORD, dtype=np.uint32))
    step_rows = max(1, (1 << 24) // max(W, 1))
    for r0 in range(0, H, step_rows):
        blk = grid[r0 : r0 + step_rows]
        out[r0 : r0 + step_rows] = (
            blk.reshape(blk.shape[0], -1, WORD).astype(np.uint32) * weights
        ).sum(axis=-1, dtype=np.uint32)
    return out


def unpack_np(packed) -> "np.ndarray":
    """Host-side unpack (numpy, blockwise — the naive (H, nw, 32) uint32
    intermediate would be ~32 GiB for a 65536² grid)."""
    import numpy as np

    packed = np.asarray(packed)
    H, nw = packed.shape
    out = np.empty((H, nw * WORD), dtype=np.uint8)
    shifts = np.arange(WORD, dtype=np.uint32)
    step_rows = max(1, (1 << 24) // max(nw * WORD, 1))
    for r0 in range(0, H, step_rows):
        blk = packed[r0 : r0 + step_rows]
        bits = (blk[:, :, None] >> shifts) & np.uint32(1)
        out[r0 : r0 + step_rows] = bits.reshape(blk.shape[0], -1).astype(np.uint8)
    return out


def column_sums(up, mid, down):
    """Carry-save vertical sums per bit column: the full 2-bit sum
    ``f = up + mid + down`` (``f0`` weight 1, ``f1`` weight 2) and the
    center-excluded 2-bit sum ``c = up + down``.  5 + 2 vector ops; the
    ``f`` sums are what gets shifted horizontally, so callers compute them
    once and reuse them for the left/right columns instead of re-summing
    shifted copies of the raw rows (the old scheme's 12 extra ops)."""
    t = up ^ mid
    f0 = t ^ down
    f1 = (up & mid) | (down & t)
    c0 = up ^ down
    c1 = up & down
    return f0, f1, c0, c1


def column_sums_f(up, mid, down):
    """Just the full vertical sum (f0, f1) — for neighbor words, where the
    center-excluded sum is never needed."""
    t = up ^ mid
    return t ^ down, (up & mid) | (down & t)


# -- rule compiler ----------------------------------------------------------
#
# After the horizontal combine the neighbor count decomposes as
#   count = s0 + 2*k,   k = L1 + c1 + R1 + ca  in 0..4
# where s0 is the parity bit of the weight-1 column and k is the sum of the
# four weight-2 addends.  Any outer-totalistic radius-1 rule is a *symmetric*
# function of those addends, so instead of materializing exact count bits
# n0..n3 and pattern-matching every count (the obvious scheme, ~40 ops for
# Life's three counts), we build threshold indicators k>=v from elementary
# AND/OR pairs (~10 ops for all five) and emit, per run of active k values, a
# minimal 2-variable function of (s0, alive).  Counts 9..15 cannot occur
# (k=4 forces s0's neighbors... max count is 8), so (k=4, s0=1) is a free
# don't-care for the minimizer.  Life compiles to
#   next = (k==1) & (s0 | mid)            -- 12 ops after the adder
# and every other radius-1 rule gets the same treatment automatically.

_FULL = 0xFFFFFFFF

# minimal builders for every 2-variable boolean function of (s0, mid);
# key = outputs for (s0, mid) in ((0,0), (0,1), (1,0), (1,1)); value =
# (op_cost, builder).  NOT is xor-with-ones (1 op).
_G2 = {
    (0, 0, 0, 0): (0, lambda s, m, F: None),           # handled as "drop term"
    (1, 1, 1, 1): (0, lambda s, m, F: "one"),          # indicator alone
    (0, 0, 1, 1): (0, lambda s, m, F: s),
    (0, 1, 0, 1): (0, lambda s, m, F: m),
    (0, 0, 0, 1): (1, lambda s, m, F: s & m),
    (0, 1, 1, 1): (1, lambda s, m, F: s | m),
    (0, 1, 1, 0): (1, lambda s, m, F: s ^ m),
    (1, 1, 0, 0): (1, lambda s, m, F: s ^ F),
    (1, 0, 1, 0): (1, lambda s, m, F: m ^ F),
    (1, 0, 0, 1): (2, lambda s, m, F: (s ^ m) ^ F),
    (1, 1, 1, 0): (2, lambda s, m, F: (s & m) ^ F),
    (1, 0, 0, 0): (2, lambda s, m, F: (s | m) ^ F),
    (0, 0, 1, 0): (2, lambda s, m, F: s & (m ^ F)),
    (0, 1, 0, 0): (2, lambda s, m, F: m & (s ^ F)),
    (1, 0, 1, 1): (2, lambda s, m, F: s | (m ^ F)),
    (1, 1, 0, 1): (2, lambda s, m, F: m | (s ^ F)),
}


def _minimal_g(table):
    """table: 4 entries in {0, 1, None} for (s0, mid) in ((0,0),(0,1),(1,0),
    (1,1)); None = don't care.  Returns (cost, builder) of the cheapest
    concrete function consistent with it."""
    best = None
    for concrete, (cost, build) in _G2.items():
        if all(t is None or t == c for t, c in zip(table, concrete)):
            if best is None or cost < best[0]:
                best = (cost, build)
    return best


def _merge_tables(ta, tb):
    """Merge two don't-care tables; None if they conflict."""
    out = []
    for x, y in zip(ta, tb):
        if x is None:
            out.append(y)
        elif y is None or x == y:
            out.append(x)
        else:
            return None
    return tuple(out)


class _Thresholds:
    """Lazy k>=v indicators over the four weight-2 addends."""

    def __init__(self, a, b, c, d, full):
        self.abcd = (a, b, c, d)
        self.full = full
        self._memo = {}

    def _pairs(self):
        if "p" not in self._memo:
            a, b, c, d = self.abcd
            self._memo["p"] = (a & b, c & d, a | b, c | d)
        return self._memo["p"]

    def ge(self, v):
        if v <= 0:
            return None  # k >= 0 is always true
        if v > 4:
            return 0     # never
        if v not in self._memo:
            p1, p2, o1, o2 = self._pairs()
            if v == 1:
                self._memo[v] = o1 | o2
            elif v == 2:
                self._memo[v] = p1 | p2 | (o1 & o2)
            elif v == 3:
                self._memo[v] = (p1 & o2) | (p2 & o1)
            else:
                self._memo[v] = p1 & p2
        return self._memo[v]

    def in_range(self, lo, hi):
        """Indicator of lo <= k <= hi (None = always true)."""
        glo = self.ge(lo)
        ghi = self.ge(hi + 1)
        if ghi is None or isinstance(ghi, int) and ghi == 0:
            return glo
        not_hi = ghi ^ self.full
        return not_hi if glo is None else glo & not_hi


def _rule_tables(rule: Rule):
    """Per-k don't-care tables want[k] over ((s0,mid) in ((0,0),(0,1),(1,0),
    (1,1))): next-state bit for count = 2k + s0, None where count > 8."""
    tables = []
    for k in range(5):
        row = []
        for s in (0, 1):
            count = 2 * k + s
            for alive in (0, 1):
                if count > 8:
                    row.append(None)
                else:
                    row.append(int(count in (rule.survive if alive else rule.birth)))
        # row order built as (s0,alive)=(0,0),(0,1),(1,0),(1,1)
        tables.append(tuple(row))
    return tables


def bit_next(f0, f1, c0, c1, f0p, f1p, f0n, f1n, mid, rule: Rule):
    """Next state of ``mid`` given the vertical column sums of its own words
    (f*, c*) and of the previous/next words along the row (f*p, f*n), whose
    top bits provide the cross-word shift carries."""
    one = jnp.uint32(1)
    t31 = jnp.uint32(31)
    full = jnp.uint32(_FULL)

    # horizontal gather: L/R = the 2-bit column sums one column left/right
    L0 = (f0 << one) | (f0p >> t31)
    L1 = (f1 << one) | (f1p >> t31)
    R0 = (f0 >> one) | (f0n << t31)
    R1 = (f1 >> one) | (f1n << t31)

    # count = s0 + 2*(L1 + c1 + R1 + ca)
    u = L0 ^ c0
    s0 = u ^ R0
    ca = (L0 & c0) | (R0 & u)

    th = _Thresholds(L1, c1, R1, ca, full)

    # greedy maximal runs of consecutive k with compatible next-functions
    tables = _rule_tables(rule)
    acc = None
    k = 0
    while k < 5:
        if not any(t == 1 for t in tables[k]):
            k += 1
            continue
        merged = tables[k]
        hi = k
        while hi + 1 < 5:
            m2 = _merge_tables(merged, tables[hi + 1])
            if m2 is None or not any(t == 1 for t in tables[hi + 1]):
                # only extend over ks that actually fire, to keep ge() cheap
                break
            merged, hi = m2, hi + 1
        cost_build = _minimal_g(merged)
        ind = th.in_range(k, hi)
        g = cost_build[1](s0, mid, full)
        if g is None:
            term = None
        elif isinstance(g, str):  # "one": indicator alone
            term = ind if ind is not None else jnp.full_like(mid, full)
        else:
            term = g if ind is None else ind & g
        if term is not None:
            acc = term if acc is None else acc | term
        k = hi + 1
    if acc is None:
        return jnp.zeros_like(mid)
    return acc


def bit_step_rows(up, mid, down, up_p, mid_p, down_p, up_n, mid_n, down_n, rule: Rule):
    """Next state of the `mid` row words given all nine packed inputs.
    Compatibility wrapper: callers that can share vertical sums across the
    horizontal shift (the Pallas kernel, the sharded stepper) should call
    ``column_sums`` + ``bit_next`` directly."""
    f0, f1, c0, c1 = column_sums(up, mid, down)
    f0p, f1p = column_sums_f(up_p, mid_p, down_p)
    f0n, f1n = column_sums_f(up_n, mid_n, down_n)
    return bit_next(f0, f1, c0, c1, f0p, f1p, f0n, f1n, mid, rule)


def bit_step(packed: jax.Array, rule: Rule = LIFE, boundary: str = "periodic") -> jax.Array:
    """One generation on a packed (H, W/32) uint32 grid, single device."""
    if rule.radius != 1:
        raise ValueError("bitpacked engine supports radius-1 rules only")
    periodic = boundary == "periodic"
    zero_row = jnp.zeros_like(packed[:1])
    zero_col = jnp.zeros_like(packed[:, :1])

    if periodic:
        up = jnp.roll(packed, 1, axis=0)
        down = jnp.roll(packed, -1, axis=0)
    else:
        up = jnp.concatenate([zero_row, packed[:-1]], axis=0)
        down = jnp.concatenate([packed[1:], zero_row], axis=0)

    def word_shift(x, direction):
        # previous/next word along the row for cross-word bit carries
        if periodic:
            return jnp.roll(x, direction, axis=1)
        if direction == 1:
            return jnp.concatenate([zero_col, x[:, :-1]], axis=1)
        return jnp.concatenate([x[:, 1:], zero_col], axis=1)

    # vertical sums once, then shift the 2-bit sums (4 shifted arrays)
    # instead of the raw rows (6) — the sums of a shifted word ARE the
    # shifted sums.
    f0, f1, c0, c1 = column_sums(up, packed, down)
    return bit_next(
        f0, f1, c0, c1,
        word_shift(f0, 1), word_shift(f1, 1),
        word_shift(f0, -1), word_shift(f1, -1),
        packed, rule,
    )


@functools.partial(
    jax.jit, static_argnames=("rule", "boundary", "steps"), donate_argnums=0
)
def _evolve_bits(packed, rule, boundary, steps):
    def body(p, _):
        return bit_step(p, rule, boundary), None

    out, _ = lax.scan(body, packed, None, length=steps)
    return out


def make_bit_stepper(rule: Rule = LIFE, boundary: str = "periodic"):
    """evolve(grid_u8, steps) -> grid_u8, running packed internally."""

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=0)
    def evolve(grid: jax.Array, steps: int) -> jax.Array:
        return unpack(_evolve_bits(pack(grid), rule, boundary, steps))

    return evolve
