"""Bitpacked (SWAR) engine for radius-1 B/S rules — 32 cells per uint32
lane, bit-parallel neighbor counting.

The dense paths (``ops/stencil.py``, ``ops/pallas_stencil.py``) spend ~15
vector ops per *cell*; at one uint8 cell per lane-byte the VPU becomes the
bottleneck around ~70 G cell-updates/s/chip.  Packing 32 cells into each
uint32 lane turns the same VPU ops into 32-cell-wide bitwise arithmetic:
~35 ops per *word* ≈ 1 op/cell, and HBM traffic drops 8x.  This is the
classic bit-parallel Game-of-Life technique re-expressed for the TPU VPU,
and it is how the framework beats the north-star throughput target per
chip instead of merely meeting it.

Scheme (exact neighbor counts, center excluded, so any radius-1 B/S rule
works — Life, HighLife, Seeds, Day & Night):

* column sums via a carry-save adder over the three row words
  (up/mid/down): full 3-bit column ``f = u + m + d`` for the side columns,
  2-bit ``c = u + d`` for the center column (center cell excluded — this
  avoids a 4-bit subtraction later);
* horizontal gather via word shifts with cross-word carries
  (LSB = lowest column index): ``L = (x << 1) | (prev >> 31)``,
  ``R = (x >> 1) | (next << 31)``;
* total count ``N = L + C + R`` (max 8) via a two-layer adder producing
  exact bits n0, n1, n2, n3;
* the rule becomes a boolean function of (n3..n0, alive), built as an OR
  of bit-pattern matches over the rule's count sets.

Everything is uint32 elementwise — XLA fuses the whole step into one pass
on any backend, and the identical code runs inside ``shard_map`` (the
halo exchange just also shifts the packed edge words, ``parallel``
integration) and under ``lax.scan``.

Layout: (H, W) cells → (H, W/32) uint32; bit ``j`` of word ``w`` is the
cell at column ``w*32 + j``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_tpu.models.rules import Rule, LIFE

WORD = 32


def packable(shape: Tuple[int, int], rule: Rule) -> bool:
    return rule.radius == 1 and shape[1] % WORD == 0


def pack(grid: jax.Array) -> jax.Array:
    """(H, W) uint8 0/1 → (H, W/32) uint32, LSB = lowest column."""
    H, W = grid.shape
    if W % WORD:
        raise ValueError(f"width {W} not a multiple of {WORD}")
    bits = grid.reshape(H, W // WORD, WORD).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    return (bits * weights).sum(axis=-1, dtype=jnp.uint32)


def unpack(packed: jax.Array) -> jax.Array:
    """(H, W/32) uint32 → (H, W) uint8 0/1."""
    H, nw = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(H, nw * WORD).astype(jnp.uint8)


def init_packed(
    rows: int,
    cols: int,
    seed: int,
    row_offset=0,
    col_offset=0,
    block_rows: int = 1024,
) -> jax.Array:
    """Hash-init a grid tile directly in packed form, streaming over row
    blocks — a 65536² grid (512 MiB packed) initializes without ever
    materializing the 4 GiB unpacked uint8 array or the 16 GiB pack()
    intermediate.  Offsets make it decomposition-invariant like
    ``init_tile_jnp`` (traceable, usable inside shard_map)."""
    if cols % WORD:
        raise ValueError(f"cols {cols} not a multiple of {WORD}")
    from mpi_tpu.utils.hashinit import init_tile_jnp

    block_rows = min(block_rows, rows)
    while rows % block_rows:
        block_rows //= 2

    def one_block(r0):
        return pack(init_tile_jnp(block_rows, cols, seed, row_offset=r0,
                                  col_offset=col_offset))

    starts = jnp.uint32(row_offset) + jnp.arange(0, rows, block_rows, dtype=jnp.uint32)
    blocks = lax.map(one_block, starts)
    return blocks.reshape(rows, cols // WORD)


def pack_np(grid) -> "np.ndarray":
    """Host-side pack (numpy, blockwise to bound intermediates)."""
    import numpy as np

    grid = np.asarray(grid, dtype=np.uint8)
    H, W = grid.shape
    if W % WORD:
        raise ValueError(f"width {W} not a multiple of {WORD}")
    out = np.empty((H, W // WORD), dtype=np.uint32)
    weights = (np.uint32(1) << np.arange(WORD, dtype=np.uint32))
    step_rows = max(1, (1 << 24) // max(W, 1))
    for r0 in range(0, H, step_rows):
        blk = grid[r0 : r0 + step_rows]
        out[r0 : r0 + step_rows] = (
            blk.reshape(blk.shape[0], -1, WORD).astype(np.uint32) * weights
        ).sum(axis=-1, dtype=np.uint32)
    return out


def unpack_np(packed) -> "np.ndarray":
    """Host-side unpack (numpy, blockwise — the naive (H, nw, 32) uint32
    intermediate would be ~32 GiB for a 65536² grid)."""
    import numpy as np

    packed = np.asarray(packed)
    H, nw = packed.shape
    out = np.empty((H, nw * WORD), dtype=np.uint8)
    shifts = np.arange(WORD, dtype=np.uint32)
    step_rows = max(1, (1 << 24) // max(nw * WORD, 1))
    for r0 in range(0, H, step_rows):
        blk = packed[r0 : r0 + step_rows]
        bits = (blk[:, :, None] >> shifts) & np.uint32(1)
        out[r0 : r0 + step_rows] = bits.reshape(blk.shape[0], -1).astype(np.uint8)
    return out


def _maj(a, b, c):
    return (a & b) | (c & (a ^ b))


def _rule_predicate(counts_bits, intervals):
    """OR of 4-bit equality matches for every count in the rule set.
    counts_bits = (n0, n1, n2, n3); returns a uint32 bitmask."""
    n0, n1, n2, n3 = counts_bits
    acc = None
    for lo, hi in intervals:
        for k in range(lo, hi + 1):
            m = n0 if k & 1 else ~n0
            m = m & (n1 if k & 2 else ~n1)
            m = m & (n2 if k & 4 else ~n2)
            m = m & (n3 if k & 8 else ~n3)
            acc = m if acc is None else acc | m
    if acc is None:
        return jnp.uint32(0)
    return acc


def bit_neighbor_bits(up, mid, down, up_p, mid_p, down_p, up_n, mid_n, down_n):
    """Exact neighbor-count bits (n0..n3) for each cell bit, given the
    packed word rows (up/mid/down) and their previous/next words along the
    row (for the cross-word shift carries)."""
    one = jnp.uint32(1)
    t31 = jnp.uint32(31)

    # column sums: side columns need u+m+d (0..3), center column u+d (0..2)
    f0 = up ^ mid ^ down
    f1 = _maj(up, mid, down)
    c0 = up ^ down
    c1 = up & down
    # the same sums for the neighboring words (for carry bits)
    fp0 = up_p ^ mid_p ^ down_p
    fp1 = _maj(up_p, mid_p, down_p)
    fn0 = up_n ^ mid_n ^ down_n
    fn1 = _maj(up_n, mid_n, down_n)

    # horizontal shifts: L = column to the left of each cell, R = right
    L0 = (f0 << one) | (fp0 >> t31)
    L1 = (f1 << one) | (fp1 >> t31)
    R0 = (f0 >> one) | (fn0 << t31)
    R1 = (f1 >> one) | (fn1 << t31)

    # N = L + C + R (L, R are 2-bit 0..3; C is 2-bit 0..2; max 8)
    n0 = L0 ^ c0 ^ R0
    ca = _maj(L0, c0, R0)                      # weight-2 carry
    n1 = L1 ^ c1 ^ R1 ^ ca
    pairs = (L1 & c1) | (L1 & R1) | (L1 & ca) | (c1 & R1) | (c1 & ca) | (R1 & ca)
    all4 = L1 & c1 & R1 & ca
    n2 = pairs & ~all4                         # weight-4 bit
    n3 = all4                                  # weight-8 bit (count == 8)
    return n0, n1, n2, n3


def bit_step_rows(up, mid, down, up_p, mid_p, down_p, up_n, mid_n, down_n, rule: Rule):
    """Next state of the `mid` row words given all nine packed inputs."""
    bits = bit_neighbor_bits(up, mid, down, up_p, mid_p, down_p, up_n, mid_n, down_n)
    born = _rule_predicate(bits, rule.birth_intervals)
    keep = _rule_predicate(bits, rule.survive_intervals)
    return (mid & keep) | (~mid & born)


def bit_step(packed: jax.Array, rule: Rule = LIFE, boundary: str = "periodic") -> jax.Array:
    """One generation on a packed (H, W/32) uint32 grid, single device."""
    if rule.radius != 1:
        raise ValueError("bitpacked engine supports radius-1 rules only")
    periodic = boundary == "periodic"
    zero_row = jnp.zeros_like(packed[:1])
    zero_col = jnp.zeros_like(packed[:, :1])

    if periodic:
        up = jnp.roll(packed, 1, axis=0)
        down = jnp.roll(packed, -1, axis=0)
    else:
        up = jnp.concatenate([zero_row, packed[:-1]], axis=0)
        down = jnp.concatenate([packed[1:], zero_row], axis=0)

    def word_shift(x, direction):
        # previous/next word along the row for cross-word bit carries
        if periodic:
            return jnp.roll(x, direction, axis=1)
        if direction == 1:
            return jnp.concatenate([zero_col, x[:, :-1]], axis=1)
        return jnp.concatenate([x[:, 1:], zero_col], axis=1)

    return bit_step_rows(
        up, packed, down,
        word_shift(up, 1), word_shift(packed, 1), word_shift(down, 1),
        word_shift(up, -1), word_shift(packed, -1), word_shift(down, -1),
        rule,
    )


@functools.partial(
    jax.jit, static_argnames=("rule", "boundary", "steps"), donate_argnums=0
)
def _evolve_bits(packed, rule, boundary, steps):
    def body(p, _):
        return bit_step(p, rule, boundary), None

    out, _ = lax.scan(body, packed, None, length=steps)
    return out


def make_bit_stepper(rule: Rule = LIFE, boundary: str = "periodic"):
    """evolve(grid_u8, steps) -> grid_u8, running packed internally."""

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=0)
    def evolve(grid: jax.Array, steps: int) -> jax.Array:
        return unpack(_evolve_bits(pack(grid), rule, boundary, steps))

    return evolve
