"""Bit-sliced SWAR engine for radius-r (Larger-than-Life) rules.

The radius-1 engine (``ops/bitlife.py``) exploits that an 8-neighbor
count fits the ``s0 + 2k`` symmetric-function trick; at radius r the
count runs to ``(2r+1)² − 1`` (120 for Bosco's r=5), so that trick dies
— and the dense uint8 path that serves those rules spends ~(2r+1)²
vector ops per *cell* (measured 88 Gcell/s for radius 1, far less at
radius 5).  This engine keeps the 32-cells-per-uint32-lane packing and
represents every per-cell integer as a list of uint32 *bit planes*
(plane k holds bit k of each cell's value, LSB first):

* **vertical sums** — a carry-save (3:2 compressor) reduction of the
  2r+1 vertically shifted row words gives each column's (2r+1)-cell sum
  as a ≤4-plane bit-sliced number;
* **horizontal sums** — each plane is shifted d = −r..r bits with
  cross-word carries from the adjacent words (one prev/next roll per
  plane, reused across all d), and the 2r+1 shifted column sums are
  Wallace-tree-compressed (``bs_sum``) into the ≤8-plane bit-sliced
  neighborhood total with a single final carry propagation — round 3
  replaced the sequential ripple chains here, cutting the engine from
  ~48 to ~13.6 ALU ops/cell (traced-jaxpr count, ``tools/roofline.py``);
* **rule application** — the total *includes* the center cell, so
  instead of a bit-sliced subtraction the survive intervals are tested
  shifted by +1 (alive ⇒ total = count + 1); birth/survive interval
  membership is an MSB-first bit-sliced comparator (~2 ops per plane
  per threshold), and the next state is
  ``(dead & born) | (alive & survives)``.

Cost for Bosco (r=5): ~436 uint32 ops per 32-cell word ≈ 13.6 ops/cell
pre-CSE (counted from the traced jaxpr by ``tools/roofline.py``; the
sequential-ripple version of this engine measured ~48 ops/cell before
the round-3 Wallace-tree rewrite) vs the dense path's ~121 ops *per
cell* at 1 cell/lane, with 8× less HBM traffic; measured 3.6× faster
end-to-end even pre-rewrite (PERF.md).  Everything is elementwise jnp on the packed (H,
W/32) uint32 layout shared with ``bitlife``, so XLA fuses the step and
the identical code runs under ``lax.scan`` and inside ``shard_map``.

Reference parity anchor: this replaces the generalized form of the
``next()`` neighbor sweep (``/root/reference/main.cpp:79-90``) for
radius > 1; the numpy oracle (``backends/serial_np.py``) remains the
bit-exactness pin.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from mpi_tpu.models.rules import Rule
from mpi_tpu.ops.bitlife import WORD

Plane = Optional[jax.Array]  # None encodes the constant-0 plane


def _and(a: Plane, b: Plane) -> Plane:
    if a is None or b is None:
        return None
    return a & b


def _xor(a: Plane, b: Plane) -> Plane:
    if a is None:
        return b
    if b is None:
        return a
    return a ^ b


def _or(a: Plane, b: Plane) -> Plane:
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _full_add(x: Plane, y: Plane, z: Plane):
    """(sum, carry) of three one-bit planes — 5 ops when all present
    (majority as ``(x&y) | (z & (x^y))``, reusing the sum's ``x^y``),
    degrading gracefully through the None-plane algebra (e.g. z=None
    makes it a 2-op half adder)."""
    t = _xor(x, y)
    return _xor(t, z), _or(_and(x, y), _and(z, t))


def bs_add(a: List[Plane], b: List[Plane]) -> List[Plane]:
    """Ripple add two bit-sliced numbers (LSB-first plane lists)."""
    out: List[Plane] = []
    carry: Plane = None
    for i in range(max(len(a), len(b))):
        x = a[i] if i < len(a) else None
        y = b[i] if i < len(b) else None
        s, carry = _full_add(x, y, carry)
        out.append(s)
    if carry is not None:
        out.append(carry)
    return out


def bs_sum(numbers: List[List[Plane]]) -> List[Plane]:
    """Sum of many bit-sliced numbers by carry-save (3:2 compressor)
    reduction, then ONE ripple propagate — the Wallace-tree shape.

    Sequential ``bs_add`` chains re-propagate carries through the whole
    running total on every addend (~7 ops per full-adder plane of every
    intermediate); compressing all planes of one weight three-at-a-time
    costs 5 ops per compressor with no intermediate propagation, and the
    single final ``bs_add`` joins the ≤2 surviving planes per weight.
    For Bosco's horizontal combine (11 four-plane addends) this is
    ~45% fewer adder ops — directly visible throughput for an engine
    sitting at the VPU roof (perf/roofline.json)."""
    buckets: dict = {}
    maxw = 0
    for num in numbers:
        for w, p in enumerate(num):
            if p is not None:
                buckets.setdefault(w, []).append(p)
                maxw = max(maxw, w)
    w = 0
    while w <= maxw:
        planes = buckets.get(w, [])
        while len(planes) >= 3:
            s, c = _full_add(planes.pop(), planes.pop(), planes.pop())
            planes.append(s)
            if c is not None:
                buckets.setdefault(w + 1, []).append(c)
                maxw = max(maxw, w + 1)
        w += 1
    a: List[Plane] = []
    b: List[Plane] = []
    for w in range(maxw + 1):
        ps = buckets.get(w, [])
        a.append(ps[0] if len(ps) > 0 else None)
        b.append(ps[1] if len(ps) > 1 else None)
    while b and b[-1] is None:
        b.pop()
    return bs_add(a, b) if b else a


def bs_ge(planes: List[Plane], t: int, zero: jax.Array) -> jax.Array:
    """Mask of cells whose bit-sliced value is >= the constant ``t``.

    ``zero`` is a concrete all-zeros word array used to realize constant
    planes when a comparison needs them."""
    if t <= 0:
        return ~zero  # all ones
    if t >= (1 << len(planes)):
        return zero  # value can never reach t
    # NOTE two distinct None conventions here: a None *plane* is the
    # constant-0 plane (as everywhere in this module), while eq=None
    # means "all cells still equal" (constant-1 mask) — so eq is
    # narrowed with an explicit helper, never with _and.
    gt: Plane = None  # strictly greater, decided at a higher plane
    eq: Plane = None  # still equal so far (None = all cells equal)

    def narrow(eq_mask, m):
        return m if eq_mask is None else (eq_mask & m)

    for k in reversed(range(len(planes))):
        p = planes[k]
        tb = (t >> k) & 1
        if tb == 0:
            if p is not None:
                # value bit 1 where t bit 0 → greater (if equal above)
                gt = _or(gt, narrow(eq, p))
                # equality continues where the value bit is 0
                eq = narrow(eq, ~p)
            # p None: value bit 0 == t bit 0 → eq unchanged, no gt
        else:
            if p is None:
                # value bit 0 < t bit 1 → equality impossible below here
                return gt if gt is not None else zero
            # equality continues only where the value bit is 1
            eq = narrow(eq, p)
    eq_mask = ~zero if eq is None else eq
    return eq_mask if gt is None else (gt | eq_mask)


def _in_intervals(planes: List[Plane], intervals, shift: int,
                  zero: jax.Array) -> jax.Array:
    """OR of inclusive-interval tests ``lo+shift <= value <= hi+shift``."""
    acc = zero
    for lo, hi in intervals:
        # bs_ge returns the zero mask for unreachable thresholds, so the
        # upper test degenerates gracefully for intervals past max_count
        m = bs_ge(planes, lo + shift, zero) \
            & ~bs_ge(planes, hi + shift + 1, zero)
        acc = acc | m
    return acc


def make_hshift(v: List[Plane], word_roll):
    """Horizontal bit-shift family over bit-sliced planes ``v``.

    Returns ``hshift(k)`` producing v shifted so bit j sees column j+k
    (|k| < 32), with cross-word bits supplied by ``word_roll(plane,
    ±1)`` — computed once here and reused across all shift distances.
    Shared by the XLA path (jnp.roll words) and the Pallas kernel
    (pltpu.roll lanes): LSB = lowest column index, so "column j+k" is a
    right bit-shift fed from the next word."""
    prev = [None if p is None else word_roll(p, 1) for p in v]
    nxt = [None if p is None else word_roll(p, -1) for p in v]

    def hshift(k: int) -> List[Plane]:
        if k == 0:
            return list(v)
        sh = jnp.uint32(abs(k))
        inv = jnp.uint32(WORD - abs(k))
        out: List[Plane] = []
        for p, pw, nw_ in zip(v, prev, nxt):
            if p is None:
                out.append(None)
            elif k > 0:
                out.append((p >> sh) | (nw_ << inv))
            else:
                out.append((p << sh) | (pw >> inv))
        return out

    return hshift


def supports(shape: Tuple[int, int], rule: Rule) -> bool:
    """Packed-width shapes this engine serves (any radius the rule
    system allows; radius-1 rules should prefer ``bitlife``)."""
    H, W = shape
    return W % WORD == 0 and H >= 2 * rule.radius + 1 and rule.radius <= 7


def _vshift(x: jax.Array, d: int, periodic: bool) -> jax.Array:
    """Rows shifted so row i sees row i+d; dead boundary shifts in 0."""
    rolled = jnp.roll(x, -d, axis=0)
    if periodic:
        return rolled
    H = x.shape[0]
    idx = jnp.arange(H, dtype=jnp.int32)[:, None]
    valid = (idx + d >= 0) & (idx + d < H)
    return jnp.where(valid, rolled, jnp.uint32(0))


def ltl_step(packed: jax.Array, rule: Rule,
             boundary: str = "periodic") -> jax.Array:
    """One generation of a radius-r outer-totalistic rule on a packed
    (H, W/32) uint32 grid."""
    H, NW = packed.shape
    r = rule.radius
    periodic = boundary == "periodic"
    zero = jnp.zeros_like(packed)
    mid = packed

    # 1. vertical (column) sums: carry-save sum of the 2r+1 row words
    v = bs_sum(
        [[mid]]
        + [[_vshift(mid, d, periodic)] for d in range(1, r + 1)]
        + [[_vshift(mid, -d, periodic)] for d in range(1, r + 1)]
    )

    # 2. horizontal sums over the bit-sliced planes (see make_hshift)
    def word_roll(x, d):
        rolled = jnp.roll(x, d, axis=1)
        if periodic:
            return rolled
        col = jnp.arange(NW, dtype=jnp.int32)[None, :]
        valid = (col - d >= 0) & (col - d < NW)
        return jnp.where(valid, rolled, jnp.uint32(0))

    hshift = make_hshift(v, word_roll)

    total = bs_sum(
        [list(v)]
        + [hshift(d) for d in range(1, r + 1)]
        + [hshift(-d) for d in range(1, r + 1)]
    )

    # 3. rule application; total includes the center cell, so survive
    # intervals are tested shifted by +1 (alive ⇒ total = count + 1)
    born = _in_intervals(total, rule.birth_intervals, 0, zero)
    stay = _in_intervals(total, rule.survive_intervals, 1, zero)
    return (~mid & born) | (mid & stay)


def make_ltl_stepper(rule: Rule, boundary: str = "periodic"):
    """evolve(packed, steps) — jitted scan with donated carry, mirroring
    ``bitlife.make_bit_stepper``'s contract (lowerable for AOT)."""
    import functools

    from jax import lax

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=(0,))
    def evolve(packed, steps: int):
        out, _ = lax.scan(
            lambda g, _: (ltl_step(g, rule, boundary), None),
            packed, None, length=steps,
        )
        return out

    return evolve
