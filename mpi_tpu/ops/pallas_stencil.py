"""Fused Pallas TPU stencil kernel — the hot path for the per-step update.

Why a kernel when XLA already fuses the shift-add stencil
(``ops/stencil.py``)?  The XLA path materializes the padded array and the
row-sum intermediate in HBM each step (~5x the grid's bytes of traffic);
this kernel streams each row-block through VMEM exactly once: one HBM read
per cell, one HBM write, everything else (vertical window sum, horizontal
window sum via lane rotations, rule select) stays in registers/VMEM.  At
HBM-bandwidth-bound sizes that is the difference between ~37 and >100
G cell-updates/s on one v5e chip.

Structure (cf. pallas_guide.md "Async DMA" / "Grid and Block
Specifications"):

* the grid stays **unpadded** in HBM (``memory_space=ANY``); the kernel
  grid iterates over row blocks;
* each program DMAs its block plus a radius-wide row halo into a VMEM
  scratch (three DMAs: top halo, center, bottom halo — the top/bottom
  start rows wrap modulo H, which implements periodic rows for free;
  dead rows are zeroed with ``pl.when`` at the edge blocks);
* column neighbors come from ``pltpu.roll`` lane rotations (periodic
  columns for free; dead columns are masked with a lane iota);
* the B/S rule is applied as interval compares, same as the XLA path.

The row-block + halo DMA scheme is the single-chip mirror of the
multi-chip design: what ``parallel/halo.py`` does with ``ppermute``
between chips, this does with wrapped DMAs between row blocks of one
chip's HBM.  Reference analog: the per-cell ``next()`` sweep
(``/root/reference/main.cpp:79-103``), here as one VPU pass per block.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.ops.stencil import _in_any_interval


def _pick_block_rows(H: int, W: int, radius: int) -> Optional[int]:
    """Largest divisor of H with block bytes in a VMEM-friendly budget."""
    del radius  # halo slabs are a fixed 8 rows for any supported radius
    budget = 1 << 21  # 2 MiB per double-buffer slot (uint8, +16 halo rows)
    best = None
    for bm in (512, 256, 128, 64, 32, 16, 8):
        if H % bm == 0 and (bm + 16) * W <= budget:
            best = bm
            break
    return best


def _pick_sub_rows(BM: int, W: int) -> int:
    """Row sub-tile so each widened (SR, W) i32 temp stays <= 1 MiB."""
    sr = BM
    while sr > 8 and sr * W * 4 > (1 << 20):
        sr //= 2
    return sr


def supports(shape, rule: Rule) -> bool:
    """Shapes the kernel handles; callers fall back to the XLA path else."""
    H, W = shape
    return (
        W % 128 == 0
        and H >= 2 * rule.radius
        and _pick_block_rows(H, W, rule.radius) is not None
    )


def _make_kernel(rule: Rule, boundary: str, H: int, W: int, BM: int):
    r = rule.radius
    win = 2 * r + 1
    periodic = boundary == "periodic"
    nblocks = H // BM
    birth_iv = rule.birth_intervals
    survive_iv = rule.survive_intervals

    # DMA row slices must be aligned to the (8, 128) sublane tiling, so the
    # halo slabs are a fixed 8 rows (>= r for every supported radius) and
    # the kernel reads the r rows it needs from inside the slab.
    HALO = 8
    assert r <= HALO and BM % HALO == 0

    def _block_dmas(in_hbm, scratch, sems, blk, slot):
        """The three async copies loading block `blk` into scratch slot
        `slot`: top halo slab, center rows, bottom halo slab.  Slab starts
        wrap modulo H — periodic rows come out of the addressing; dead rows
        are zeroed at compute time.  rem() hides divisibility from the
        compiler, so re-assert the 8-row alignment of the wrapped starts
        (base and H are multiples of HALO)."""
        base = blk * BM
        top = pl.multiple_of(lax.rem(base - HALO + H, H), HALO)
        bot = pl.multiple_of(lax.rem(base + BM, H), HALO)
        return (
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, HALO), :],
                scratch.at[slot, pl.ds(0, HALO), :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(base, BM), :],
                scratch.at[slot, pl.ds(HALO, BM), :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, HALO), :],
                scratch.at[slot, pl.ds(HALO + BM, HALO), :],
                sems.at[slot, 2],
            ),
        )

    def kernel(in_hbm, out_ref, dbuf, sems):
        # Double-buffered streaming (pallas_guide.md "Patterns: Double
        # Buffering"): scratch persists across grid programs, so program i
        # prefetches block i+1 into the other slot before computing block i
        # — the next block's HBM reads overlap this block's VPU work.
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        next_slot = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, 0, 0):
                d.start()

        @pl.when(i + 1 < nblocks)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, i + 1, next_slot):
                d.start()

        for d in _block_dmas(in_hbm, dbuf, sems, i, slot):
            d.wait()

        scratch = dbuf.at[slot]

        if not periodic:
            @pl.when(i == 0)
            def _():
                scratch[0:HALO, :] = jnp.zeros((HALO, W), dtype=jnp.uint8)

            @pl.when(i == nblocks - 1)
            def _():
                scratch[HALO + BM :, :] = jnp.zeros((HALO, W), dtype=jnp.uint8)

        # Mosaic vector arithmetic needs i16/i32 and lane rotates need i32,
        # so sums are computed widened — but widening the whole block would
        # blow VMEM at large widths.  Process the block in row sub-tiles:
        # only (SR, W) i32 temporaries are ever live.
        SR = _pick_sub_rows(BM, W)
        lane = (
            None if periodic
            else lax.broadcasted_iota(jnp.int32, (SR, W), dimension=1)
        )
        for s0 in range(0, BM, SR):
            lo = HALO - r + s0
            v = scratch[lo : lo + SR, :].astype(jnp.int32)
            for k in range(1, win):
                v = v + scratch[lo + k : lo + k + SR, :].astype(jnp.int32)
            # horizontal window sum via lane rotations; pltpu.roll takes
            # non-negative shifts: shift s rotates lanes right (column j
            # reads j-s); the left rotation is shift W-s.
            h = v
            if periodic:
                for s in range(1, r + 1):
                    h = h + pltpu.roll(v, s, axis=1) + pltpu.roll(v, W - s, axis=1)
            else:
                zero = jnp.zeros_like(v)
                for s in range(1, r + 1):
                    left = jnp.where(lane >= s, pltpu.roll(v, s, axis=1), zero)
                    right = jnp.where(lane < W - s, pltpu.roll(v, W - s, axis=1), zero)
                    h = h + left + right
            center = scratch[HALO + s0 : HALO + s0 + SR, :].astype(jnp.int32)
            counts = h - center
            # keep the select in i32 lanes; a single i32->i8 truncation at
            # the store is the only narrow op Mosaic needs to handle
            born = _in_any_interval(counts, birth_iv).astype(jnp.int32)
            keep = _in_any_interval(counts, survive_iv).astype(jnp.int32)
            out_ref[s0 : s0 + SR, :] = jnp.where(center != 0, keep, born).astype(
                jnp.uint8
            )

    return kernel


def pallas_step(
    grid: jax.Array,
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
) -> jax.Array:
    """One generation on a single device via the fused kernel.
    Requires ``supports(grid.shape, rule)``."""
    H, W = grid.shape
    BM = _pick_block_rows(H, W, rule.radius)
    if BM is None or not supports(grid.shape, rule):
        raise ValueError(
            f"pallas_step does not support shape {grid.shape} "
            f"(need W % 128 == 0 and a VMEM-sized row-block divisor of H)"
        )
    r = rule.radius
    kernel = _make_kernel(rule, boundary, H, W, BM)
    return pl.pallas_call(
        kernel,
        grid=(H // BM,),
        out_shape=jax.ShapeDtypeStruct((H, W), jnp.uint8),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((BM, W), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            # two slots of (BM + two 8-row halo slabs) for double buffering
            pltpu.VMEM((2, BM + 16, W), jnp.uint8),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )(grid)


def make_pallas_stepper(rule: Rule = LIFE, boundary: str = "periodic", interpret: bool = False):
    """evolve(grid, steps) using the fused kernel per step; jitted with a
    donated carry so ``evolve.lower`` works for ahead-of-time compilation
    (the same contract as ``pallas_bitlife.make_pallas_bit_stepper``)."""

    @functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=0)
    def evolve(grid: jax.Array, steps: int) -> jax.Array:
        def body(g, _):
            return pallas_step(g, rule, boundary, interpret=interpret), None

        out, _ = lax.scan(body, grid, None, length=steps)
        return out

    return evolve


def use_pallas(shape, rule: Rule) -> bool:
    """Single source of truth for the kernel-vs-XLA dispatch: the fused
    kernel needs a real TPU backend and a supported shape."""
    return jax.default_backend() == "tpu" and supports(shape, rule)


def best_step_fn(shape, rule: Rule = LIFE):
    """step(grid, rule, boundary) — fused kernel where eligible, XLA else."""
    if use_pallas(shape, rule):
        return pallas_step
    from mpi_tpu.ops.stencil import step

    return step


def best_stepper(shape, rule: Rule = LIFE, boundary: str = "periodic"):
    """The fastest available single-device stepper for this shape: the
    fused Pallas kernel on TPU when the shape qualifies, else the XLA
    shift-add path (which works everywhere, any shape)."""
    if use_pallas(shape, rule):
        return make_pallas_stepper(rule, boundary)
    from mpi_tpu.ops.stencil import make_stepper

    return make_stepper(rule, boundary)
