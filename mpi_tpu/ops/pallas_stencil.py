"""Fused Pallas TPU stencil kernel — the hot path for the per-step update.

Why a kernel when XLA already fuses the shift-add stencil
(``ops/stencil.py``)?  The XLA path materializes the padded array and the
row-sum intermediate in HBM each step (~5x the grid's bytes of traffic);
this kernel streams each row-block through VMEM exactly once: one HBM read
per cell, one HBM write, everything else (vertical window sum, horizontal
window sum via lane rotations, rule select) stays in registers/VMEM.  At
HBM-bandwidth-bound sizes that is the difference between ~37 and >100
G cell-updates/s on one v5e chip.

Structure (cf. pallas_guide.md "Async DMA" / "Grid and Block
Specifications"):

* the grid stays **unpadded** in HBM (``memory_space=ANY``); the kernel
  grid iterates over row blocks;
* each program DMAs its block plus a radius-wide row halo into a VMEM
  scratch (three DMAs: top halo, center, bottom halo — the top/bottom
  start rows wrap modulo H, which implements periodic rows for free;
  dead rows are zeroed with ``pl.when`` at the edge blocks);
* column neighbors come from ``pltpu.roll`` lane rotations (periodic
  columns for free; dead columns are masked with a lane iota);
* the B/S rule is applied as interval compares, same as the XLA path.

The row-block + halo DMA scheme is the single-chip mirror of the
multi-chip design: what ``parallel/halo.py`` does with ``ppermute``
between chips, this does with wrapped DMAs between row blocks of one
chip's HBM.  Reference analog: the per-cell ``next()`` sweep
(``/root/reference/main.cpp:79-103``), here as one VPU pass per block.

Temporal blocking (``gens`` > 1, the dense mirror of
``ops/pallas_bitlife.py``): the DMA-alignment halo slab (8 rows, or 16
when gens·r > 8) is deeper than one generation's radius needs, so after
one HBM round-trip the slab is stepped up to ``gens`` generations in
VMEM — each generation trims ``r`` valid rows from each side of the
scratch window (the classic trapezoidal tiling; neighboring blocks
recompute each other's fringe redundantly from the same input, so
blocks stay independent), and after ``gens`` generations the middle BM
rows are exactly ``gens`` steps ahead.  One kernel invocation replaces
the chain of ``gens`` per-generation ``pallas_call``s a ``comm_every=k``
segment used to issue: HBM traffic AND dispatch count both drop
``gens``×.  Bounded by gens·r ≤ 16 (the halo slab).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.ops.stencil import _in_any_interval


def _halo_rows(gens: int, radius: int) -> int:
    """DMA row slices must be 8-sublane aligned; the halo must also cover
    ``radius`` consumed rows per temporally-blocked generation."""
    return 8 if gens * radius <= 8 else 16


def _pick_block_rows(H: int, W: int, radius: int, gens: int = 1) -> Optional[int]:
    """Largest divisor of H with block bytes in a VMEM-friendly budget."""
    halo = _halo_rows(gens, radius)
    if gens * radius > halo:
        return None  # the trapezoid would consume more than the slab
    if halo > 8 and H % halo:
        return None  # wrapped halo-slab DMA starts must stay halo-aligned
    budget = 1 << 21  # 2 MiB per double-buffer slot (uint8, + halo slabs)
    best = None
    for bm in (512, 256, 128, 64, 32, 16, 8):
        if halo > 8 and bm % halo:
            continue
        if H % bm == 0 and (bm + 2 * halo) * W <= budget:
            best = bm
            break
    return best


def _pick_sub_rows(BM: int, W: int) -> int:
    """Row sub-tile so each widened (SR, W) i32 temp stays <= 1 MiB."""
    sr = BM
    while sr > 8 and sr * W * 4 > (1 << 20):
        sr //= 2
    return sr


def supports(shape, rule: Rule, gens: int = 1) -> bool:
    """Shapes the kernel handles at the given temporal-blocking depth
    (deeper gens need a deeper halo slab, so query with the gens you will
    run); callers fall back to the XLA path else."""
    H, W = shape
    return (
        W % 128 == 0
        and H >= 2 * rule.radius * gens
        and _pick_block_rows(H, W, rule.radius, gens) is not None
    )


def _out_struct(grid, H: int, W: int):
    """Output aval for the kernel: when tracing inside ``shard_map`` the
    result varies over the same mesh axes as the input, and shard_map's
    vma checking requires that to be declared on the out_shape."""
    try:
        vma = jax.typeof(grid).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        return jax.ShapeDtypeStruct((H, W), jnp.uint8, vma=vma)
    return jax.ShapeDtypeStruct((H, W), jnp.uint8)


def _make_kernel(
    rule: Rule, boundary: str, H: int, W: int, BM: int,
    gens: int = 1, SR: Optional[int] = None,
):
    r = rule.radius
    win = 2 * r + 1
    periodic = boundary == "periodic"
    nblocks = H // BM
    birth_iv = rule.birth_intervals
    survive_iv = rule.survive_intervals

    # DMA row slices must be aligned to the (8, 128) sublane tiling, so the
    # halo slabs are 8 rows (>= r for every supported radius) — or 16 when
    # the temporal-blocking trapezoid consumes more than 8 (gens·r > 8) —
    # and the kernel reads the rows it needs from inside the slab.
    HALO = _halo_rows(gens, r)
    assert gens * r <= HALO and BM % HALO == 0

    def _block_dmas(in_hbm, scratch, sems, blk, slot):
        """The three async copies loading block `blk` into scratch slot
        `slot`: top halo slab, center rows, bottom halo slab.  Slab starts
        wrap modulo H — periodic rows come out of the addressing; dead rows
        are zeroed at compute time.  rem() hides divisibility from the
        compiler, so re-assert the 8-row alignment of the wrapped starts
        (base and H are multiples of HALO)."""
        base = blk * BM
        top = pl.multiple_of(lax.rem(base - HALO + H, H), HALO)
        bot = pl.multiple_of(lax.rem(base + BM, H), HALO)
        return (
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, HALO), :],
                scratch.at[slot, pl.ds(0, HALO), :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(base, BM), :],
                scratch.at[slot, pl.ds(HALO, BM), :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, HALO), :],
                scratch.at[slot, pl.ds(HALO + BM, HALO), :],
                sems.at[slot, 2],
            ),
        )

    def kernel(in_hbm, out_ref, dbuf, sems):
        # Double-buffered streaming (pallas_guide.md "Patterns: Double
        # Buffering"): scratch persists across grid programs, so program i
        # prefetches block i+1 into the other slot before computing block i
        # — the next block's HBM reads overlap this block's VPU work.
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        next_slot = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, 0, 0):
                d.start()

        @pl.when(i + 1 < nblocks)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, i + 1, next_slot):
                d.start()

        for d in _block_dmas(in_hbm, dbuf, sems, i, slot):
            d.wait()

        scratch = dbuf.at[slot]

        if not periodic:
            # Zero the whole edge slabs: rows beyond the grid are dead.
            # (This only establishes the gen-0 state — during multi-gen
            # loops the slab rows adjacent to live grid rows can be "born"
            # and must be re-killed after every generation; see below.)
            @pl.when(i == 0)
            def _():
                scratch[0:HALO, :] = jnp.zeros((HALO, W), dtype=jnp.uint8)

            @pl.when(i == nblocks - 1)
            def _():
                scratch[HALO + BM :, :] = jnp.zeros((HALO, W), dtype=jnp.uint8)

        # Mosaic vector arithmetic needs i16/i32 and lane rotates need i32,
        # so sums are computed widened — but widening the whole block would
        # blow VMEM at large widths.  Process each generation's row window
        # in sub-tiles: only (rows <= SR, W) i32 temporaries are ever live.
        sr = SR if SR is not None else _pick_sub_rows(BM, W)
        assert sr >= r  # the saved-rows carry holds exactly r rows

        def sub_gen(wn, rows):
            """Next state of the middle ``rows`` rows of window ``wn``
            ((rows + 2r, W) uint8, the pre-generation state)."""
            v = wn[0:rows, :].astype(jnp.int32)
            for k in range(1, win):
                v = v + wn[k : k + rows, :].astype(jnp.int32)
            # horizontal window sum via lane rotations; pltpu.roll takes
            # non-negative shifts: shift s rotates lanes right (column j
            # reads j-s); the left rotation is shift W-s.
            h = v
            if periodic:
                for s in range(1, r + 1):
                    h = h + pltpu.roll(v, s, axis=1) + pltpu.roll(v, W - s, axis=1)
            else:
                lane = lax.broadcasted_iota(jnp.int32, (rows, W), dimension=1)
                zero = jnp.zeros_like(v)
                for s in range(1, r + 1):
                    left = jnp.where(lane >= s, pltpu.roll(v, s, axis=1), zero)
                    right = jnp.where(lane < W - s, pltpu.roll(v, W - s, axis=1), zero)
                    h = h + left + right
            center = wn[r : r + rows, :].astype(jnp.int32)
            counts = h - center
            # keep the select in i32 lanes; a single i32->i8 truncation at
            # the store is the only narrow op Mosaic needs to handle
            born = _in_any_interval(counts, birth_iv).astype(jnp.int32)
            keep = _in_any_interval(counts, survive_iv).astype(jnp.int32)
            return jnp.where(center != 0, keep, born).astype(jnp.uint8)

        # Each generation consumes r valid rows from each side of the slab;
        # only rows that later generations (or the output block) still need
        # are recomputed.  Within a generation the row window is evaluated
        # in SR-row sub-tiles; the update is in place, so each sub-tile's
        # top r neighbor rows (overwritten by the previous sub-tile) are
        # carried in ``saved``.  All bounds are Python ints — fully static.
        lo, hi = 0, BM + 2 * HALO
        for g in range(gens):
            rem = gens - 1 - g  # generations still to run after this one
            glo = max(lo + r, HALO - rem * r)
            ghi = min(hi - r, HALO + BM + rem * r)
            saved = None
            a = glo
            while a < ghi:
                b = min(a + sr, ghi)
                rows = b - a
                # pre-generation rows [a - r, b + r): the top r rows were
                # overwritten by the previous sub-tile and ride in `saved`
                top = scratch[a - r : a, :] if saved is None else saved
                wn = jnp.concatenate([top, scratch[a : b + r, :]], axis=0)
                if rem:
                    saved = scratch[b - r : b, :]  # old value, read pre-write
                new = sub_gen(wn, rows)
                if rem:
                    scratch[a:b, :] = new
                else:
                    out_ref[a - HALO : b - HALO, :] = new
                a = b
            if rem:
                if not periodic:
                    # Rows beyond the grid edge are not real cells: live
                    # grid neighbors would "give birth" into them — re-kill
                    # them after every in-VMEM generation at the edge blocks.
                    if glo < HALO:
                        @pl.when(i == 0)
                        def _():
                            scratch[glo:HALO, :] = jnp.zeros(
                                (HALO - glo, W), dtype=jnp.uint8
                            )

                    if ghi > HALO + BM:
                        @pl.when(i == nblocks - 1)
                        def _():
                            scratch[HALO + BM : ghi, :] = jnp.zeros(
                                (ghi - HALO - BM, W), dtype=jnp.uint8
                            )
                lo, hi = glo, ghi

    return kernel


def pallas_step(
    grid: jax.Array,
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
    gens: int = 1,
    blocks: tuple[int, int] | None = None,
) -> jax.Array:
    """``gens`` generations (default one) on a single device via the fused
    kernel, in a single HBM round-trip.  Requires
    ``supports(grid.shape, rule, gens)``.  ``blocks`` overrides the
    auto-picked (BM, SR) DMA-block/sub-tile rows (the autotuner's knob)."""
    H, W = grid.shape
    BM, SR = blocks if blocks else (None, None)
    if BM is None:
        BM = _pick_block_rows(H, W, rule.radius, gens)
    if BM is None or not supports(grid.shape, rule, gens):
        raise ValueError(
            f"pallas_step does not support shape {grid.shape} at gens={gens} "
            f"(need W % 128 == 0 and a VMEM-sized row-block divisor of H)"
        )
    if gens > 1 and 0 in rule.birth:
        # dead-boundary halo rows must stay dead across in-VMEM generations
        raise ValueError("gens > 1 requires a rule without birth-on-0")
    HALO = _halo_rows(gens, rule.radius)
    kernel = _make_kernel(rule, boundary, H, W, BM, gens, SR)
    return pl.pallas_call(
        kernel,
        grid=(H // BM,),
        out_shape=_out_struct(grid, H, W),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((BM, W), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            # two slots of (BM + two halo slabs) for double buffering
            pltpu.VMEM((2, BM + 2 * HALO, W), jnp.uint8),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )(grid)


def make_pallas_stepper(
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
    gens: int = 1,
    blocks: tuple[int, int] | None = None,
):
    """evolve(grid, steps) using the fused kernel, running ``gens``
    generations per kernel pass (temporal blocking); jitted with a donated
    carry so ``evolve.lower`` works for ahead-of-time compilation (the same
    contract as ``pallas_bitlife.make_pallas_bit_stepper``).  ``blocks``
    overrides the auto-picked (BM, SR) per pass — the autotuner's
    block-shape knob (a bad override fails at compile and takes the
    engine's XLA fallback, never a wrong answer)."""
    from mpi_tpu.utils.segmenting import segmented_evolve

    def make_local(k):
        def local(g):
            return pallas_step(g, rule, boundary, interpret=interpret,
                               gens=k, blocks=blocks)

        return local

    return segmented_evolve(make_local, gens)


def use_pallas(shape, rule: Rule) -> bool:
    """Single source of truth for the kernel-vs-XLA dispatch: the fused
    kernel needs a real TPU backend and a supported shape."""
    return jax.default_backend() == "tpu" and supports(shape, rule)


def best_step_fn(shape, rule: Rule = LIFE):
    """step(grid, rule, boundary) — fused kernel where eligible, XLA else."""
    if use_pallas(shape, rule):
        return pallas_step
    from mpi_tpu.ops.stencil import step

    return step


def best_stepper(shape, rule: Rule = LIFE, boundary: str = "periodic"):
    """The fastest available single-device stepper for this shape: the
    fused Pallas kernel on TPU when the shape qualifies, else the XLA
    shift-add path (which works everywhere, any shape)."""
    if use_pallas(shape, rule):
        return make_pallas_stepper(rule, boundary)
    from mpi_tpu.ops.stencil import make_stepper

    return make_stepper(rule, boundary)
