"""Activity-gated sparse stepping: skip tiles that provably cannot change.

Realistic long-running boards are mostly quiescent — still lifes, dead
space, a few oscillators and gliders — yet the dense steppers recompute
every cell every generation.  This module partitions the board into
fixed T×T tiles and maintains an **on-device per-tile dirty map**: a
tile is *active* next step iff it or any of its 8 tile-neighbors changed
this step.  State propagates at most ``r`` cells per generation (the
rule's neighborhood radius), so for T >= r one ring of tile dilation is
an *exact* superset of everything that can change — skipped tiles are
bit-identical to recomputed ones, by construction, for every rule and
boundary.

Architecture (shaped by measurement, see PERF.md): XLA:CPU materializes
a full copy of every buffer that crosses a ``lax.cond``/``switch``
boundary, and a dense SWAR step only costs ~2 copies' worth of work —
so a per-step branch between sparse and dense can never win more than
~2x.  The evolve is instead a **phase pipeline of while_loops** inside
one jitted program (while-loop carries alias in place; nothing is
copied at phase boundaries):

  outer loop until the step budget is spent:
    for K in capacity ladder (ascending):   # sparse phases
      while steps remain and the active set fits K: one K-tile step
    while the board is active:                        # dense phases
      unprobed dense generations in a descending chunk ladder of
      static-trip fori_loops under an all-ones changed map, then ONE
      probed final generation that compares consecutive grids into an
      exact per-tile changed map — every dispatch hands back an exact
      map, and the probe tax is paid once per dispatch, not per step

Each sparse step is fixed-shape and in-place: ``jnp.nonzero(size=K,
fill_value=0)`` pads the K-slot active list with tile 0 (padding lanes
recompute a tile and write back the identical correct value — no mask
needed); the K haloed tiles are gathered side by side into ONE wide
[tile+2·halo rows, K·(tile+2·halo) cols] stripe and stepped by
dead-boundary calls of the engine's kernel — each tile owns a column
stripe, so vertical neighbor reads stay inside its stripe and
horizontal reads reach at most the halo columns that get sliced off —
then written back with a chain of in-place ``dynamic_update_slice``.
The halo is gathered **s·r deep** and the stripe stepped **s
generations** before scattering (deep halo: the interior stays exact
for s generations, and change propagates at most s·r <= T cells, so
the one-ring dilation still covers everything that can change between
dirty-map updates).  That amortizes the fixed nonzero/gather/scatter
costs — the bulk of a sparse step on XLA:CPU — over s generations.
The dirty bit accumulates CONSECUTIVE-generation interior compares,
so oscillators of any period stay marked.  The ascending-K ladder
keeps the static gather cost proportional to the board's actual
activity; above the top rung the dense phase IS the fast path
(measured: big-K gather/scatter loses to the dense kernel's one
contiguous sweep).  Hysteresis is the gap between the dense phase's
entry (active > top rung) and exit (active <= release threshold)
conditions.  The dense phase's between-probe changed map is implicitly
all-ones — a conservative superset, so exactness is preserved while the
full-grid compare cost is amortized to 1/P.  Everything stays on
device: no per-step host sync, donation-safe, vmap-safe (batched
serving lanes mask independently).

Tiles are expressed in *array units*: rows are cells, columns are words
for the packed SWAR/LtL engines (T must be a multiple of 32 there) and
cells for the dense engine.  ``backends/tpu.py`` builds the
:class:`TilePlan` and supplies the stripe-local step (``bit_step`` /
``ltl_step`` / ``stencil.step`` with boundary="dead").
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
from dataclasses import dataclass
from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# Capacity ladder (fractions of total tiles): the active list is padded
# to a STATIC size per rung, so a sparse step costs its rung, not the
# true active count — one big capacity would cap the win at ~2x.  The
# pipeline tries rungs in ascending order; a nearly quiescent board
# rides the smallest rung.  The ladder deliberately stops at 1/8: the
# measured crossover on XLA:CPU is near there — gathering and
# per-tile-scattering more tiles than that loses to the dense kernel's
# one contiguous sweep, so denser boards take the dense phase.
CAPACITY_FRACS = (1 / 32, 1 / 8)
# Hysteresis: the dense phase is entered when the active set exceeds the
# top rung (CAPACITY_FRACS[-1]) and exited only when a probe finds it at
# or below RELEASE_FRAC — the gap prevents mode oscillation near the
# threshold.
RELEASE_FRAC = 0.10
# Dense-phase chunk ladder: unprobed dense generations run in
# statically-unrolled chunks, largest first, under an all-ones changed
# map (a conservative superset — exactness preserved).  Only the
# dispatch's FINAL generation pays the exact changed-map compare (the
# probe, measured ~2-4ms vs a ~0.7ms step at 4096^2), so the dense-mode
# tax is one probe per dispatch regardless of depth, and every evolve
# call still hands back an exact map — hysteresis release to sparse
# happens at dispatch boundaries, where serve observes it anyway.
DENSE_CHUNKS = (128, 32, 8, 1)
# Deep-halo depth for the sparse phases: gather each active tile with an
# s*r-deep halo and step the stripe s generations in place before
# scattering — the classic deep-halo trade, applied to the gather.  The
# fixed per-iteration costs (nonzero, gather, scatter, map update) are
# the bulk of a sparse step on XLA:CPU, so amortizing them over s
# generations roughly halves the per-generation cost.  Capped at
# tile_px // radius: the one-ring tile dilation must cover s*r cells of
# propagation between dirty-map updates.
DEPTH_TARGET = 8

# Persistent-compile-cache opt-out for the sparse evolve.  jaxlib
# 0.4.37's XLA:CPU intermittently corrupts the heap when THIS module's
# jitted evolve is **deserialized** from the persistent compilation
# cache: warm-cache processes segfault ~25-50% of the time at a later,
# unrelated allocation (the crash site wanders — numpy unpacking,
# importlib), while cold compiles and cache-disabled runs never crash,
# and dense-only cached runs never crash.  Op-level micro-repros
# (padded nonzero, modular gather, while/fori scatter chains, even a
# miniature donated evolve) do NOT reproduce it — the bug needs the
# real full-size program — so rather than chase the op we opt this one
# executable out of the cache:
#   * a per-process net-zero salt constant is folded into the traced
#     program, so its cache key can never match an entry serialized by
#     another process — the deserialization path is unreachable;
#   * the write side is suppressed around this program's compiles (the
#     salted key would otherwise strand one orphan entry per process
#     in the unbounded LRU directory).
# In-process jit caching is untouched (the salt is constant within a
# process): still exactly one compile per (shape, depth).
# The opt-out is version-gated (:func:`_cache_optout_active`): the root
# cause is XLA:CPU's executable **deserialization** path in jaxlib
# <= 0.4.37 (heap corruption when this module's full-size donated
# while/gather/scatter program is reloaded from the persistent cache);
# newer jaxlibs rebuilt that path, so they keep warm-cache starts.
_CACHE_SALT: int = (
    os.getpid() ^ int.from_bytes(os.urandom(4), "little")) & 0x7FFFFFFF


@functools.lru_cache(maxsize=1)
def _cache_optout_active() -> bool:
    """True when the sparse evolve must opt out of the persistent compile
    cache: jaxlib <= 0.4.37, whose XLA:CPU corrupts the heap while
    deserializing this module's jitted evolve (see _CACHE_SALT above).
    Unparseable versions count as affected — the opt-out only costs a
    recompile, the bug costs a segfault."""
    try:
        import jaxlib

        ver = tuple(int(p) for p in jaxlib.__version__.split(".")[:3])
    except Exception:  # pragma: no cover — version scheme changed
        return True
    return ver <= (0, 4, 37)


def cache_salt() -> int:
    """The live per-process cache salt.  The IR verifier's canonicalizer
    (analysis/ir/canon.py) scrubs literals equal to this value so sparse
    stepper fingerprints stay stable across processes."""
    return _CACHE_SALT


def _no_persistent_cache_write():
    """Context manager raising the persistent cache's min-compile-time
    write threshold so the enclosed compile is never serialized; no-op
    if the private config relayouts in a future jax."""
    try:
        from jax._src.config import persistent_cache_min_compile_time_secs
        return persistent_cache_min_compile_time_secs(float("inf"))
    except Exception:  # pragma: no cover — jax internals moved
        return contextlib.nullcontext()


class _UncachedLowered:
    """Proxy over a ``jax.stages.Lowered`` whose ``compile`` runs under
    the persistent-cache write suppression."""

    def __init__(self, lowered):
        self._lowered = lowered

    def compile(self, *args, **kwargs):
        with _no_persistent_cache_write():
            return self._lowered.compile(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._lowered, name)


class _UncachedEvolve:
    """Callable proxy over the jitted sparse evolve mirroring the two
    entry points the engine uses (``__call__`` and ``lower().compile()``)
    with persistent-cache writes suppressed around the actual compile."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, state, steps):
        with _no_persistent_cache_write():
            return self._fn(state, steps)

    def lower(self, state, steps):
        return _UncachedLowered(self._fn.lower(state, steps))

    def __getattr__(self, name):
        return getattr(self._fn, name)


class SparseState(NamedTuple):
    """Pytree carried through jit/scan/vmap in place of the bare grid:
    the engine's array (packed words or dense cells) plus the [nti, ntj]
    bool map of tiles that changed during the last committed step (an
    all-ones map is always a safe — merely slower — value)."""

    grid: jax.Array
    changed: jax.Array


@dataclass(frozen=True)
class TilePlan:
    """Static tile geometry, in array units (rows=cells, cols=words for
    packed engines).  ``tile_px`` is the user-facing tile size in cells
    (the ``sparse_tile`` knob); ``cell_cols_per_unit`` converts array
    columns back to cells (32 for packed, 1 for dense).  ``capacities``
    is the ascending static-gather rung ladder; ``release_tiles`` the
    hysteresis release threshold (see RELEASE_FRAC)."""

    tile_px: int
    tile_r: int
    tile_c: int
    halo_r: int
    halo_c: int
    nti: int
    ntj: int
    capacities: tuple
    release_tiles: int
    periodic: bool
    cell_cols_per_unit: int
    gens: int = 1                     # deep-halo generations per gather

    @property
    def ntiles(self) -> int:
        return self.nti * self.ntj

    @property
    def capacity(self) -> int:
        """Top rung — the largest active set the sparse phases serve."""
        return self.capacities[-1]


def make_plan(*, rows: int, cols_units: int, tile_px: int, radius: int,
              periodic: bool, packed: bool, depth: int = 0) -> TilePlan:
    """Tile geometry for a [rows, cols_units] grid.  Callers validate
    divisibility and T >= r up front (ConfigError with context); the
    asserts here are the last line of defense.  ``depth`` overrides the
    deep-halo generations-per-gather (0 = auto: DEPTH_TARGET capped so
    s*r propagation stays within one tile ring)."""
    unit = 32 if packed else 1
    assert tile_px % unit == 0 and rows % tile_px == 0
    assert (cols_units * unit) % tile_px == 0 and tile_px >= radius
    gens = max(1, min(depth or DEPTH_TARGET, tile_px // radius))
    tile_r = tile_px
    tile_c = tile_px // unit
    nti = rows // tile_r
    ntj = cols_units // tile_c
    ntiles = nti * ntj
    capacities = tuple(sorted(set(
        max(1, min(ntiles, math.ceil(f * ntiles))) for f in CAPACITY_FRACS)))
    release_tiles = min(capacities[-1], max(1, int(RELEASE_FRAC * ntiles)))
    halo = gens * radius
    return TilePlan(
        tile_px=tile_px, tile_r=tile_r, tile_c=tile_c,
        halo_r=halo,
        halo_c=max(1, math.ceil(halo / unit)) if packed else halo,
        nti=nti, ntj=ntj, capacities=capacities,
        release_tiles=release_tiles, periodic=periodic,
        cell_cols_per_unit=unit, gens=gens,
    )


def initial_state(grid: jax.Array, plan: TilePlan) -> SparseState:
    """Wrap a freshly initialized grid.  The prior step is unknown, so
    every tile is marked changed — the first dense probe settles the
    gate on its own."""
    return SparseState(
        grid=grid,
        changed=jnp.ones((plan.nti, plan.ntj), dtype=jnp.bool_),
    )


def dilate_tiles(changed: jax.Array, periodic: bool) -> jax.Array:
    """8-neighbor dilation of the tile changed map (separable 3×3 OR).
    Periodic boundaries wrap — an edge tile neighbors across the seam,
    so a glider leaving the right edge re-activates the left column."""
    def along(x, axis):
        if periodic:
            return x | jnp.roll(x, 1, axis=axis) | jnp.roll(x, -1, axis=axis)
        pad = [(0, 0), (0, 0)]
        pad[axis] = (1, 1)
        p = jnp.pad(x, pad)
        n = x.shape[axis]
        return (lax.slice_in_dim(p, 0, n, axis=axis)
                | lax.slice_in_dim(p, 1, n + 1, axis=axis)
                | lax.slice_in_dim(p, 2, n + 2, axis=axis))
    return along(along(changed, 0), 1)


def active_count(changed: jax.Array, periodic: bool) -> jax.Array:
    """Number of tiles the NEXT step must compute (dilated changed map;
    int32 scalar, traced — the phase-pipeline loop conditions)."""
    return jnp.sum(dilate_tiles(changed, periodic), dtype=jnp.int32)


def gather_stripe(grid: jax.Array, ti: jax.Array, tj: jax.Array,
                  plan: TilePlan) -> jax.Array:
    """[tile_r + 2*halo_r, K*(tile_c + 2*halo_c)] wide stripe of the K
    haloed tiles laid side by side (tile k owns columns [k*C, (k+1)*C)).
    Periodic wrap is modular indexing — no full-grid pad copy; dead
    edges clip and mask the out-of-board halo to zero."""
    H, W = grid.shape
    C = plan.tile_c + 2 * plan.halo_c
    rows = jnp.arange(-plan.halo_r, plan.tile_r + plan.halo_r,
                      dtype=jnp.int32)
    cols = jnp.arange(-plan.halo_c, plan.tile_c + plan.halo_c,
                      dtype=jnp.int32)
    # wrap/clip on the small per-tile [K, R] / [K, C] index vectors
    # BEFORE broadcasting to the stripe shape — the integer div/mod is
    # a measurable fraction of the gather at small K
    ur = ti[:, None] * plan.tile_r + rows[None, :]
    uc = tj[:, None] * plan.tile_c + cols[None, :]
    if plan.periodic:
        rr = jnp.repeat((ur % H).T, C, axis=1)
        cc = (uc % W).reshape(-1)[None, :]
        return grid[rr, jnp.broadcast_to(cc, rr.shape)]
    rr = jnp.repeat(jnp.clip(ur, 0, H - 1).T, C, axis=1)
    cc = jnp.clip(uc, 0, W - 1).reshape(-1)[None, :]
    valid = (jnp.repeat(((ur >= 0) & (ur < H)).T, C, axis=1)
             & ((uc >= 0) & (uc < W)).reshape(-1)[None, :])
    stripe = grid[rr, jnp.broadcast_to(cc, rr.shape)]
    return jnp.where(valid, stripe, jnp.zeros((), dtype=grid.dtype))


def tile_changed_map(new: jax.Array, old: jax.Array, plan: TilePlan) -> jax.Array:
    """Exact [nti, ntj] map of tiles where new != old.  ONLY valid
    across a single generation (the probe compares consecutive steps —
    a longer baseline would mark period-p oscillators clean)."""
    d = new != old
    # split reduction (columns first, then rows) — the fused one-shot
    # any(axis=(1, 3)) reduce measures ~15% slower inside the dense loop
    return (d.reshape(plan.nti, plan.tile_r, plan.ntj, plan.tile_c)
            .any(axis=3).any(axis=1))


def make_sparse_evolve(base_evolve: Callable, local_step: Callable,
                       plan: TilePlan) -> Callable:
    """The Engine-facing evolve: ``(SparseState, steps) -> SparseState``,
    jitted with a static step count and a donated carry — the same
    contract as the dense evolves it wraps, so ``Engine.step`` /
    ``step_units`` / the vmapped batched stepper work unchanged.

    ``base_evolve`` advances a full grid (the engine's dense evolve,
    used at depth 1 by the dense phase); ``local_step`` maps a wide
    dead-boundary stripe of side-by-side haloed tiles to its stepped
    stripe (interiors sliced out here)."""
    hr, hc = plan.halo_r, plan.halo_c
    tr, tc = plan.tile_r, plan.tile_c
    C = tc + 2 * hc

    def sparse_body(K, g):
        def body(st):
            grid, changed, done = st
            active = dilate_tiles(changed, plan.periodic)
            (idx,) = jnp.nonzero(active.reshape(-1), size=K, fill_value=0)
            idx = idx.astype(jnp.int32)
            ti = idx // plan.ntj
            tj = idx % plan.ntj
            stripe = gather_stripe(grid, ti, tj, plan)

            def interior(x):
                return x[hr:hr + tr].reshape(tr, K, C)[:, :, hc:hc + tc]

            # g in-stripe generations per gather (deep halo: the s*r-deep
            # halo keeps the interior exact for s generations, so the
            # fixed nonzero/gather/scatter costs amortize over g).  The
            # dirty bit accumulates CONSECUTIVE interior compares — a
            # final-vs-initial compare would mark period-p oscillators
            # (p dividing g) clean and freeze them.  The static-trip
            # fori_loop is a fusion boundary: unrolling the stencil
            # chain makes XLA:CPU fuse it into one fusion whose
            # recomputation grows exponentially with depth (measured
            # 200x slower at depth 8)
            def gen(_, carry):
                cur, acc = carry
                nxt = local_step(cur)
                acc = acc | jnp.any(
                    interior(nxt) != interior(cur), axis=(0, 2))
                return (nxt, acc)
            cur, tile_changed = lax.fori_loop(
                0, g, gen, (stripe, jnp.zeros((K,), dtype=jnp.bool_)))
            inner = interior(cur)
            # in-place writes (the chain aliases the loop carry); padding
            # lanes rewrite tile 0 with its own correct value
            def scat(k, gg):
                blk = lax.dynamic_index_in_dim(inner, k, axis=1,
                                               keepdims=False)
                return lax.dynamic_update_slice(
                    gg, blk, (ti[k] * tr, tj[k] * tc))
            grid = lax.fori_loop(0, K, scat, grid)
            changed = (jnp.zeros((plan.ntiles,), dtype=jnp.bool_)
                       .at[idx].set(tile_changed)
                       .reshape(plan.nti, plan.ntj))
            return (grid, changed, done + g)
        return body

    def plain_chunk(n):
        # n unprobed dense generations (static-trip fori — the static
        # count is load-bearing: a traced count lowers to an XLA while
        # whose stencil body cannot alias its carry, one full grid copy
        # per generation).  The stale map would no longer be a superset
        # of what changed, so it is REPLACED by all-ones (conservative);
        # the probed final generation below restores an exact map at
        # the dispatch boundary.  The descending chunk ladder keeps the
        # per-while-iteration overhead off the per-generation cost
        def body(st):
            grid, changed, done = st
            grid = lax.fori_loop(0, n, lambda _, g: base_evolve(g, 1),
                                 grid)
            return (grid, jnp.ones_like(changed), done + n)
        return body

    def tail_probe(st):
        # one dense generation whose changed map is EXACT: consecutive
        # grids compared (see tile_changed_map)
        grid, changed, done = st
        new = base_evolve(grid, 1)
        return (new, tile_changed_map(new, grid, plan), done + 1)

    def make_phases(steps):
        phases = []
        # deep sparse rungs first (s generations per gather), then
        # depth-1 rungs to mop up the < s remainder — serve-depth-1
        # dispatches ride the depth-1 rungs directly
        depths = [plan.gens] + ([1] if plan.gens > 1 else [])
        for g in depths:
            for K in plan.capacities:
                def cond(st, K=K, g=g):
                    return (st[2] + g <= steps) & \
                        (active_count(st[1], plan.periodic) <= K)
                phases.append((cond, sparse_body(K, g)))

        def busy(st):
            # hysteresis: the dense phases are entered only when no rung
            # fits (> capacities[-1]) and exited when a probe finds the
            # board quiet enough (<= release_tiles < top rung)
            return active_count(st[1], plan.periodic) > plan.release_tiles

        # unprobed chunks, largest first; strict < leaves the final
        # generation for the probed tail
        for n in DENSE_CHUNKS:
            def chunk_cond(st, n=n):
                return (st[2] + n < steps) & busy(st)
            phases.append((chunk_cond, plain_chunk(n)))

        def tail_probe_cond(st):
            # the dispatch's final generation probes, so every evolve
            # call hands back an exact changed map (shallow serve
            # chains track activity per dispatch; deep dispatches
            # amortize probing through the super-step)
            return (st[2] < steps) & busy(st)
        phases.append((tail_probe_cond, tail_probe))
        return phases

    @partial(jax.jit, static_argnames=("steps",), donate_argnums=(0,))
    def evolve(state: SparseState, steps: int) -> SparseState:
        if steps <= 0:
            return state
        phases = make_phases(steps)

        def outer_body(st):
            for cond, body in phases:
                st = lax.while_loop(cond, body, st)
            return st

        # the step counter starts at a net-zero expression carrying the
        # per-process _CACHE_SALT (see above): the traced
        # (x*0 + salt) - salt survives into the HLO the persistent
        # cache key is computed from (pure-constant arithmetic would
        # fold eagerly during tracing and erase the salt), so this
        # program can never hit another process's serialized executable.
        # Salt 0 on unaffected jaxlibs: the key is then shared and
        # warm-cache starts come back for free.
        salt = jnp.int32(_CACHE_SALT if _cache_optout_active() else 0)
        zero = (state.changed.reshape(-1)[0].astype(jnp.int32) * 0
                + salt) - salt
        # progress each outer round is guaranteed: any activity level is
        # served by some rung or by the dense tail (release <= top rung)
        st = lax.while_loop(lambda st: st[2] < steps, outer_body,
                            (state.grid, state.changed, zero))
        return SparseState(st[0], st[1])

    return _UncachedEvolve(evolve) if _cache_optout_active() else evolve


def activity_stats(state: SparseState, plan: TilePlan) -> dict:
    """Host-side readout for gauges/describe: the *next-step* active set
    implied by the current changed map.  Small eager device ops (the
    tile map is nti×ntj bools) plus one fetch."""
    n = int(jax.device_get(active_count(state.changed, plan.periodic)))
    ntiles = plan.ntiles
    return {
        "active_tiles": n,
        "ntiles": ntiles,
        "active_fraction": n / ntiles if ntiles else 0.0,
        "mode": "sparse" if n <= plan.capacity else "dense",
        "tile": plan.tile_px,
        "capacity": plan.capacity,
    }
