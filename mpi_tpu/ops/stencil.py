"""Dense stencil step — the TPU-native equivalent of the reference's
per-cell sweep (``/root/reference/main.cpp:79-103`` gather flavor,
``/root/reference/main_serial.cpp:45-71`` scatter flavor).

Design (TPU-first, not a translation):

* The neighbor count is a **separable box sum**: a (2r+1)-tap window sum
  over rows, then over columns, minus the center — ``2·(2r+1)`` shifted
  uint8 adds instead of ``(2r+1)²`` per-cell gathers.  Everything is
  elementwise on static shapes, so XLA fuses the whole step (pad → sums →
  rule select) into one VPU loop over (8, 128) registers; no scalar code,
  no gathers, no MXU needed.
* The rule is applied as OR-of-interval comparisons (``Rule.*_intervals``)
  — comparisons and selects, which fuse into the same loop.
* Multi-step evolution is ``lax.scan`` under ``jit`` with donated carry:
  the double-buffer pointer swap of the reference (``main.cpp:294-296``)
  becomes XLA buffer donation — same memory behavior, no aliasing bugs
  possible (SURVEY.md §5.2).

Grids are uint8 0/1 arrays.  uint8 is the natural VPU lane type here; the
max neighbor count for r≤5 (120) fits comfortably.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_tpu.models.rules import Rule, LIFE

Boundary = str  # "periodic" | "dead"


def pad_grid(grid: jax.Array, radius: int, boundary: Boundary) -> jax.Array:
    """Pad a (H, W) grid with a radius-wide ring: toroidal wrap for
    "periodic" (serial oracle semantics, ``main_serial.cpp:57``) or zeros
    for "dead" (MPI program's non-periodic edges, ``main.cpp:243``)."""
    if boundary == "periodic":
        return jnp.pad(grid, radius, mode="wrap")
    if boundary == "dead":
        return jnp.pad(grid, radius, mode="constant", constant_values=0)
    raise ValueError(f"unknown boundary {boundary!r}")


def counts_from_padded(padded: jax.Array, radius: int) -> jax.Array:
    """Neighbor counts (center excluded) for the interior of a pre-padded
    array.  padded: (H+2r, W+2r) uint8 → (H, W) uint8.

    Separable: rowsum over the vertical window first (keeps full padded
    width so the column pass sees horizontally-shifted values), then the
    horizontal window, then subtract the center cell.
    """
    r = radius
    H = padded.shape[0] - 2 * r
    W = padded.shape[1] - 2 * r
    win = 2 * r + 1
    rowsum = padded[0:H, :]
    for k in range(1, win):
        rowsum = rowsum + padded[k : k + H, :]
    counts = rowsum[:, 0:W]
    for k in range(1, win):
        counts = counts + rowsum[:, k : k + W]
    return counts - padded[r : r + H, r : r + W]


def neighbor_counts(grid: jax.Array, radius: int, boundary: Boundary) -> jax.Array:
    return counts_from_padded(pad_grid(grid, radius, boundary), radius)


def _in_any_interval(counts: jax.Array, intervals: Tuple[Tuple[int, int], ...]) -> jax.Array:
    if not intervals:
        return jnp.zeros(counts.shape, dtype=jnp.bool_)
    acc = None
    for lo, hi in intervals:
        if lo == hi:
            t = counts == jnp.uint8(lo)
        else:
            t = (counts >= jnp.uint8(lo)) & (counts <= jnp.uint8(hi))
        acc = t if acc is None else acc | t
    return acc


def apply_rule(alive: jax.Array, counts: jax.Array, rule: Rule) -> jax.Array:
    """Next state from current state + neighbor counts: B/S select."""
    born = _in_any_interval(counts, rule.birth_intervals)
    keep = _in_any_interval(counts, rule.survive_intervals)
    return jnp.where(alive.astype(jnp.bool_), keep, born).astype(jnp.uint8)


def step(grid: jax.Array, rule: Rule = LIFE, boundary: Boundary = "periodic") -> jax.Array:
    """One generation on a single device."""
    counts = neighbor_counts(grid, rule.radius, boundary)
    return apply_rule(grid, counts, rule)


@functools.partial(jax.jit, static_argnames=("rule", "boundary", "steps"), donate_argnums=0)
def _evolve(grid: jax.Array, rule: Rule, boundary: Boundary, steps: int) -> jax.Array:
    def body(g, _):
        return step(g, rule, boundary), None

    out, _ = lax.scan(body, grid, None, length=steps)
    return out


def make_stepper(rule: Rule = LIFE, boundary: Boundary = "periodic"):
    """Returns evolve(grid, steps) — jitted scan with donated carry."""

    def evolve(grid: jax.Array, steps: int) -> jax.Array:
        return _evolve(grid, rule, boundary, steps)

    return evolve
