"""Pallas kernel over the bitpacked representation — the fastest engine.

The XLA bitpacked path (``ops/bitlife.py``) materializes the rolled
up/down rows and the six shifted word arrays in HBM each step, which
makes it bandwidth-bound at large grids.  This kernel streams row blocks
of the packed (H, W/32) uint32 grid through VMEM exactly as
``ops/pallas_stencil.py`` does for dense uint8 — same double-buffered
halo-slab DMA scaffold — but the per-block compute is the SWAR carry-save
adder + compiled rule of ``bitlife.column_sums``/``bit_next``: all word
shifts and lane rotations happen in registers, so HBM sees one packed
read and one packed write per block (0.25 bytes per cell per step).

Periodic rows come from the modulo-wrapped slab DMAs; periodic columns
from ``pltpu.roll`` lane rotation (the cross-word carry bits ride along
inside the rotated words).  Dead boundary: edge slabs zeroed, rotated
edge words masked with a lane iota.

Temporal blocking (``gens`` > 1): the DMA-alignment halo (8 rows, or 16
for gens > 8) is deeper than the rule's radius-1 needs, so after one HBM
round-trip the slab can be stepped up to 16 generations in VMEM — each
generation shrinks the valid row window by one from each side, and after
``gens`` generations the middle BM rows are exactly ``gens`` steps
ahead.  Neighboring blocks
recompute each other's halo rows redundantly from the same input (the
classic overlapped/trapezoidal stencil tiling), so blocks stay
independent.  HBM traffic drops by ``gens``× for ~(2·gens/BM) extra
compute; on chips where the kernel is bandwidth- or latency-bound this
is the difference between ~30% and ~100% VPU occupancy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.ops.bitlife import WORD, bit_next, column_sums, packable


def _pick_blocks(H: int, NW: int, gens: int = 1) -> tuple[int, int] | None:
    """(BM, CM): DMA-slab rows and compute-tile rows.

    BM bounds the double-buffered HBM↔VMEM slabs — bigger is better (DMA
    amortization, and with temporal blocking the whole slab is reused for
    ``gens`` generations).  CM bounds the live compute temporaries: each
    generation is evaluated over sub-tiles of CM rows.

    Narrow rows (NW ≤ 512) model the working set as ~13.5 live (rows,
    NW) u32 arrays for single-tile windows and ~16 for sub-tiled ones
    (the saved-row carry and concat add live copies) — calibrated
    against Mosaic's scoped-vmem accounting ((BM=128, NW=2048, gens=4)
    single-tile reports 16.29M over the 16M limit; (BM=512, CM=256,
    NW=512, gens=8) compiles and is kept).  They prefer the largest CM
    first — big compute tiles both run fastest (measured: (512, 256) at
    NW=512 beats every (·, ≤64) shape) and bound the unrolled sub-tile
    count — then the largest slab BM that still fits.

    Wide rows (NW > 512) use an empirical whitelist, not a model: the
    round-1 Mosaic compile-time pathology is gone (the full (BM, CM) ×
    gens map at NW=2048 compiles in under ~40 s per config: 0.9-2 s at
    gens=1, 6.5-40 s at gens=8 — `perf/compile_wall.json`, 2026-07-30)
    and the hard boundary is VMEM
    OOM, which is NOT linear in the tile rows: (512, 64) at gens=1 OOMs
    (Mosaic reports 16.25M) while (128, 128) at gens=8 — more modeled
    rows — compiles.  No single per-row coefficient separates the two,
    so the preference list carries only measured-OK shapes, every
    512-row slab is measured OOM at NW=2048 (hence the hard bm ≤ 256
    guard), and the coefficient-11 screen exists only to scale the
    whitelisted shapes' budget with ``gens`` (calibrated at gens ≥ 4:
    (512, 64) est. 16.0 MB → OOM, (128, 128) est. 15.5 MB → OK).
    Measured preference at NW=2048: (128, 128) at deep temporal
    blocking (1940 vs 1850 Gcell/s for the best single-tile slab at
    gens=8), (256, 64) shallow (1211 vs 1170 at gens=1)."""
    sizes = (512, 256, 128, 64, 32, 16, 8)
    halo = _halo_rows(gens)

    def bm_ok(bm):
        # wrapped halo-slab DMA starts must stay halo-aligned
        return H % bm == 0 and (halo <= 8 or (H % halo == 0 and bm % halo == 0))

    if NW > 512:
        # The whitelist below was measured in the halo-8 regime
        # (gens ∈ {1, 8}); calibration shows the screen under-predicts
        # Mosaic's accounting by ~0.25 MB, so in the unmeasured halo-16
        # regime (gens > 8) demand double that (0.5 MB) as headroom
        # rather than admit a shape on a 32 KB margin
        limit = int(15.25 * (1 << 20)) - (512 * 1024 if halo > 8 else 0)
        # ((256, 64) is omitted from the deep-blocking list: any shape
        # for which it passes bm_ok and the screen is always preceded by
        # a passing (128, 64) — same temps, smaller slab — so it could
        # never be selected there)
        prefs = (
            ((128, 128), (128, 64)) if gens >= 4
            else ((256, 64), (128, 128), (128, 64))
        )
        for bm, cm in prefs:
            # bm > 256 is measured VMEM OOM at wide NW for every CM and
            # gens (perf/compile_wall.json) — keep the rail even if the
            # prefs list is extended, because the coefficient screen
            # below cannot predict those OOMs (see docstring)
            if bm > 256 or not bm_ok(bm):
                continue
            need = (2 * (bm + 2 * halo) * NW * 4
                    + 11 * (cm + 2 * gens + 2) * NW * 4)
            if need <= limit:
                return bm, cm
        # single-tile fallback for shapes the preferred sub-tiles can't
        # serve (e.g. H not a multiple of 128)
        limit = int(15.75 * (1 << 20))
        for bm in sizes:
            if not bm_ok(bm):
                continue
            dbuf = 2 * (bm + 2 * halo) * NW * 4
            temps = 13.5 * (bm + 2 * gens + 2) * NW * 4
            if dbuf + temps <= limit:
                # CM ≥ BM + 2·(gens−1): every window single-tile
                return bm, bm + 2 * halo
        return None
    limit = int(15.25 * (1 << 20))
    for cm in sizes:
        room = limit - 16 * (cm + 2 * gens + 2) * NW * 4
        if room <= 0:
            continue
        for bm in sizes:
            if bm < cm or not bm_ok(bm):
                continue
            if 2 * (bm + 2 * halo) * NW * 4 <= room:
                return bm, cm
    return None


def _pick_block_rows(H: int, NW: int, gens: int = 1) -> int | None:
    picked = _pick_blocks(H, NW, gens)
    return picked[0] if picked else None


def blocks_ok(H: int, NW: int, bm: int, cm: int, gens: int = 1) -> bool:
    """Would an explicit (BM, CM) override satisfy the same alignment and
    VMEM screens :func:`_pick_blocks` applies to its own candidates?  The
    autotuner's candidate generator uses this to enumerate a rectangular
    block grid without proposing shapes that are known-OOM or misaligned
    (a bad override only costs a compile-and-fallback, never a wrong
    answer — but proposing it wastes a tuner measurement)."""
    halo = _halo_rows(gens)
    if H % bm or cm > bm + 2 * halo:
        return False
    if halo > 8 and (H % halo or bm % halo):
        return False
    if NW > 512:
        if bm > 256:  # measured VMEM OOM at wide NW, every CM and gens
            return False
        limit = int(15.25 * (1 << 20)) - (512 * 1024 if halo > 8 else 0)
        need = (2 * (bm + 2 * halo) * NW * 4
                + 11 * (cm + 2 * gens + 2) * NW * 4)
        return need <= limit
    limit = int(15.25 * (1 << 20))
    room = limit - 16 * (cm + 2 * gens + 2) * NW * 4
    return room > 0 and 2 * (bm + 2 * halo) * NW * 4 <= room


def supports(shape, rule: Rule, gens: int = 1) -> bool:
    """(H, W) cell-space shapes this kernel handles at the given temporal
    blocking depth (deeper gens need more VMEM, so query with the gens you
    will run)."""
    H, W = shape
    return (
        packable(shape, rule)
        and (W // WORD) % 128 == 0  # packed width must stay lane-aligned
        and H >= 8
        and _pick_block_rows(H, W // WORD, gens) is not None
    )


def _halo_rows(gens: int) -> int:
    # DMA row slices must be 8-sublane aligned; the halo must also cover
    # one consumed row per temporally-blocked generation
    return 8 if gens <= 8 else 16


def _out_struct(packed, H: int, NW: int):
    """Output aval for the kernel: when tracing inside ``shard_map`` the
    result varies over the same mesh axes as the input, and shard_map's
    vma checking requires that to be declared on the out_shape."""
    try:
        vma = jax.typeof(packed).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        return jax.ShapeDtypeStruct((H, NW), jnp.uint32, vma=vma)
    return jax.ShapeDtypeStruct((H, NW), jnp.uint32)


def _make_kernel(
    rule: Rule, boundary: str, H: int, NW: int, BM: int, CM: int, gens: int = 1
):
    periodic = boundary == "periodic"
    nblocks = H // BM
    HALO = _halo_rows(gens)
    if not 1 <= gens <= 16:
        raise ValueError(f"gens must be in 1..16, got {gens}")
    if HALO > 8 and (H % HALO or BM % HALO):
        raise ValueError(
            f"gens={gens} needs H and BM to be multiples of {HALO} "
            f"(wrapped halo-slab DMAs), got H={H}, BM={BM}"
        )

    def _block_dmas(in_hbm, dbuf, sems, blk, slot):
        base = blk * BM
        top = pl.multiple_of(lax.rem(base - HALO + H, H), HALO)
        bot = pl.multiple_of(lax.rem(base + BM, H), HALO)
        return (
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, HALO), :],
                dbuf.at[slot, pl.ds(0, HALO), :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(base, BM), :],
                dbuf.at[slot, pl.ds(HALO, BM), :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, HALO), :],
                dbuf.at[slot, pl.ds(HALO + BM, HALO), :],
                sems.at[slot, 2],
            ),
        )

    def kernel(in_hbm, out_ref, dbuf, sems):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        next_slot = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, 0, 0):
                d.start()

        @pl.when(i + 1 < nblocks)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, i + 1, next_slot):
                d.start()

        for d in _block_dmas(in_hbm, dbuf, sems, i, slot):
            d.wait()

        scratch = dbuf.at[slot]

        if not periodic:
            # Zero the whole edge slabs: rows beyond the grid are dead.
            # (This only establishes the gen-0 state — during multi-gen
            # loops the rows adjacent to live grid rows can be "born" and
            # must be re-killed after every generation; see below.)
            @pl.when(i == 0)
            def _():
                scratch[0:HALO, :] = jnp.zeros((HALO, NW), dtype=jnp.uint32)

            @pl.when(i == nblocks - 1)
            def _():
                scratch[HALO + BM : HALO + BM + HALO, :] = jnp.zeros(
                    (HALO, NW), dtype=jnp.uint32
                )

        def sub_gen(up, mid, down, rows):
            """Next state of mid given its row neighbors."""
            lane = (
                None if periodic
                else lax.broadcasted_iota(jnp.int32, (rows, NW), dimension=1)
            )

            def prev_word(x):
                rolled = pltpu.roll(x, 1, axis=1)
                if periodic:
                    return rolled
                return jnp.where(lane == 0, jnp.uint32(0), rolled)

            def next_word(x):
                rolled = pltpu.roll(x, NW - 1, axis=1)
                if periodic:
                    return rolled
                return jnp.where(lane == NW - 1, jnp.uint32(0), rolled)

            # vertical sums once; the left/right columns reuse the rolled
            # sums (4 lane rotations instead of 6, no re-summing of rows)
            f0, f1, c0, c1 = column_sums(up, mid, down)
            return bit_next(
                f0, f1, c0, c1,
                prev_word(f0), prev_word(f1),
                next_word(f0), next_word(f1),
                mid, rule,
            )

        # Each generation consumes one valid row from each side of the slab;
        # only rows that later generations (or the output block) still need
        # are recomputed.  Within a generation the row window is evaluated
        # in CM-row sub-tiles to bound live VMEM temporaries; the update is
        # in place, so each sub-tile's top neighbor row (overwritten by the
        # previous sub-tile) is carried in ``saved``.  All bounds are Python
        # ints — fully static.
        lo, hi = 0, BM + 2 * HALO
        for g in range(gens):
            rem = gens - 1 - g  # generations still to run after this one
            glo = max(lo + 1, HALO - rem)
            ghi = min(hi - 1, HALO + BM + rem)
            saved = None
            a = glo
            while a < ghi:
                b = min(a + CM, ghi)
                rows = b - a
                top = scratch[a - 1 : a, :] if saved is None else saved
                if rows > 1:
                    up = jnp.concatenate([top, scratch[a : b - 1, :]], axis=0)
                else:
                    up = top
                mid = scratch[a:b, :]
                down = scratch[a + 1 : b + 1, :]
                if rem:
                    saved = scratch[b - 1 : b, :]  # old value, read before write
                new = sub_gen(up, mid, down, rows)
                if rem:
                    scratch[a:b, :] = new
                else:
                    out_ref[a - HALO : b - HALO, :] = new
                a = b
            if rem:
                if not periodic:
                    # Rows beyond the grid edge are not real cells: live grid
                    # neighbors would "give birth" into them — re-kill them
                    # after every in-VMEM generation at the edge blocks.
                    if glo < HALO:
                        @pl.when(i == 0)
                        def _():
                            scratch[glo:HALO, :] = jnp.zeros(
                                (HALO - glo, NW), dtype=jnp.uint32
                            )

                    if ghi > HALO + BM:
                        @pl.when(i == nblocks - 1)
                        def _():
                            scratch[HALO + BM : ghi, :] = jnp.zeros(
                                (ghi - HALO - BM, NW), dtype=jnp.uint32
                            )
                lo, hi = glo, ghi

    return kernel


def pallas_bit_step(
    packed: jax.Array,
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
    gens: int = 1,
    blocks: tuple[int, int] | None = None,
) -> jax.Array:
    """``gens`` generations (default one) on a packed (H, W/32) uint32 grid
    via the fused SWAR kernel, in a single HBM round-trip.  Requires
    ``supports((H, W), rule)`` and ``gens <= 16``.  ``blocks`` overrides the
    auto-picked (BM, CM) DMA-slab/compute-tile rows (tests)."""
    H, NW = packed.shape
    picked = blocks or _pick_blocks(H, NW, gens)
    if rule.radius != 1 or picked is None:
        raise ValueError(f"pallas_bit_step cannot handle packed shape {packed.shape}")
    if gens > 1 and 0 in rule.birth:
        # dead-boundary halo rows must stay dead across in-VMEM generations
        raise ValueError("gens > 1 requires a rule without birth-on-0")
    BM, CM = picked
    kernel = _make_kernel(rule, boundary, H, NW, BM, CM, gens)
    return pl.pallas_call(
        kernel,
        grid=(H // BM,),
        out_shape=_out_struct(packed, H, NW),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((BM, NW), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, BM + 2 * _halo_rows(gens), NW), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )(packed)


def make_pallas_bit_stepper(
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
    gens: int = 1,
    blocks: tuple[int, int] | None = None,
):
    """evolve(packed, steps) on packed uint32 grids, running ``gens``
    generations per kernel pass (temporal blocking); jitted with donated
    input, so ``evolve.lower`` works for ahead-of-time compilation.
    ``blocks`` overrides the auto-picked (BM, CM) per pass — the
    autotuner's block-shape knob (a bad override fails at compile and
    takes the engine's XLA fallback, never a wrong answer)."""
    from mpi_tpu.utils.segmenting import segmented_evolve

    def make_local(k):
        def local(p):
            return pallas_bit_step(p, rule, boundary, interpret=interpret,
                                   gens=k, blocks=blocks)

        return local

    return segmented_evolve(make_local, gens)
