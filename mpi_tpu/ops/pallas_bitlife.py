"""Pallas kernel over the bitpacked representation — the fastest engine.

The XLA bitpacked path (``ops/bitlife.py``) materializes the rolled
up/down rows and the six shifted word arrays in HBM each step, which
makes it bandwidth-bound at large grids.  This kernel streams row blocks
of the packed (H, W/32) uint32 grid through VMEM exactly as
``ops/pallas_stencil.py`` does for dense uint8 — same double-buffered
halo-slab DMA scaffold — but the per-block compute is the SWAR carry-save
adder + compiled rule of ``bitlife.column_sums``/``bit_next``: all word
shifts and lane rotations happen in registers, so HBM sees one packed
read and one packed write per block (0.25 bytes per cell per step).

Periodic rows come from the modulo-wrapped slab DMAs; periodic columns
from ``pltpu.roll`` lane rotation (the cross-word carry bits ride along
inside the rotated words).  Dead boundary: edge slabs zeroed, rotated
edge words masked with a lane iota.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_tpu.models.rules import Rule, LIFE
from mpi_tpu.ops.bitlife import WORD, bit_next, column_sums, packable


def _pick_block_rows(H: int, NW: int) -> int | None:
    # 2 MiB per double-buffer slot: the shared-sums compute keeps few
    # enough (BM, NW) u32 temporaries live that 2 MiB slots now fit in
    # the 16 MiB VMEM (measured: +4% at 65536^2 over 1 MiB; 4 MiB
    # overflows).
    budget = 2 << 20
    for bm in (512, 256, 128, 64, 32, 16, 8):
        if H % bm == 0 and (bm + 16) * NW * 4 <= budget:
            return bm
    return None


def supports(shape, rule: Rule) -> bool:
    """(H, W) cell-space shapes this kernel handles."""
    H, W = shape
    return (
        packable(shape, rule)
        and (W // WORD) % 128 == 0  # packed width must stay lane-aligned
        and H >= 8
        and _pick_block_rows(H, W // WORD) is not None
    )


def _make_kernel(rule: Rule, boundary: str, H: int, NW: int, BM: int):
    periodic = boundary == "periodic"
    nblocks = H // BM
    HALO = 8  # DMA row slices must be 8-sublane aligned; radius is 1

    def _block_dmas(in_hbm, dbuf, sems, blk, slot):
        base = blk * BM
        top = pl.multiple_of(lax.rem(base - HALO + H, H), HALO)
        bot = pl.multiple_of(lax.rem(base + BM, H), HALO)
        return (
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(top, HALO), :],
                dbuf.at[slot, pl.ds(0, HALO), :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(base, BM), :],
                dbuf.at[slot, pl.ds(HALO, BM), :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                in_hbm.at[pl.ds(bot, HALO), :],
                dbuf.at[slot, pl.ds(HALO + BM, HALO), :],
                sems.at[slot, 2],
            ),
        )

    def kernel(in_hbm, out_ref, dbuf, sems):
        i = pl.program_id(0)
        slot = lax.rem(i, 2)
        next_slot = lax.rem(i + 1, 2)

        @pl.when(i == 0)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, 0, 0):
                d.start()

        @pl.when(i + 1 < nblocks)
        def _():
            for d in _block_dmas(in_hbm, dbuf, sems, i + 1, next_slot):
                d.start()

        for d in _block_dmas(in_hbm, dbuf, sems, i, slot):
            d.wait()

        scratch = dbuf.at[slot]

        if not periodic:
            @pl.when(i == 0)
            def _():
                scratch[HALO - 1 : HALO, :] = jnp.zeros((1, NW), dtype=jnp.uint32)

            @pl.when(i == nblocks - 1)
            def _():
                scratch[HALO + BM : HALO + BM + 1, :] = jnp.zeros((1, NW), dtype=jnp.uint32)

        lane = (
            None if periodic
            else lax.broadcasted_iota(jnp.int32, (BM, NW), dimension=1)
        )

        up = scratch[HALO - 1 : HALO - 1 + BM, :]
        mid = scratch[HALO : HALO + BM, :]
        down = scratch[HALO + 1 : HALO + 1 + BM, :]

        def prev_word(x):
            rolled = pltpu.roll(x, 1, axis=1)
            if periodic:
                return rolled
            return jnp.where(lane == 0, jnp.uint32(0), rolled)

        def next_word(x):
            rolled = pltpu.roll(x, NW - 1, axis=1)
            if periodic:
                return rolled
            return jnp.where(lane == NW - 1, jnp.uint32(0), rolled)

        # vertical sums once; the left/right columns reuse the rolled sums
        # (4 lane rotations instead of 6, no re-summing of shifted rows)
        f0, f1, c0, c1 = column_sums(up, mid, down)
        out_ref[:] = bit_next(
            f0, f1, c0, c1,
            prev_word(f0), prev_word(f1),
            next_word(f0), next_word(f1),
            mid, rule,
        )

    return kernel


def pallas_bit_step(
    packed: jax.Array,
    rule: Rule = LIFE,
    boundary: str = "periodic",
    interpret: bool = False,
) -> jax.Array:
    """One generation on a packed (H, W/32) uint32 grid via the fused
    SWAR kernel.  Requires ``supports((H, W), rule)``."""
    H, NW = packed.shape
    BM = _pick_block_rows(H, NW)
    if rule.radius != 1 or BM is None:
        raise ValueError(f"pallas_bit_step cannot handle packed shape {packed.shape}")
    kernel = _make_kernel(rule, boundary, H, NW, BM)
    return pl.pallas_call(
        kernel,
        grid=(H // BM,),
        out_shape=jax.ShapeDtypeStruct((H, NW), jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((BM, NW), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, BM + 16, NW), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )(packed)


@functools.partial(
    jax.jit, static_argnames=("rule", "boundary", "steps", "interpret"), donate_argnums=0
)
def _evolve_bits_pallas(packed, rule, boundary, steps, interpret):
    def body(p, _):
        return pallas_bit_step(p, rule, boundary, interpret=interpret), None

    out, _ = lax.scan(body, packed, None, length=steps)
    return out


def make_pallas_bit_stepper(
    rule: Rule = LIFE, boundary: str = "periodic", interpret: bool = False
):
    """evolve(packed, steps) on packed uint32 grids."""

    def evolve(packed: jax.Array, steps: int) -> jax.Array:
        return _evolve_bits_pallas(packed, rule, boundary, steps, interpret)

    return evolve
