"""Multi-window burn-rate SLO engine (ISSUE 15 tentpole, part b).

Declarative objectives — availability (non-5xx ratio), latency (p-high
under a threshold), freshness (age of the last committed dispatch) —
evaluated on every telemetry sampler tick as MULTI-WINDOW burn rates:

    burn(window) = observed bad-event ratio / allowed bad-event ratio

with a FAST (5 m) and a SLOW (1 h) window that must BOTH exceed a
threshold before the state worsens (the SRE-workbook discipline: the
slow window proves the burn is sustained, the fast window proves it is
still happening, so a transient spike and a long-recovered incident
both stay quiet).  Default thresholds: warning at burn 6, critical at
14.4 — at 14.4 a 99.9% budget is gone in ~2 days.  Freshness is a
staleness measure, not an error-budget ratio, so it gets absolute-style
thresholds instead (warning at 75% of ``max_age_s``, critical at 100%).

State transitions are asymmetric (flap damping): worsening applies
immediately — alert latency matters — while improving requires
``damp_evals`` consecutive calmer evaluations, so an objective
oscillating around a threshold cannot ring the transition counter on
every tick.  Each transition increments
``mpi_tpu_slo_transitions_total{slo,to}`` and emits an
``slo_transition`` trace event; current states render as
``mpi_tpu_slo_state{slo}`` (0 ok / 1 warning / 2 critical).

Everything here is armed-only (``Obs.arm_telemetry``): unarmed builds
register none of these families and the scrape stays byte-identical.
SLO state is ALERTING, not readiness — it never flips ``/healthz``'s
``ok`` (see README: a burning availability SLO with a healthy fallback
must not get the process restarted or ejected from a load balancer).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from mpi_tpu.config import ConfigError
from mpi_tpu.obs.timeseries import TelemetryRecorder

STATES = ("ok", "warning", "critical")
_RANK = {"ok": 0, "warning": 1, "critical": 2}

# fast/slow burn windows (seconds) — the 5m/1h pair from the digests'
# window vocabulary
FAST_S, SLOW_S = 300.0, 3600.0

# ratio-type objectives: budget multiples (14.4 burns a 30-day budget in
# ~2 days); freshness: fractions of max_age_s
_DEFAULT_BURN = {
    "availability": (6.0, 14.4),
    "latency": (6.0, 14.4),
    "freshness": (0.75, 1.0),
}

LATENCY_PATHS = ("dispatch", "http", "ticket_wait")


def default_objectives() -> List[dict]:
    """The built-in objectives used when ``--slo-file`` is not given."""
    return [
        {"name": "availability", "type": "availability", "target": 0.999},
        {"name": "dispatch-p99", "type": "latency", "path": "dispatch",
         "threshold_s": 1.0, "target": 0.99},
        {"name": "freshness", "type": "freshness", "max_age_s": 600.0},
    ]


def _normalize(obj: dict, seen: set) -> dict:
    if not isinstance(obj, dict):
        raise ConfigError(f"objective must be an object, got {obj!r}")
    kind = obj.get("type")
    if kind not in _DEFAULT_BURN:
        raise ConfigError(
            f"objective type must be one of {sorted(_DEFAULT_BURN)}, "
            f"got {kind!r}")
    name = obj.get("name") or kind
    if not isinstance(name, str) or not name:
        raise ConfigError(f"objective name must be a string, got {name!r}")
    if name in seen:
        raise ConfigError(f"duplicate objective name {name!r}")
    seen.add(name)
    out = {"name": name, "type": kind}
    if kind in ("availability", "latency"):
        target = obj.get("target")
        if not isinstance(target, (int, float)) or not 0.0 < target < 1.0:
            raise ConfigError(
                f"{name}: target must be a ratio in (0,1), got {target!r}")
        out["target"] = float(target)
    if kind == "latency":
        path = obj.get("path", "dispatch")
        if path not in LATENCY_PATHS:
            raise ConfigError(
                f"{name}: path must be one of {LATENCY_PATHS}, got {path!r}")
        thr = obj.get("threshold_s")
        if not isinstance(thr, (int, float)) or thr <= 0:
            raise ConfigError(
                f"{name}: threshold_s must be > 0, got {thr!r}")
        out["path"] = path
        out["threshold_s"] = float(thr)
    if kind == "freshness":
        age = obj.get("max_age_s")
        if not isinstance(age, (int, float)) or age <= 0:
            raise ConfigError(
                f"{name}: max_age_s must be > 0, got {age!r}")
        out["max_age_s"] = float(age)
    warn_d, crit_d = _DEFAULT_BURN[kind]
    warn = obj.get("warn_burn", warn_d)
    crit = obj.get("crit_burn", crit_d)
    for k, v in (("warn_burn", warn), ("crit_burn", crit)):
        if not isinstance(v, (int, float)) or v <= 0:
            raise ConfigError(f"{name}: {k} must be > 0, got {v!r}")
    if warn > crit:
        raise ConfigError(
            f"{name}: warn_burn {warn} must not exceed crit_burn {crit}")
    out["warn_burn"], out["crit_burn"] = float(warn), float(crit)
    unknown = set(obj) - set(out) - {"target", "path", "threshold_s",
                                     "max_age_s", "warn_burn", "crit_burn",
                                     "name", "type"}
    if unknown:
        raise ConfigError(f"{name}: unknown keys {sorted(unknown)}")
    return out


def normalize_objectives(raw) -> Tuple[List[dict], dict]:
    """Validate an ``--slo-file`` payload: either a bare list of
    objectives or ``{"objectives": [...], "damp_evals": N}``.  Returns
    ``(objectives, options)``; raises :class:`ConfigError` with the
    offending field named."""
    options: dict = {}
    if isinstance(raw, dict):
        if "objectives" not in raw:
            raise ConfigError('slo file object needs an "objectives" list')
        damp = raw.get("damp_evals")
        if damp is not None:
            if not isinstance(damp, int) or damp < 1:
                raise ConfigError(
                    f"damp_evals must be an int >= 1, got {damp!r}")
            options["damp_evals"] = damp
        unknown = set(raw) - {"objectives", "damp_evals"}
        if unknown:
            raise ConfigError(f"unknown top-level keys {sorted(unknown)}")
        raw = raw["objectives"]
    if not isinstance(raw, list) or not raw:
        raise ConfigError("slo file needs a non-empty objectives list")
    seen: set = set()
    return [_normalize(o, seen) for o in raw], options


def load_slo_file(path: str) -> Tuple[List[dict], dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except OSError as e:
        raise ConfigError(f"cannot read slo file {path!r}: {e}") from e
    except ValueError as e:
        raise ConfigError(f"slo file {path!r} is not JSON: {e}") from e
    return normalize_objectives(raw)


class SloEngine:
    """Burn-rate evaluation + the flap-damped state machine.

    ``evaluate`` runs on the telemetry sampler's cadence (wired as
    ``TelemetryRecorder.after_sample``); everything it needs — window
    deltas, digests, dispatch age — is read from the recorder and the
    manager, never shadow-counted.
    """

    def __init__(self, objectives: List[dict],
                 telemetry: TelemetryRecorder,
                 manager=None, obs=None, damp_evals: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        # normalization is idempotent — callers may pass raw objective
        # dicts (defaults, tests) or already-validated slo-file output
        seen: set = set()
        objectives = [_normalize(o, seen) for o in objectives]
        self.objectives = objectives
        self._telemetry = telemetry
        self._manager = manager
        self._obs = obs
        self.damp_evals = max(1, int(damp_evals))
        self._clock = clock
        self._lock = threading.Lock()
        self._state: Dict[str, str] = {o["name"]: "ok" for o in objectives}
        # name -> (candidate calmer state, consecutive evals seen at it)
        self._streak: Dict[str, Tuple[str, int]] = {}
        self._transitions: Dict[Tuple[str, str], int] = {}
        self._burn: Dict[str, dict] = {o["name"]: {"fast": 0.0, "slow": 0.0}
                                       for o in objectives}
        self._detail: Dict[str, dict] = {o["name"]: {} for o in objectives}
        self._evals = 0
        # post-evaluate listeners, called OUTSIDE the lock with the
        # engine's worst state after every evaluation — the seam the
        # admission load-shedder hangs off; a raising listener is
        # swallowed (the sampler must never die because an actuator
        # hiccuped)
        self._listeners: List[Callable[[str], None]] = []

    # -- burn computation --------------------------------------------------

    def _burn_availability(self, obj: dict, now: float):
        tel = self._telemetry
        budget = 1.0 - obj["target"]
        burns, detail = {}, {}
        for wname, ws in (("fast", FAST_S), ("slow", SLOW_S)):
            total = tel.window_delta("http_requests", ws, now)
            bad = tel.window_delta("http_5xx", ws, now)
            ratio = (bad / total) if total > 0 else 0.0
            burns[wname] = ratio / budget
            detail[wname] = {"requests": total, "bad": bad,
                             "ratio": round(ratio, 6)}
        return burns["fast"], burns["slow"], detail

    def _burn_latency(self, obj: dict, now: float):
        dig = self._telemetry.digests[obj["path"]]
        budget = 1.0 - obj["target"]
        burns, detail = {}, {}
        for wname, ws in (("fast", FAST_S), ("slow", SLOW_S)):
            frac = dig.fraction_above(obj["threshold_s"], ws, now)
            burns[wname] = frac / budget
            detail[wname] = {"count": dig.count(ws, now),
                             "over_threshold": round(frac, 6)}
        return burns["fast"], burns["slow"], detail

    def _burn_freshness(self, obj: dict, now: float):
        mgr = self._manager
        age = mgr.last_dispatch_age_s() if mgr is not None else None
        # never-dispatched is "no data", not "stale": a process that has
        # served nothing yet has no freshness to lose
        burn = 0.0 if age is None else age / obj["max_age_s"]
        detail = {"age_s": None if age is None else round(age, 3),
                  "max_age_s": obj["max_age_s"]}
        return burn, burn, detail

    _BURN_FNS = {"availability": _burn_availability,
                 "latency": _burn_latency,
                 "freshness": _burn_freshness}

    # -- the state machine -------------------------------------------------

    @staticmethod
    def _classify(obj: dict, fast: float, slow: float) -> str:
        # both windows must agree before the state worsens
        if fast >= obj["crit_burn"] and slow >= obj["crit_burn"]:
            return "critical"
        if fast >= obj["warn_burn"] and slow >= obj["warn_burn"]:
            return "warning"
        return "ok"

    def evaluate(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        for obj in self.objectives:
            name = obj["name"]
            fast, slow, detail = self._BURN_FNS[obj["type"]](self, obj, now)
            target = self._classify(obj, fast, slow)
            with self._lock:
                self._burn[name] = {"fast": fast, "slow": slow}
                self._detail[name] = detail
                cur = self._state[name]
                if _RANK[target] > _RANK[cur]:
                    # worsening: immediate — alert latency matters
                    self._transition(name, cur, target, fast, slow)
                elif _RANK[target] < _RANK[cur]:
                    # improving: hold down until damp_evals consecutive
                    # calmer evaluations agree (flap damping)
                    cand, n = self._streak.get(name, (None, 0))
                    n = n + 1 if cand == target else 1
                    if n >= self.damp_evals:
                        self._transition(name, cur, target, fast, slow)
                        self._streak.pop(name, None)
                    else:
                        self._streak[name] = (target, n)
                else:
                    self._streak.pop(name, None)
        with self._lock:
            self._evals += 1
        if self._listeners:
            worst = self.worst()
            for fn in list(self._listeners):
                try:
                    fn(worst)
                except Exception:  # noqa: BLE001 — see _listeners above
                    pass

    def _transition(self, name: str, frm: str, to: str,
                    fast: float, slow: float) -> None:
        # caller holds the lock
        self._state[name] = to
        key = (name, to)
        self._transitions[key] = self._transitions.get(key, 0) + 1
        if self._obs is not None:
            self._obs.event("slo_transition", slo=name, to=to,
                            burn_fast=round(fast, 3),
                            burn_slow=round(slow, 3), **{"from": frm})

    # -- readouts ----------------------------------------------------------

    def worst(self) -> str:
        with self._lock:
            return max(self._state.values(), key=_RANK.__getitem__,
                       default="ok")

    def add_listener(self, fn: Callable[[str], None]) -> None:
        """Subscribe to post-evaluate worst-state callbacks (the
        admission shedder's feed).  Idempotent registration is the
        caller's problem; the engine just calls everything in order."""
        self._listeners.append(fn)

    def transitions_total(self) -> int:
        with self._lock:
            return sum(self._transitions.values())

    def snapshot(self) -> dict:
        """The `/slo` payload (sans cluster block)."""
        tel = self._telemetry
        with self._lock:
            states = dict(self._state)
            burns = {n: dict(b) for n, b in self._burn.items()}
            details = {n: dict(d) for n, d in self._detail.items()}
            transitions = sorted(
                (n, to, c) for (n, to), c in self._transitions.items())
            evals = self._evals
        slos = []
        for obj in self.objectives:
            name = obj["name"]
            row = {"name": name, "type": obj["type"],
                   "state": states[name],
                   "burn": {w: round(v, 4)
                            for w, v in burns[name].items()},
                   "thresholds": {"warn": obj["warn_burn"],
                                  "crit": obj["crit_burn"]},
                   "detail": details[name]}
            for k in ("target", "path", "threshold_s", "max_age_s"):
                if k in obj:
                    row[k] = obj[k]
            slos.append(row)
        return {
            "interval_s": tel.interval_s,
            "evals": evals,
            "windows_s": {"fast": FAST_S, "slow": SLOW_S},
            "worst": max(states.values(), key=_RANK.__getitem__,
                         default="ok"),
            "slos": slos,
            "transitions_total": sum(c for _, _, c in transitions),
            "transitions": [{"slo": n, "to": to, "count": c}
                            for n, to, c in transitions],
            "windows": tel.windows_summary(),
        }

    def compact(self) -> dict:
        """The gossiped per-node SLO block: current states, the
        CUMULATIVE transition count (so the roll-up can sum snapshots
        exactly, the ledger discipline), and a light 5m window summary."""
        with self._lock:
            states = dict(self._state)
            transitions = sum(self._transitions.values())
            evals = self._evals
        windows = {}
        for path, dig in sorted(self._telemetry.digests.items()):
            s = dig.summary(FAST_S)
            windows[path] = {"count": s["count"], "p99": s["p99"]}
        return {"worst": max(states.values(), key=_RANK.__getitem__,
                             default="ok"),
                "states": states, "transitions": transitions,
                "evals": evals, "windows": windows}

    def health_block(self) -> dict:
        """`/healthz`'s ``slo`` block: worst state + the burning
        objectives.  Alerting only — the caller must NOT fold this into
        ``ok`` (alerting is not readiness)."""
        with self._lock:
            burning = sorted(n for n, s in self._state.items() if s != "ok")
            worst = max(self._state.values(), key=_RANK.__getitem__,
                        default="ok")
        return {"worst": worst, "burning": burning}

    # -- armed-only registry families --------------------------------------

    def bind_metrics(self, m) -> None:
        def _states():
            with self._lock:
                return [({"slo": n}, float(_RANK[s]))
                        for n, s in sorted(self._state.items())]

        m.gauge_fn("mpi_tpu_slo_state",
                   "SLO state per objective (0 ok, 1 warning, 2 critical)",
                   _states)

        def _transitions():
            with self._lock:
                return [({"slo": n, "to": to}, c)
                        for (n, to), c in sorted(self._transitions.items())]

        m.counter_fn("mpi_tpu_slo_transitions_total",
                     "SLO state transitions by objective and destination "
                     "state",
                     _transitions)
