"""Per-dispatch flight recorder (ISSUE 19 tentpole, part 1).

Every device dispatch the serve stack commits — solo, batched, depth-1
unit round, host fallback — leaves one bounded *flight record*: which
plan signature ran, which engine kind took it (dense / fused / sparse /
seam), how the k-generation segment schedule decomposed the request,
how many boards rode the batch, which sparse rung fired and over how
many active tiles, whether the input buffer was donated, and where the
wall time went (``setup_s`` = ensure-compiled + stacking, ``device_s``
= dispatch wall including the sync, ``block_s`` = the
``block_until_ready`` tail alone).  Records carry the request id and
distributed-trace linkage of the dispatch that produced them, so a slow
``/debug/flights`` row joins back to its trace with no guesswork.

The ring reuses the tracer's "lock-free-ish" discipline (``trace.py``):
slot indices from ``itertools.count()`` (atomic ``__next__`` in
CPython), each record one slot store of an immutable-by-convention
dict, a (mono, unix) anchor pair so wall-clock conversion happens at
export time only.  A full turn of the ring emits one ``flight_drop``
trace event — the trace stream says "history was lost here" without
per-record overhead.

Armed-only (``Obs.arm_flight`` behind ``--flight-recorder``): the
unarmed scrape text, trace JSONL, and every served payload stay
byte-identical to the pre-flight build.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from mpi_tpu.obs.tracectx import TRACE_CONTEXT
from mpi_tpu.obs.trace import REQUEST_ID

__all__ = ["FlightRecorder", "engine_kind"]


def engine_kind(engine) -> str:
    """Classify a live engine the way PERF.md talks about it: ``sparse``
    (dirty-tile plan armed), ``seam`` (periodic halo-in-pad dispatch),
    ``fused`` (Pallas k-generation kernel actually in use), else
    ``dense``.  Sparse wins ties — the rung decides what runs."""
    if getattr(engine, "sparse_plan", None) is not None:
        return "sparse"
    if (getattr(engine, "pad_bits", 0) > 0
            and getattr(engine.config, "boundary", None) == "periodic"):
        return "seam"
    if getattr(engine, "_used_pallas", False):
        return "fused"
    return "dense"


class FlightRecorder:
    """Bounded ring of per-dispatch flight records.

    ``record`` is called inside the dispatch sites' existing
    ``obs is not None`` blocks, AFTER the timings are taken — it adds
    one dict build and one slot store to the armed path and nothing to
    the unarmed one.  ``on_record`` (the anomaly detector's feed) is
    invoked outside any lock with ``(signature, device_s, trace_id)``.
    """

    def __init__(self, capacity: int = 1024, obs=None):
        if capacity < 1:
            raise ValueError("flight ring capacity must be >= 1")
        self.capacity = int(capacity)
        self._obs = obs
        self._buf: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._seq = itertools.count()
        # export-time wall-clock anchor, same scheme as Tracer
        self._anchor_mono = time.perf_counter()
        self._anchor_unix = time.time()
        self.on_record: Optional[Callable[[Optional[str], float,
                                           Optional[str]], None]] = None

    # -- recording -------------------------------------------------------

    def record(self, mode: str, *, engine=None, steps: int = 0,
               session: Optional[str] = None,
               sessions: Optional[List[str]] = None,
               batch: Optional[int] = None,
               setup_s: float = 0.0, device_s: float = 0.0,
               block_s: float = 0.0, sparse: Optional[dict] = None,
               rid: Optional[int] = None,
               links: Optional[List[str]] = None,
               request_ids: Optional[List] = None,
               window: Optional[Tuple[int, int, int, int]] = None,
               shards_touched: Optional[int] = None) -> Dict[str, Any]:
        """Record one committed dispatch.  ``engine`` is the live engine
        the dispatch ran on — signature, kind, donation, tuning, mesh
        shape, and the k-segment composition are derived here so the
        call sites stay one line.  ``sparse`` is the ``sparse_stats``
        dict the session path already computed (never recomputed — a
        donated grid may be gone by now).  ``window`` (an ``x0, y0, h,
        w`` viewport) and ``shards_touched`` attribute O(viewport)
        reads: which board slice was served and how many device shards
        it cost (ISSUE 20)."""
        steps = int(steps)
        rec: Dict[str, Any] = {
            "mode": mode,
            "steps": steps,
            "setup_s": round(setup_s, 9),
            "device_s": round(device_s, 9),
            "block_s": round(block_s, 9),
        }
        if session is not None:
            rec["session"] = session
        if sessions is not None:
            rec["sessions"] = list(sessions)
        if batch is not None:
            rec["batch"] = int(batch)
        sig = None
        if engine is not None:
            sig = getattr(engine, "sig_label", None)
            rec["signature"] = sig
            rec["engine"] = engine_kind(engine)
            rec["donated"] = bool(getattr(engine, "donates_input", False))
            rec["tuned"] = getattr(engine, "tuned_plan", None) is not None
            rec["bitpacked"] = bool(getattr(engine, "bitpacked", False))
            k = int(getattr(engine.config, "comm_every", 1) or 1)
            rec["k"] = k
            if steps:
                rec["segments"] = {"full": steps // k, "rem": steps % k}
            mi = getattr(engine, "mi", None)
            mj = getattr(engine, "mj", None)
            if mi and mj:
                rec["mesh"] = f"{mi}x{mj}"
        else:
            rec["engine"] = "host"
        if window is not None:
            x0, y0, h, w = window
            rec["window"] = {"x0": int(x0), "y0": int(y0),
                             "h": int(h), "w": int(w)}
        if shards_touched is not None:
            rec["shards"] = int(shards_touched)
        if sparse is not None:
            rec["sparse"] = {
                "active_tiles": sparse.get("active_tiles"),
                "active_fraction": sparse.get("active_fraction"),
                "rung": sparse.get("mode"),
            }
        if rid is None:
            rid = REQUEST_ID.get()
        if rid is not None:
            rec["rid"] = rid
        ctx = TRACE_CONTEXT.get()
        trace_id = None
        if ctx is not None:
            trace_id = ctx.trace_id
            rec["trace_id"] = trace_id
            rec["span_id"] = ctx.span_id
        if links:
            rec["links"] = list(links)
        if request_ids:
            rec["request_ids"] = list(request_ids)
        i = next(self._seq)
        rec["seq"] = i
        rec["t_mono"] = time.perf_counter()
        self._buf[i % self.capacity] = rec
        # one drop marker per full turn of the ring, not per overwrite:
        # the trace stream records that flight history was lost without
        # the hot path paying for an event per dispatch
        if i and i % self.capacity == 0 and self._obs is not None:
            self._obs.event("flight_drop", dropped=self.capacity, total=i)
        cb = self.on_record
        # zero-step records (viewport reads) never feed the anomaly
        # baseline — it models dispatch latency, not transfer wall
        if cb is not None and steps:
            cb(sig, device_s, trace_id)
        return rec

    # -- export ----------------------------------------------------------

    def _to_dict(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        d = dict(rec)
        t0 = d.pop("t_mono")
        d["t_unix"] = round(self._anchor_unix + (t0 - self._anchor_mono), 6)
        return d

    def snapshot(self, session: Optional[str] = None,
                 signature: Optional[str] = None,
                 slower_than: Optional[float] = None,
                 trace: Optional[str] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Filtered flight records, oldest first.  ``trace`` matches the
        record's own ``trace_id`` or any ``links`` entry (links are
        ``trace_id:span_id`` strings — prefix match, like
        ``tools/trace_view.py``)."""
        recs = [r for r in self._buf if r is not None]
        recs.sort(key=lambda r: r["seq"])
        out = []
        for r in recs:
            if session is not None and (
                    r.get("session") != session
                    and session not in (r.get("sessions") or ())):
                continue
            if signature is not None and r.get("signature") != signature:
                continue
            if slower_than is not None and r["device_s"] <= slower_than:
                continue
            if trace is not None and not (
                    r.get("trace_id") == trace
                    or any(ln.startswith(trace)
                           for ln in (r.get("links") or ()))):
                continue
            out.append(self._to_dict(r))
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def dump(self, path: str) -> int:
        """Flush the ring as JSONL (crash-dump folding)."""
        recs = self.snapshot()
        with open(path, "w", encoding="utf-8") as fh:
            for d in recs:
                fh.write(json.dumps(d, separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return len(recs)

    def stats(self) -> Dict[str, Any]:
        recorded = 0
        for r in self._buf:
            if r is not None and r["seq"] >= recorded:
                recorded = r["seq"] + 1
        return {
            "capacity": self.capacity,
            "recorded": recorded,
            "dropped": max(0, recorded - self.capacity),
        }

    # -- armed-only registry families ------------------------------------

    def bind_metrics(self, m) -> None:
        m.counter_fn(
            "mpi_tpu_flight_records_total",
            "Dispatch flight records written (present only when "
            "--flight-recorder arms the ring)",
            lambda: self.stats()["recorded"])
        m.counter_fn(
            "mpi_tpu_flight_dropped_total",
            "Flight records overwritten by ring wrap",
            lambda: self.stats()["dropped"])
