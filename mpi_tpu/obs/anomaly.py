"""Anomaly-triggered profiling (ISSUE 19 tentpole, part 3).

``POST /debug/profile`` needs an operator already watching when the
regression happens.  This module watches instead: every committed
dispatch feeds a per-plan-signature :class:`~mpi_tpu.obs.timeseries.
WindowedDigest`, and on the telemetry cadence the detector compares the
RECENT median (both the 1m and 5m windows must agree — the SLO
engine's two-window discipline) against the 1h baseline median of the
same signature.  The ratio test is rank-relative, so it is unitless
and self-calibrating per plan: a 64×64 toy and a 2¹⁵×2¹⁵ production
grid drift on the same threshold.  Both directions are detected —
``slow`` (regression) and ``fast`` (suspicious speedup: work silently
skipped, wrong rung) — with asymmetric flap damping copied from
``slo.py``: entering an anomalous state is immediate, leaving it takes
``damp_evals`` consecutive calm evaluations.

On a transition into an anomalous state the detector emits ONE
``dispatch_anomaly`` trace event carrying exemplar trace ids of the
slowest recent dispatches (so the operator joins straight into
``/debug/flights`` and the distributed trace), appends an episode to
the ``/debug/anomalies`` ring, and — for ``slow`` drift only, when a
``--profile-dir`` is armed — starts ONE bounded ``jax.profiler``
capture into a rotated ``anomaly-*`` directory.  Duty-cycling is
enforced twice: a cooldown between captures (never back-to-back) and a
retention cap pruning the oldest capture directories, so an anomalous
week cannot fill the disk.

Armed-only (``Obs.arm_flight(anomaly=...)`` behind
``--anomaly-detect``); unarmed builds register none of these families.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from mpi_tpu.obs.timeseries import WindowedDigest

__all__ = ["AnomalyDetector"]

STATES = ("ok", "fast", "slow")
_RANK = {"ok": 0, "fast": 1, "slow": 2}

# recent windows that must BOTH drift before a transition (5m proves it
# is sustained, 1m proves it is still happening), vs the 1h baseline
RECENT_WINDOWS: Tuple[Tuple[str, float], ...] = (("1m", 60.0),
                                                 ("5m", 300.0))
BASELINE_S = 3600.0


def _default_capture(logdir: str, secs: float) -> None:
    """Fire-and-forget bounded capture on a daemon thread, through
    ``run_profile`` so the endpoint's process-global ``_profile_lock``
    serializes us against an operator-initiated capture."""
    from mpi_tpu.obs.profile import run_profile

    threading.Thread(target=run_profile, args=(logdir, secs),
                     name="mpi-tpu-anomaly-capture", daemon=True).start()


class AnomalyDetector:
    """Per-signature rank-relative drift detection + capture arming.

    ``observe`` is the flight recorder's ``on_record`` feed (armed-only
    hot path: one digest observe + one deque append).  ``evaluate``
    runs on the telemetry sampler's cadence, chained after the SLO
    evaluation.
    """

    def __init__(self, obs, ratio: float = 2.0, damp_evals: int = 3,
                 min_recent: int = 8, min_baseline: int = 32,
                 profile_dir: Optional[str] = None,
                 capture_s: float = 2.0, cooldown_s: float = 600.0,
                 retention: int = 4,
                 clock: Callable[[], float] = time.monotonic,
                 capture_fn: Optional[Callable[[str, float], None]] = None):
        if ratio <= 1.0:
            raise ValueError(f"drift ratio must be > 1, got {ratio}")
        self._obs = obs
        self.ratio = float(ratio)
        self.damp_evals = max(1, int(damp_evals))
        self.min_recent = max(1, int(min_recent))
        self.min_baseline = max(1, int(min_baseline))
        self.profile_dir = profile_dir
        self.capture_s = float(capture_s)
        self.cooldown_s = float(cooldown_s)
        self.retention = max(1, int(retention))
        self._clock = clock
        self._capture_fn = capture_fn or _default_capture
        self._lock = threading.Lock()
        self._digests: Dict[str, WindowedDigest] = {}
        # per sig: recent (wall_s, trace_id) pairs — exemplar pool for
        # the dispatch_anomaly event (slowest first at emission)
        self._recent: Dict[str, deque] = {}
        self._state: Dict[str, str] = {}
        self._streak: Dict[str, int] = {}
        self._episodes: deque = deque(maxlen=64)
        self._counts: Dict[str, int] = {}
        self._captures = 0
        self._capture_seq = 0
        self._last_capture: Optional[float] = None
        self._evals = 0

    # -- the hot-path feed -----------------------------------------------

    def observe(self, sig: Optional[str], wall_s: float,
                trace_id: Optional[str] = None) -> None:
        if sig is None:
            return
        with self._lock:
            dig = self._digests.get(sig)
            if dig is None:
                dig = self._digests[sig] = WindowedDigest(clock=self._clock)
                self._recent[sig] = deque(maxlen=8)
                self._state[sig] = "ok"
            recent = self._recent[sig]
        dig.observe(wall_s)
        recent.append((wall_s, trace_id))

    # -- evaluation ------------------------------------------------------

    def _classify(self, dig: WindowedDigest, now: float):
        base_n = dig.count(BASELINE_S, now)
        base = dig.quantile(0.5, BASELINE_S, now)
        detail = {"baseline_p50": base, "baseline_count": base_n,
                  "ratios": {}}
        if base is None or base <= 0 or base_n < self.min_baseline:
            return "ok", detail
        slow = fast = True
        for wname, ws in RECENT_WINDOWS:
            n = dig.count(ws, now)
            q = dig.quantile(0.5, ws, now)
            if n < self.min_recent or q is None:
                return "ok", detail
            r = q / base
            detail["ratios"][wname] = round(r, 4)
            if wname == "1m":
                detail["recent_p50"] = q
            slow = slow and r >= self.ratio
            fast = fast and r <= 1.0 / self.ratio
        if slow:
            return "slow", detail
        if fast:
            return "fast", detail
        return "ok", detail

    def evaluate(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            sigs = list(self._digests.items())
        for sig, dig in sigs:
            target, detail = self._classify(dig, now)
            with self._lock:
                cur = self._state[sig]
                if target != "ok" and target != cur:
                    # entering (or re-classifying) an anomaly: immediate
                    self._state[sig] = target
                    self._streak.pop(sig, None)
                    episode = self._episode(sig, target, detail, now)
                else:
                    episode = None
                    if target == "ok" and cur != "ok":
                        # leaving: damp_evals consecutive calm evals
                        n = self._streak.get(sig, 0) + 1
                        if n >= self.damp_evals:
                            self._state[sig] = "ok"
                            self._streak.pop(sig, None)
                        else:
                            self._streak[sig] = n
                    else:
                        self._streak.pop(sig, None)
            if episode is not None:
                self._emit(episode)
        with self._lock:
            self._evals += 1

    def _episode(self, sig: str, direction: str, detail: dict,
                 now: float) -> dict:
        # caller holds the lock
        pool = sorted(self._recent.get(sig, ()),
                      key=lambda p: p[0], reverse=True)
        exemplars = [tid for _, tid in pool if tid is not None][:3]
        self._counts[direction] = self._counts.get(direction, 0) + 1
        ep = {
            "sig": sig,
            "direction": direction,
            "t": now,
            "ratios": detail.get("ratios", {}),
            "baseline_p50": detail.get("baseline_p50"),
            "recent_p50": detail.get("recent_p50"),
            "baseline_count": detail.get("baseline_count"),
            "exemplars": exemplars,
            "capture_dir": None,
        }
        self._episodes.append(ep)
        return ep

    def _emit(self, ep: dict) -> None:
        if ep["direction"] == "slow":
            ep["capture_dir"] = self._maybe_capture(ep["t"])
        if self._obs is not None:
            base = ep["baseline_p50"]
            recent = ep["recent_p50"]
            self._obs.event(
                "dispatch_anomaly", sig=ep["sig"],
                direction=ep["direction"], ratios=ep["ratios"],
                baseline_p50=None if base is None else round(base, 9),
                recent_p50=None if recent is None else round(recent, 9),
                exemplars=ep["exemplars"],
                capture=ep["capture_dir"])

    # -- capture duty cycle ----------------------------------------------

    def _maybe_capture(self, now: float) -> Optional[str]:
        """Arm at most one bounded capture per cooldown window; prune
        the oldest ``anomaly-*`` capture dirs past the retention cap.
        Returns the capture directory, or None when disarmed/cooling."""
        with self._lock:
            if self.profile_dir is None:
                return None
            if (self._last_capture is not None
                    and now - self._last_capture < self.cooldown_s):
                return None
            # stamp BEFORE starting: a slow capture must not let the
            # next evaluation arm a back-to-back one
            self._last_capture = now
            self._capture_seq += 1
            seq = self._capture_seq
            self._captures += 1
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(self.profile_dir,
                            f"anomaly-{stamp}-{seq:03d}")
        try:
            self._prune_captures(keep_for=path)
            os.makedirs(path, exist_ok=True)
        except OSError:
            return None
        self._capture_fn(path, self.capture_s)
        return path

    def _prune_captures(self, keep_for: Optional[str] = None) -> None:
        """Drop the oldest ``anomaly-*`` dirs so at most ``retention``
        captures (including the one about to be written) remain."""
        try:
            names = sorted(n for n in os.listdir(self.profile_dir)
                           if n.startswith("anomaly-"))
        except OSError:
            return
        if keep_for is not None:
            names = [n for n in names
                     if n != os.path.basename(keep_for)]
        while len(names) >= self.retention:
            victim = names.pop(0)
            shutil.rmtree(os.path.join(self.profile_dir, victim),
                          ignore_errors=True)

    # -- readouts --------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/anomalies`` payload."""
        with self._lock:
            states = dict(self._state)
            episodes = list(self._episodes)
            counts = dict(self._counts)
            evals = self._evals
            captures = self._captures
            digs = list(self._digests.items())
        signatures = []
        for sig, dig in sorted(digs):
            s = dig.summary(BASELINE_S)
            signatures.append({"sig": sig, "state": states.get(sig, "ok"),
                               "baseline_count": s["count"],
                               "baseline_p50": s["p50"]})
        return {
            "ratio": self.ratio,
            "damp_evals": self.damp_evals,
            "min_recent": self.min_recent,
            "min_baseline": self.min_baseline,
            "windows_s": {w: s for w, s in RECENT_WINDOWS},
            "baseline_s": BASELINE_S,
            "capture": {
                "profile_dir": self.profile_dir,
                "capture_s": self.capture_s,
                "cooldown_s": self.cooldown_s,
                "retention": self.retention,
                "captures": captures,
            },
            "evals": evals,
            "anomalies_total": counts,
            "signatures": signatures,
            "episodes": episodes,
        }

    def stats(self) -> dict:
        with self._lock:
            return {"signatures": len(self._digests),
                    "episodes": len(self._episodes),
                    "captures": self._captures,
                    "evals": self._evals}

    # -- armed-only registry families ------------------------------------

    def bind_metrics(self, m) -> None:
        def _totals():
            with self._lock:
                return [({"direction": d}, c)
                        for d, c in sorted(self._counts.items())]

        m.counter_fn("mpi_tpu_dispatch_anomalies_total",
                     "Dispatch-latency drift episodes by direction "
                     "(present only when --anomaly-detect arms the "
                     "detector)",
                     _totals)

        def _states():
            with self._lock:
                return [({"sig": s}, float(_RANK[st]))
                        for s, st in sorted(self._state.items())]

        m.gauge_fn("mpi_tpu_anomaly_state",
                   "Per-signature drift state (0 ok, 1 fast, 2 slow)",
                   _states)
        m.counter_fn("mpi_tpu_anomaly_captures_total",
                     "Profiler captures armed by the anomaly detector",
                     lambda: self._captures)
