"""Jaxpr ALU lane-op counting — the counted-ops core of the roofline
audit, promoted out of ``tools/roofline.py`` so the live service can use
it too: the cost-card capture (``obs/cost.py``) falls back to counting a
stepper's traced jaxpr wherever XLA's ``cost_analysis()`` reports no
FLOPs for the compiled executable.

The count is arithmetic, not an estimate: every elementwise ALU
primitive in the (closed) jaxpr costs ``prod(shape of its first
output)`` lane-ops, recursing into sub-jaxprs (scan/while/cond/pjit
bodies).  Memory-movement primitives (slice/concat/pad/roll/transpose)
are NOT ALU ops and are excluded — on bandwidth-bound programs a roof
ratio computed from this count therefore *understates* the gap.

Unlike the tool, this module performs NO platform pinning and touches no
environment: it only traces (``jax.make_jaxpr``), which needs no device.
``tools/roofline.py`` keeps its own import-time CPU pin and re-exports
these names for backward compatibility.
"""

from __future__ import annotations

import numpy as np

# elementwise ALU primitives that occupy a VPU lane-op per output element
ALU_PRIMS = {
    "and", "or", "xor", "not", "add", "sub", "mul",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "max", "min",
    "population_count", "rem", "convert_element_type",
}


def _count_ops(jaxpr, consts_env=None) -> float:
    """Total ALU lane-ops in a (closed) jaxpr, recursing into sub-jaxprs;
    each primitive costs prod(shape of its first output)."""
    total = 0.0
    for eqn in jaxpr.eqns:
        for key in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
            sub = eqn.params.get(key)
            if sub is not None:
                inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                total += _count_ops(inner)
        if "branches" in eqn.params:
            for br in eqn.params["branches"]:
                total += _count_ops(br.jaxpr if hasattr(br, "jaxpr") else br)
        if eqn.primitive.name in ALU_PRIMS:
            aval = eqn.outvars[0].aval
            total += float(np.prod(aval.shape)) if aval.shape else 1.0
    return total


def count_ops(closed) -> float:
    """ALU lane-ops of a ``jax.make_jaxpr`` result (or a bare jaxpr)."""
    return _count_ops(closed.jaxpr if hasattr(closed, "jaxpr") else closed)


def ops_per_cell(step_fn, example, cells: int) -> float:
    """Lane-ops per cell of one traced application of ``step_fn``."""
    import jax

    closed = jax.make_jaxpr(step_fn)(example)
    return _count_ops(closed.jaxpr) / cells
