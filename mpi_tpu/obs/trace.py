"""Span tracer: bounded ring buffer + optional JSONL stream.

The design target is the serve hot path: recording a span must cost
about as much as two ``perf_counter`` calls and a tuple store, because
it brackets work (device dispatch, lock waits) measured in tens of
microseconds on CPU.  So the ring is "lock-free-ish": slot indices come
from ``itertools.count()`` (whose ``__next__`` is atomic in CPython)
and each record is a single list-slot store — no lock, no allocation
beyond the record tuple itself.  Torn reads are possible at the wrap
boundary during a concurrent ``snapshot()``; that is acceptable for a
diagnostic buffer and is why records are immutable tuples (a slot is
either the old record or the new one, never half of each).

Timestamps are ``time.perf_counter()`` (monotonic, ns-resolution) so
durations are exact; a single (mono, unix) anchor pair captured at
tracer creation converts them to wall-clock at *export* time, keeping
``time.time()`` out of the hot path.

Request-id propagation uses a ``ContextVar`` so the id set by the HTTP
handler flows into every span recorded downstream on the same logical
request — including watchdog worker threads (via ``copy_context``) and
batched follower commits (the batcher stashes the id per entry and
re-enters it around each commit).  One id, end-to-end: that is what
makes a request's lifecycle greppable out of the JSONL.

Since schema v2 every record may additionally carry the distributed
trace context (``trace_id``/``span_id``/``parent_span_id``, see
``obs/tracectx.py``): a ``Span`` entered under an ambient context
allocates its own span id and re-parents descendants to itself for the
duration of the block, and events get leaf span ids.  Records emitted
outside any request (gossip, stream pushes, crash markers) carry no
trace keys — exactly like ``rid`` — which is also how v1 logs read
back: the context keys are optional everywhere.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

from mpi_tpu.obs.tracectx import (
    TRACE_CONTEXT, TraceContext, reset_trace_context, set_trace_context,
)

# Ring/record layout and JSONL schema version: v1 records were
# (seq, name, t0, dur_s, rid, thread, fields); v2 appends the trace
# context triple (None outside a traced request).  The JSONL keys are
# strictly additive, so v1 readers and logs interoperate both ways.
TRACE_SCHEMA_VERSION = 2

# The one process-wide request-id slot.  httpd sets it at request entry;
# everything downstream (session, batcher, engine, recovery) reads it.
REQUEST_ID: ContextVar[Optional[int]] = ContextVar(
    "mpi_tpu_request_id", default=None)


def current_request_id() -> Optional[int]:
    return REQUEST_ID.get()


def set_request_id(rid: Optional[int]):
    """Returns a token for ``reset_request_id``."""
    return REQUEST_ID.set(rid)


def reset_request_id(token) -> None:
    REQUEST_ID.reset(token)


class Span:
    """Context-manager span.  ``with tracer.span("x", sid=s) as sp:``
    records name/duration/tags on exit; an exception inside the block is
    recorded as an ``error`` field and re-raised."""

    __slots__ = ("_tracer", "name", "fields", "t0", "_ctx", "_ctx_token")

    def __init__(self, tracer: "Tracer", name: str, fields: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.fields = fields
        self.t0 = 0.0
        self._ctx: Optional[TraceContext] = None
        self._ctx_token = None

    def tag(self, **kv) -> "Span":
        self.fields.update(kv)
        return self

    @property
    def ctx(self) -> Optional["TraceContext"]:
        """This span's trace context (None outside a traced request)."""
        return self._ctx

    def __enter__(self) -> "Span":
        ctx = TRACE_CONTEXT.get()
        if ctx is not None:
            # this span becomes the parent of everything in the block
            self._ctx = ctx.child()
            self._ctx_token = set_trace_context(self._ctx)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self.t0
        if self._ctx_token is not None:
            reset_trace_context(self._ctx_token)
            self._ctx_token = None
        if exc_type is not None:
            self.fields["error"] = f"{exc_type.__name__}: {exc}"
        self._tracer._record(self.name, self.t0, dur, self.fields,
                             tctx=self._ctx)
        return False


class Tracer:
    def __init__(self, capacity: int = 4096,
                 log_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.log_path = log_path
        self._buf: List[Optional[tuple]] = [None] * capacity
        self._seq = itertools.count()
        # Anchor pair: wall time corresponding to a perf_counter reading,
        # taken once so export-time t_unix = anchor_unix + (t - anchor_mono).
        self._anchor_mono = time.perf_counter()
        self._anchor_unix = time.time()
        self._log_lock = threading.Lock()
        self._log_fh = None

    # -- recording -------------------------------------------------------

    def span(self, name: str, **fields) -> Span:
        return Span(self, name, fields)

    def event(self, name: str, dur_s: float = 0.0,
              t0: Optional[float] = None, **fields) -> None:
        """Record a point (or pre-measured interval) without the
        context-manager overhead — the hot-path primitive."""
        self._record(name, time.perf_counter() if t0 is None else t0,
                     dur_s, fields)

    def _record(self, name: str, t0: float, dur_s: float,
                fields: Dict[str, Any],
                tctx: Optional[TraceContext] = None) -> None:
        rid = fields.pop("rid", None)
        if rid is None:
            rid = REQUEST_ID.get()
        if tctx is None:
            # events are leaves: own span id, parented to the ambient
            # context (one ContextVar.get when untraced — hot-path safe)
            ctx = TRACE_CONTEXT.get()
            if ctx is not None:
                tctx = ctx.child()
        i = next(self._seq)
        rec = (i, name, t0, dur_s, rid,
               threading.current_thread().name, fields or None, tctx)
        self._buf[i % self.capacity] = rec
        if self.log_path is not None:
            self._stream(rec)

    def _stream(self, rec: tuple) -> None:
        try:
            with self._log_lock:
                if self._log_fh is None:
                    self._log_fh = open(self.log_path, "a",
                                        encoding="utf-8")
                self._log_fh.write(json.dumps(
                    self._to_dict(rec), separators=(",", ":")) + "\n")
                self._log_fh.flush()
        except OSError:
            # A full/yanked disk must not take the serve loop down.
            pass

    # -- export ----------------------------------------------------------

    def _to_dict(self, rec: tuple) -> Dict[str, Any]:
        i, name, t0, dur_s, rid, thr, fields, tctx = rec
        d: Dict[str, Any] = {
            "seq": i,
            "name": name,
            "t_unix": round(self._anchor_unix + (t0 - self._anchor_mono), 6),
            "t_mono": round(t0, 9),
            "dur_s": round(dur_s, 9),
            "thread": thr,
        }
        if rid is not None:
            d["rid"] = rid
        if tctx is not None:
            d["trace_id"] = tctx.trace_id
            d["span_id"] = tctx.span_id
            if tctx.parent_span_id is not None:
                d["parent_span_id"] = tctx.parent_span_id
        if fields:
            for k, v in fields.items():
                if k not in d:
                    d[k] = v
        return d

    def snapshot(self) -> List[Dict[str, Any]]:
        recs = [r for r in self._buf if r is not None]
        recs.sort(key=lambda r: r[0])
        return [self._to_dict(r) for r in recs]

    def dump(self, path: str) -> int:
        recs = self.snapshot()
        with open(path, "w", encoding="utf-8") as fh:
            for d in recs:
                fh.write(json.dumps(d, separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return len(recs)

    def dump_on_crash(self, note: str = "") -> Optional[str]:
        """Called from the httpd catch-all 500 handler.  If already
        streaming to --trace-log the crash marker lands there; otherwise
        the ring is flushed to a tempdir file so the evidence survives."""
        self.event("crash_dump", note=note)
        if self.log_path is not None:
            return self.log_path
        path = os.path.join(tempfile.gettempdir(),
                            f"mpi_tpu_trace_crash_{os.getpid()}.jsonl")
        try:
            self.dump(path)
        except OSError:
            return None
        return path

    def stats(self) -> Dict[str, Any]:
        recorded = 0
        for r in self._buf:
            if r is not None and r[0] >= recorded:
                recorded = r[0] + 1
        return {
            "capacity": self.capacity,
            "recorded": recorded,
            "dropped": max(0, recorded - self.capacity),
            "streaming": self.log_path is not None,
            "schema": TRACE_SCHEMA_VERSION,
        }

    def close(self) -> None:
        with self._log_lock:
            if self._log_fh is not None:
                try:
                    self._log_fh.flush()
                    os.fsync(self._log_fh.fileno())
                    self._log_fh.close()
                except OSError:
                    pass
                self._log_fh = None
