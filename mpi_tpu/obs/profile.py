"""Profiling hooks: on-demand JAX device traces over live traffic and a
compile-vs-execute breakdown from counters the engine already keeps.

``run_profile`` brackets ``jax.profiler.start_trace``/``stop_trace`` for
the ``POST /debug/profile?secs=N`` endpoint — the operator captures a
TensorBoard-readable device trace of whatever the serve loop is doing
*right now*, without restarting anything.  One capture at a time: JAX's
profiler is a process-global singleton, so a second concurrent request
is refused rather than corrupting the first capture.

``compile_execute_breakdown`` answers the triage question PERF.md keeps
asking by hand: is this deployment compile-bound (XLA wall dominates),
dispatch-bound (the ~68 ms fixed per-call cost dominates — batching
would help), or compute-bound (the device is actually busy)?  It is
derived entirely from counters the engine and batcher already maintain
(``compile_count``/``step_calls``/``batched_step_calls``/
``compile_wall_s`` and the batcher's amortization stats) — no new
instrumentation on the hot path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

_profile_lock = threading.Lock()


def run_profile(logdir: str, secs: float) -> Dict:
    """Capture ``secs`` of device trace into ``logdir``.  Returns a JSON-
    ready dict; a capture already in flight answers ``ok: False`` (the
    profiler is process-global — two captures would corrupt each other).
    """
    secs = max(0.05, min(float(secs), 120.0))
    if not _profile_lock.acquire(blocking=False):
        return {"ok": False, "error": "a profile capture is already running"}
    try:
        import jax

        try:
            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001
            # a start_trace that raises partway (bad logdir, a capture
            # started out-of-band) can leave JAX's process-global
            # profiler half-armed; best-effort stop so the NEXT capture
            # is not refused for the process lifetime
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — nothing was started
                pass
            return {"ok": False, "log_dir": logdir,
                    "error": f"{type(e).__name__}: {e}"}
        try:
            time.sleep(secs)
        finally:
            jax.profiler.stop_trace()
        return {"ok": True, "log_dir": logdir, "seconds": secs}
    except Exception as e:  # noqa: BLE001 — profiling must not 500 the server
        return {"ok": False, "log_dir": logdir,
                "error": f"{type(e).__name__}: {e}"}
    finally:
        _profile_lock.release()


def _live_engines(manager) -> List:
    """Every distinct engine the manager can reach: the cache's entries
    plus any engine a live session still holds after eviction (sessions
    keep their own reference — cache.py's eviction contract)."""
    seen, out = set(), []
    for eng in manager.cache.engines():
        if id(eng) not in seen:
            seen.add(id(eng))
            out.append(eng)
    with manager._lock:
        sessions = list(manager._sessions.values())
    for s in sessions:
        eng = s.engine
        if eng is not None and id(eng) not in seen:
            seen.add(id(eng))
            out.append(eng)
    return out


def compile_execute_breakdown(manager) -> Dict:
    """Aggregate compile vs execute time over every reachable engine and
    name the regime.  'compile-bound': XLA wall exceeds execute wall
    (cold start, signature churn).  'dispatch-bound': batching is
    amortizing a large fixed per-call cost (or would — solo per-call
    time dwarfs the batched per-board time).  'compute-bound': neither —
    the device is doing real work."""
    engines = _live_engines(manager)
    compiles = sum(e.compile_count for e in engines)
    batched_compiles = sum(e.batched_compile_count for e in engines)
    step_calls = sum(e.step_calls for e in engines)
    batched_calls = sum(e.batched_step_calls for e in engines)
    compile_wall = sum(getattr(e, "compile_wall_s", 0.0) for e in engines)
    if manager.batcher is not None:
        bs = manager.batcher.stats()
        execute_wall = bs["batched_step_s"] + bs["solo_step_s"]
        solo_steps = bs["solo_steps"]
        amortized = bs["amortized_board_step_s"]
        solo_avg = (bs["solo_step_s"] / solo_steps) if solo_steps else None
    else:
        with manager._lock:
            sessions = list(manager._sessions.values())
        execute_wall = sum(s.steady_s for s in sessions)
        amortized = None
        solo_avg = (execute_wall / step_calls) if step_calls else None
    if compiles == 0 and step_calls == 0 and batched_calls == 0:
        regime = "idle"
    elif compile_wall > execute_wall:
        regime = "compile-bound"
    elif (amortized is not None and solo_avg
          and 1.0 - amortized / solo_avg > 0.5):
        # batching recovers >50% of the per-call cost: the fixed
        # dispatch overhead, not the compute, was the bill
        regime = "dispatch-bound"
    else:
        regime = "compute-bound"
    return {
        "engines": len(engines),
        "compiles": compiles,
        "batched_compiles": batched_compiles,
        "compile_wall_s": round(compile_wall, 6),
        "step_calls": step_calls,
        "batched_step_calls": batched_calls,
        "execute_wall_s": round(execute_wall, 6),
        "solo_avg_call_s": round(solo_avg, 6) if solo_avg else None,
        "amortized_board_step_s": amortized,
        "regime": regime,
    }
