"""Device memory + compile-cache telemetry (ISSUE 19 tentpole, part 2).

On real TPUs the question after "where did the time go" is "where did
the HBM go": a leaked donated buffer or an engine-cache blowup shows up
as an OOM generations later, far from the cause.  This sampler rides
the PR-15 telemetry ticker (chained on ``TelemetryRecorder.
after_sample``) and, once per tick:

* reads ``jax.local_devices()`` memory stats per device — ``in_use`` /
  ``limit`` / ``peak`` where the runtime reports them (TPU/GPU), with a
  ``live_arrays`` fallback (sum of addressable-shard nbytes) on
  XLA:CPU where ``memory_stats()`` is None — exported as
  ``mpi_tpu_device_memory_bytes{device,kind}`` and recorded into the
  telemetry ring so ``/debug/timeseries`` can plot the trend;
* records EngineCache / batched-stepper / tune-cache occupancy
  (``mpi_tpu_engine_cache_entries{cache}`` reads the authoritative
  ``OrderedDict`` sizes at scrape time — the no-shadow-counting rule);
* times one ghost-ring exchange on the serving mesh through
  :func:`mpi_tpu.parallel.step.make_halo_probe` (memoized per
  mesh/shape, first compile call discarded, multi-device meshes only)
  into ``mpi_tpu_halo_exchange_seconds{mesh}`` — the per-shard halo
  seam the paper's scaling story lives or dies on.

Armed-only: constructed by ``Obs.arm_flight`` when telemetry is armed;
unarmed builds register none of these families.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from mpi_tpu.obs.metrics import LATENCY_BUCKETS

__all__ = ["DevMemSampler", "read_device_memory"]


def read_device_memory() -> Dict[Tuple[str, str], float]:
    """``{(device_label, kind): bytes}`` across local devices.  Kinds:
    ``in_use``/``limit``/``peak`` from the runtime's ``memory_stats()``
    where available, else ``live_arrays`` (addressable-shard nbytes sum
    — the XLA:CPU fallback, which has no allocator stats)."""
    import jax

    out: Dict[Tuple[str, str], float] = {}
    bare = []
    for d in jax.local_devices():
        label = f"{d.platform}:{d.id}"
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats support
            stats = None
        if stats:
            for src, kind in (("bytes_in_use", "in_use"),
                              ("bytes_limit", "limit"),
                              ("peak_bytes_in_use", "peak")):
                if src in stats:
                    out[(label, kind)] = float(stats[src])
        else:
            bare.append(label)
    if bare:
        acc = {lbl: 0.0 for lbl in bare}
        try:
            for arr in jax.live_arrays():
                try:
                    for sh in arr.addressable_shards:
                        d = sh.device
                        lbl = f"{d.platform}:{d.id}"
                        if lbl in acc:
                            acc[lbl] += sh.data.nbytes
                except Exception:  # noqa: BLE001 — deleted mid-iteration
                    continue
        except Exception:  # noqa: BLE001
            pass
        for lbl, v in acc.items():
            out[(lbl, "live_arrays")] = v
    return out


class DevMemSampler:
    """One tick of device-memory + cache + halo telemetry.

    ``sample(now)`` is chained after the SLO evaluation on the telemetry
    ticker; a raising backend must not kill the sampler (errors are
    counted, the tick survives).  The memory snapshot is held for the
    scrape callbacks — sampling at scrape time would put a
    ``live_arrays`` walk on every ``/metrics`` GET.
    """

    def __init__(self, obs, manager=None, halo_probe: bool = True,
                 probe_tile: int = 128,
                 clock: Callable[[], float] = time.monotonic):
        self._obs = obs
        self._manager = manager
        self._halo_enabled = halo_probe
        self._probe_tile = int(probe_tile)
        self._clock = clock
        self._lock = threading.Lock()
        self._mem: Dict[Tuple[str, str], float] = {}
        self._samples = 0
        self._errors = 0
        # memoized probe: (mesh key) -> (probe fn, operand, warmed)
        self._probe_key = None
        self._probe = None
        self.halo_hist = obs.metrics.histogram(
            "mpi_tpu_halo_exchange_seconds",
            "Wall time of one probed ghost-ring exchange on the serving "
            "mesh (armed only: --flight-recorder + telemetry)",
            LATENCY_BUCKETS)

    # -- sampling --------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> None:
        try:
            mem = read_device_memory()
            with self._lock:
                self._mem = mem
                self._samples += 1
        except Exception:  # noqa: BLE001 — the ticker must outlive jax
            with self._lock:
                self._errors += 1
            return
        if self._halo_enabled:
            try:
                self._probe_halo()
            except Exception:  # noqa: BLE001
                with self._lock:
                    self._errors += 1

    def memory_total(self, kind: str = "in_use") -> float:
        """Summed bytes across devices for one kind, with the
        ``live_arrays`` fallback when the allocator kind is absent —
        the telemetry-ring series feed."""
        with self._lock:
            mem = dict(self._mem)
        total = sum(v for (_, k), v in mem.items() if k == kind)
        if total == 0.0 and kind == "in_use":
            total = sum(v for (_, k), v in mem.items()
                        if k == "live_arrays")
        return total

    # -- halo probe ------------------------------------------------------

    def _serving_mesh(self):
        """The first multi-device mesh among live engines, or None (a
        1-device mesh exchanges with itself — nothing worth timing)."""
        mgr = self._manager
        if mgr is None:
            return None, None
        from mpi_tpu.obs.profile import _live_engines

        for e in _live_engines(mgr):
            mesh = getattr(e, "mesh", None)
            if mesh is not None and mesh.devices.size > 1:
                return mesh, getattr(e.config, "boundary", "periodic")
        return None, None

    def _probe_halo(self) -> None:
        mesh, boundary = self._serving_mesh()
        if mesh is None:
            return
        key = (tuple(mesh.shape.items()), boundary,
               tuple(d.id for d in mesh.devices.flat))
        if key != self._probe_key:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec

            from mpi_tpu.parallel.mesh import AXES
            from mpi_tpu.parallel.step import make_halo_probe

            ni = mesh.shape.get(AXES[0], 1)
            nj = mesh.shape.get(AXES[1], 1)
            operand = jax.device_put(
                jnp.zeros((ni * self._probe_tile, nj * self._probe_tile),
                          dtype=jnp.uint8),
                NamedSharding(mesh, PartitionSpec(*AXES)))
            fn = make_halo_probe(mesh, boundary)
            # warm the compile outside the timed window: the first call
            # is XLA wall, not halo wall
            fn(operand).block_until_ready()
            label = "x".join(str(mesh.shape[a]) for a in sorted(mesh.shape))
            self._probe_key = key
            self._probe = (fn, operand, self.halo_hist.series(mesh=label))
        fn, operand, series = self._probe
        t0 = time.perf_counter()
        fn(operand).block_until_ready()
        series.observe(time.perf_counter() - t0)

    # -- readouts --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"samples": self._samples, "errors": self._errors,
                    "devices": len({d for d, _ in self._mem}),
                    "halo_probe": self._halo_enabled}

    # -- armed-only registry families ------------------------------------

    def bind_metrics(self, m) -> None:
        def _mem_series():
            with self._lock:
                mem = dict(self._mem)
            return [({"device": dev, "kind": kind}, v)
                    for (dev, kind), v in sorted(mem.items())]

        m.gauge_fn("mpi_tpu_device_memory_bytes",
                   "Per-device memory by kind (in_use/limit/peak from "
                   "the allocator, live_arrays on backends without "
                   "stats)",
                   _mem_series)

        def _cache_entries():
            mgr = self._manager
            if mgr is None:
                return []
            st = mgr.cache.stats()
            out = [({"cache": "engine"}, st["size"]),
                   ({"cache": "batched"}, st["batched"]["size"])]
            tc = getattr(mgr, "tune_cache", None)
            if tc is not None:
                out.append(({"cache": "tune"},
                            len(getattr(tc, "_entries", ()))))
            return out

        m.gauge_fn("mpi_tpu_engine_cache_entries",
                   "Compiled-engine, batched-stepper, and tune-cache "
                   "occupancy (authoritative sizes read at scrape time)",
                   _cache_entries)
