"""Sliding-window quantile digests + the in-process telemetry recorder
(ISSUE 15 tentpole, part a).

The scrape surface (`/metrics`) answers "now"; this module gives the
process a bounded MEMORY of its own recent behaviour so the SLO engine
(:mod:`.slo`) and `/debug/timeseries` can answer "over the last
1m/5m/1h" without an external Prometheus:

* :class:`WindowedDigest` — a DDSketch-style log-bucket quantile digest
  over a ring of 5-second time slices.  Bucket bounds grow geometrically
  (``gamma = (1+alpha)/(1-alpha)``), so any quantile estimate is within
  ``alpha`` (default 5%) RELATIVE error of a true sample at that rank —
  the bound ``tests/test_slo.py`` checks against ``numpy.percentile`` on
  adversarial (bimodal, heavy-tail) distributions.  Slices rotate lazily
  off the injected clock (no timer thread per digest), windows are
  accurate to one slice (±5 s), and memory is bounded: ≤720 slices of
  sparse bucket-count dicts (~300 possible buckets across 13 decades).
* :class:`TelemetryRecorder` — a fixed-capacity ring buffer of sampled
  registry series (counters recorded cumulatively, rendered as rates;
  gauges recorded raw), one sample per ``--telemetry-interval-s`` tick
  from a daemon thread.  Sampling READS the authoritative instruments
  (``Counter.total`` / ``Histogram.total_count`` / ``_FnMetric.read_sum``)
  — the no-shadow-counting rule extends to history.

Everything here exists only when armed: the recorder (and its one
``mpi_tpu_telemetry_samples_total`` family) is constructed by
``Obs.arm_telemetry`` behind ``--telemetry-interval-s``, so the unarmed
scrape text and trace JSONL stay byte-identical to the pre-telemetry
build.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

# the window vocabulary shared by digests, /debug/timeseries, and the
# SLO engine's fast/slow burn windows
WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("1m", 60.0), ("5m", 300.0), ("1h", 3600.0))
WINDOW_S: Dict[str, float] = dict(WINDOWS)

# values at or below this clamp share one bucket ("effectively zero" —
# latencies this small are below clock resolution anyway)
_MIN_VALUE = 1e-9


class WindowedDigest:
    """Quantiles over a sliding time window, log-bucket quantization.

    ``observe`` is O(1): one clock read, one log, one dict increment
    under the digest lock — armed-only hot-path cost.  Queries merge the
    slices younger than the window and walk the sorted sparse buckets.
    """

    SLICE_S = 5.0

    def __init__(self, alpha: float = 0.05, max_window_s: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0,1), got {alpha}")
        self.alpha = alpha
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self._gamma)
        self._clock = clock
        self._nslices = int(math.ceil(max_window_s / self.SLICE_S)) + 1
        # ring position = epoch % nslices; the stored epoch disambiguates
        # a live slice from a stale one (lazy rotation: an observe or a
        # query simply ignores/overwrites slices whose epoch is old)
        self._slices: List[Optional[Dict[int, int]]] = [None] * self._nslices
        self._epochs: List[int] = [-1] * self._nslices
        self._lock = threading.Lock()

    def _index(self, value: float) -> int:
        # bucket i covers (gamma^(i-1), gamma^i]; ceil keeps v <= gamma^i
        return int(math.ceil(math.log(max(value, _MIN_VALUE)) / self._lg))

    def _estimate(self, idx: int) -> float:
        # 2*gamma^i/(gamma+1): relative error to any value in the bucket
        # is at most (gamma-1)/(gamma+1) == alpha
        return 2.0 * (self._gamma ** idx) / (self._gamma + 1.0)

    def observe(self, value: float) -> None:
        epoch = int(self._clock() / self.SLICE_S)
        pos = epoch % self._nslices
        idx = self._index(value)
        with self._lock:
            if self._epochs[pos] != epoch:
                self._slices[pos] = {}
                self._epochs[pos] = epoch
            sl = self._slices[pos]
            sl[idx] = sl.get(idx, 0) + 1

    def _merged(self, window_s: float,
                now: Optional[float] = None) -> Dict[int, int]:
        """Bucket counts across slices younger than ``window_s`` (window
        edges quantized to one slice — ±``SLICE_S`` of slack)."""
        now = self._clock() if now is None else now
        cur_epoch = int(now / self.SLICE_S)
        min_epoch = int((now - window_s) // self.SLICE_S)
        counts: Dict[int, int] = {}
        with self._lock:
            for pos in range(self._nslices):
                e = self._epochs[pos]
                # e == -1 is a never-written slice; it must not pass the
                # staleness filter when the window reaches past t=0 of a
                # near-zero clock (injected clocks, freshly booted hosts)
                if e < max(0, min_epoch) or e > cur_epoch:
                    continue
                for idx, c in self._slices[pos].items():
                    counts[idx] = counts.get(idx, 0) + c
        return counts

    def count(self, window_s: float, now: Optional[float] = None) -> int:
        return sum(self._merged(window_s, now).values())

    def quantile(self, q: float, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        """The q-quantile estimate over the window, or None when empty."""
        counts = self._merged(window_s, now)
        total = sum(counts.values())
        if total == 0:
            return None
        rank = max(1, int(math.ceil(q * total)))
        cum = 0
        for idx in sorted(counts):
            cum += counts[idx]
            if cum >= rank:
                return self._estimate(idx)
        return self._estimate(max(counts))  # pragma: no cover — q > 1

    def fraction_above(self, threshold: float, window_s: float,
                       now: Optional[float] = None) -> float:
        """Fraction of windowed observations strictly above the
        threshold's bucket — the latency-SLO "bad events" ratio.  Values
        in the bucket straddling the threshold count as under it
        (quantization error bounded by ``alpha``)."""
        counts = self._merged(window_s, now)
        total = sum(counts.values())
        if total == 0:
            return 0.0
        thr_idx = self._index(threshold)
        above = sum(c for idx, c in counts.items() if idx > thr_idx)
        return above / total

    def summary(self, window_s: float,
                now: Optional[float] = None) -> dict:
        counts = self._merged(window_s, now)
        total = sum(counts.values())
        if total == 0:
            return {"count": 0, "p50": None, "p95": None, "p99": None}
        ordered = sorted(counts)
        out = {"count": total}
        for label, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            rank = max(1, int(math.ceil(q * total)))
            cum = 0
            for idx in ordered:
                cum += counts[idx]
                if cum >= rank:
                    out[label] = self._estimate(idx)
                    break
        return out


class TelemetryRecorder:
    """Ring-buffered samples of selected registry series + the hot-path
    latency digests, advanced by one daemon thread per process.

    The sampled set is fixed and small (see ``_read_all``): request and
    dispatch counters (stored cumulative, exposed as rates), failure
    counters, and the queue/session gauges the SLO engine and
    ``/debug/timeseries`` consumers actually use.  Families that are not
    registered yet (e.g. before ``bind_manager``) are skipped that tick
    and picked up once they appear.
    """

    # ring capacity: 720 samples = 1 h of history at the 5 s default
    # cadence — matches the digests' longest window
    def __init__(self, registry, interval_s: float = 5.0,
                 capacity: int = 720, alpha: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 2:
            raise ValueError("telemetry ring needs capacity >= 2")
        self.registry = registry
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._rings: Dict[str, deque] = {}
        self._samples = 0
        self._sample_errors = 0
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        # extra sampled series registered by armed-only subsystems
        # (ISSUE 19's devmem sampler): read on every tick alongside the
        # fixed SERIES set
        self._extra: List[Tuple[str, Callable[[], Optional[float]]]] = []
        # called after every sample with the sample time — the SLO
        # engine's evaluation piggybacks on the same cadence
        self.after_sample: Optional[Callable[[float], None]] = None
        # sliding-window quantile digests for the hot latency paths;
        # sites reach these through the pre-looked-up handles below
        # (one attribute load + None check when unarmed)
        self.digests: Dict[str, WindowedDigest] = {
            path: WindowedDigest(alpha=alpha, clock=clock)
            for path in ("dispatch", "http", "ticket_wait")}
        self.dispatch_digest = self.digests["dispatch"]
        self.http_digest = self.digests["http"]
        self.ticket_wait_digest = self.digests["ticket_wait"]

    # -- armed-only registry family ---------------------------------------

    def bind_metrics(self, m) -> None:
        m.counter_fn(
            "mpi_tpu_telemetry_samples_total",
            "Telemetry sampler ticks (present only when "
            "--telemetry-interval-s arms the recorder)",
            lambda: self._samples)

    # -- sampling ----------------------------------------------------------

    # (name, kind): counters are recorded cumulatively so the SLO engine
    # can take exact window deltas; /debug/timeseries renders them as
    # rates between consecutive samples
    SERIES: Tuple[Tuple[str, str], ...] = (
        ("http_requests", "counter"),
        ("http_5xx", "counter"),
        ("dispatches", "counter"),
        ("dispatch_seconds", "counter"),
        ("engine_failures", "counter"),
        ("trace_spans", "counter"),
        ("sessions", "gauge"),
        ("degraded_sessions", "gauge"),
        ("tickets_pending", "gauge"),
        ("batch_queue_depth", "gauge"),
    )
    KINDS: Dict[str, str] = dict(SERIES)

    def _read_all(self) -> Dict[str, float]:
        from mpi_tpu.obs.metrics import Counter, Histogram, _FnMetric

        reg = self.registry
        out: Dict[str, float] = {}

        req = reg.get("mpi_tpu_http_requests_total")
        if isinstance(req, Counter):
            out["http_requests"] = req.total()
            out["http_5xx"] = req.total(
                where=lambda lbl: str(lbl.get("code", "")).startswith("5"))
        lat = reg.get("mpi_tpu_dispatch_latency_seconds")
        if isinstance(lat, Histogram):
            out["dispatches"] = float(lat.total_count())
            out["dispatch_seconds"] = lat.total_sum()
        for series, family in (
                ("engine_failures", "mpi_tpu_engine_failures_total"),
                ("trace_spans", "mpi_tpu_trace_spans_total"),
                ("sessions", "mpi_tpu_sessions"),
                ("degraded_sessions", "mpi_tpu_degraded_sessions"),
                ("tickets_pending", "mpi_tpu_tickets_pending"),
                ("batch_queue_depth", "mpi_tpu_batch_queue_depth")):
            fm = reg.get(family)
            if isinstance(fm, _FnMetric):
                v = fm.read_sum()
                if v is not None:
                    out[series] = v
        return out

    def add_series(self, name: str, kind: str,
                   read_fn: Callable[[], Optional[float]]) -> None:
        """Register an extra sampled series (armed-only subsystems —
        e.g. the devmem sampler's device-memory and cache-occupancy
        feeds).  ``read_fn`` returns the current value, or None to skip
        the tick.  ``KINDS`` is copied onto the instance on first use so
        the class schema stays fixed."""
        if kind not in ("counter", "gauge"):
            raise ValueError(f"series kind must be counter|gauge, "
                             f"got {kind!r}")
        if self.KINDS is type(self).KINDS:
            self.KINDS = dict(type(self).KINDS)
        self.KINDS[name] = kind
        self._extra.append((name, read_fn))

    def sample_once(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        vals = self._read_all()
        for name, fn in self._extra:
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — one sick provider must
                self._sample_errors += 1  # not kill the tick
                continue
            if v is not None:
                vals[name] = float(v)
        with self._lock:
            for name, v in vals.items():
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = deque(maxlen=self.capacity)
                ring.append((now, v))
            self._samples += 1
        cb = self.after_sample
        if cb is not None:
            cb(now)

    def window_delta(self, name: str, window_s: float,
                     now: Optional[float] = None) -> float:
        """Counter increase over the trailing window: latest sample minus
        the sample at the window start, clipped to recorded history (a
        younger-than-window process reports its whole history)."""
        with self._lock:
            ring = self._rings.get(name)
            if not ring:
                return 0.0
            now = self._clock() if now is None else now
            cutoff = now - window_s
            base = ring[0][1]
            for t, v in ring:
                if t > cutoff:
                    break
                base = v
            return max(0.0, ring[-1][1] - base)

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def points(self, name: str, window_s: float,
               now: Optional[float] = None) -> List[List[float]]:
        """``[[t, value], ...]`` for the trailing window — gauges raw,
        counters as the rate between consecutive samples (anchored at
        the later sample's timestamp)."""
        with self._lock:
            ring = self._rings.get(name)
            snap = list(ring) if ring else []
        if not snap:
            return []
        now = self._clock() if now is None else now
        cutoff = now - window_s
        if self.KINDS.get(name) == "gauge":
            return [[t, v] for t, v in snap if t >= cutoff]
        out: List[List[float]] = []
        prev_t, prev_v = None, None
        for t, v in snap:
            if prev_t is not None and t >= cutoff and t > prev_t:
                out.append([t, max(0.0, v - prev_v) / (t - prev_t)])
            prev_t, prev_v = t, v
        return out

    def windows_summary(self) -> dict:
        """Per-path digest summaries over every window — the `/slo`
        payload's ``windows`` block."""
        return {path: {label: dig.summary(sec)
                       for label, sec in WINDOWS}
                for path, dig in sorted(self.digests.items())}

    def stats(self) -> dict:
        with self._lock:
            return {"samples": self._samples,
                    "sample_errors": self._sample_errors,
                    "series": len(self._rings),
                    "interval_s": self.interval_s}

    # -- background cadence ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return
        # an immediate baseline sample: window deltas then cover all
        # traffic since arming, not since the first timer tick
        try:
            self.sample_once()
        except Exception:  # noqa: BLE001
            self._sample_errors += 1
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mpi-tpu-telemetry", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — the sampler must outlive
                self._sample_errors += 1  # one sick provider/objective

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self._stop = None
