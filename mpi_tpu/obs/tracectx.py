"""W3C-``traceparent``-style trace context for cross-process stitching.

A request's trace identity is three ids: a 32-hex ``trace_id`` shared
by every span the request causes anywhere in the cluster, a 16-hex
``span_id`` naming one span, and the ``parent_span_id`` that makes the
set a tree.  The context is minted once at the serving edge (AppCore),
carried on the wire as an ``X-Gol-Traceparent`` header (the W3C
``00-<trace>-<span>-01`` shape) through one-hop proxy forwards and
stream redirects, and carried in-process by a ``ContextVar`` beside the
request id — so watchdog workers, the batch leader's thread hop, and
the async dispatcher (tickets persist their minting context) all record
spans under one trace id, end to end across processes.

The hot-path contract matches ``obs/trace.py``: a span recorded with no
ambient context costs one ``ContextVar.get`` and nothing else; span-id
generation (one ``os.urandom`` call) happens only on traced requests,
never on the bare ``manager.step`` path that ``bench.py --serve-obs``
gates.
"""

from __future__ import annotations

import os
from contextvars import ContextVar
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

# Header carrying the context across process hops (proxy forwards, the
# /stream 307 redirect, and every instrumented response so clients can
# correlate logs and feed GET /debug/trace/<trace_id>).
TRACEPARENT_HEADER = "X-Gol-Traceparent"

_NULL_SPAN = "0" * 16


class TraceContext(NamedTuple):
    """``span_id is None`` marks an edge anchor: a context that parents
    spans but is not itself a span (a freshly minted trace's virtual
    root).  A parsed remote context keeps the remote span id, so local
    spans become its children in the stitched tree."""

    trace_id: str
    span_id: Optional[str] = None
    parent_span_id: Optional[str] = None

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def link(self) -> str:
        """Compact ``trace_id:span_id`` reference for span *links*
        (riders of a shared dispatch, related but not parented)."""
        return f"{self.trace_id}:{self.span_id or _NULL_SPAN}"


TRACE_CONTEXT: ContextVar[Optional[TraceContext]] = ContextVar(
    "mpi_tpu_trace_context", default=None)


def _new_span_id() -> str:
    return os.urandom(8).hex()


def mint() -> TraceContext:
    """A fresh trace anchor for a request that arrived without a
    traceparent: new trace id, no span of its own — the first span
    recorded under it becomes the tree root."""
    return TraceContext(os.urandom(16).hex(), None, None)


def current_trace_context() -> Optional[TraceContext]:
    return TRACE_CONTEXT.get()


def set_trace_context(ctx: Optional[TraceContext]):
    """Returns a token for ``reset_trace_context``."""
    return TRACE_CONTEXT.set(ctx)


def reset_trace_context(token) -> None:
    TRACE_CONTEXT.reset(token)


def format_traceparent(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id or _NULL_SPAN}-01"


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """``00-<32hex>-<16hex>-<2hex>`` -> anchor context (the remote span
    becomes the local parent).  Anything malformed is ignored — a bad
    header must never fail a request, it just starts a fresh trace."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or version == "ff":
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32:
        return None
    if span_id == _NULL_SPAN:
        span_id = None
    return TraceContext(trace_id, span_id, None)


# -- stitching -------------------------------------------------------------


def stitch_spans(spans: List[Dict[str, Any]]) -> Tuple[
        List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Order trace fragments from many nodes into one tree.

    ``spans`` are exported trace records (each node's ``t_unix`` already
    comes off its own monotonic+wall anchor pair, so wall ordering is
    the cross-node skew normalization).  Returns ``(ordered, roots)``:
    the flat list sorted by ``(t_unix, seq)``, and a nested tree where
    each node is ``{**span, "children": [...]}``; a span whose parent is
    not in the set (a virtual mint anchor, or a fragment lost to a dead
    peer) becomes a root."""
    ordered = sorted(spans, key=lambda r: (r.get("t_unix", 0.0),
                                           r.get("seq", 0)))
    by_id: Dict[str, Dict[str, Any]] = {}
    nodes: List[Dict[str, Any]] = []
    for rec in ordered:
        node = dict(rec)
        node["children"] = []
        nodes.append(node)
        sid = rec.get("span_id")
        if sid and sid not in by_id:
            by_id[sid] = node
    roots: List[Dict[str, Any]] = []
    for node in nodes:
        parent = by_id.get(node.get("parent_span_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return ordered, roots
