"""Counters, gauges, and fixed-bucket histograms with a Prometheus
text-format renderer — the serve stack's metrics channel.

Stdlib-only and deliberately tiny: the serving stack needs exactly three
instrument kinds (the ones every production inference server is tuned
off — Orca-style occupancy/latency histograms, breaker/cache gauges,
dispatch counters), not a client-library dependency.  All mutation goes
through one registry lock; ``observe``/``inc`` are a dict lookup plus an
integer bump (~1 µs), cheap enough for the step hot path, and rendering
walks the registry only at scrape time (``GET /metrics``).

Two callback flavors (``gauge_fn``/``counter_fn``) evaluate at scrape
time instead of being pushed: values that already live somewhere
authoritative (engine compile counters, breaker states, queue depth)
must not be shadow-counted — double bookkeeping is how metrics drift
from the truth they claim to report.  Registration is idempotent by
name so re-binding a manager to a registry never raises.

Histogram buckets are FIXED at creation (cumulative ``le`` semantics,
``+Inf`` implied): fixed buckets make ``observe`` O(log n_buckets) with
zero allocation, and bucket counts are monotone by construction — the
property ``tests/test_obs.py`` asserts on the rendered text.

Histograms additionally keep the last trace context seen per bucket as
an OpenMetrics *exemplar* (``# {trace_id="..."} value timestamp`` after
the ``_bucket`` sample).  Exemplars are rendered ONLY when the scraper
negotiates ``Accept: application/openmetrics-text`` — the default
Prometheus text stays byte-identical whether or not any were captured,
and capture itself costs one ``ContextVar`` read (a no-op store when
the observe happens outside a traced request).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from mpi_tpu.obs.tracectx import current_trace_context

# Dispatch/request latencies: 0.5 ms (CPU dispatch floor) up to 10 s
# (a watchdogged hang) — PERF.md's ~68 ms TPU tunnel constant sits
# mid-range where the resolution is finest.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
# Batch occupancy B: bounded by --batch-max (default 8), headroom to 32.
OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)
# XLA/Mosaic compile wall: ~10 ms warm-cache reloads to multi-minute
# cold sharded compiles (PERF.md's compile-wall artifact).
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0)
# Checkpoint/restore file+replay work: sub-ms JSON rewrites to
# multi-second replays.
IO_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats minimally."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _labels_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _exemplar_str(ex: Optional[tuple]) -> str:
    """OpenMetrics exemplar suffix for a ``_bucket`` sample: the last
    traced observation that landed in the bucket, or nothing."""
    if ex is None:
        return ""
    trace_id, value, ts = ex
    return (f' # {{trace_id="{_escape(trace_id)}"}} '
            f"{_fmt(value)} {ts:.3f}")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, lock: threading.Lock):
        self.name = name
        self.help = help_
        self._lock = lock
        # registry-wide constant labels (cluster mode's host/process),
        # prepended to every rendered sample; () renders nothing — the
        # single-process text format is byte-identical
        self.const: Tuple[Tuple[str, str], ...] = ()

    def _header(self) -> List[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.kind}"]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, lock):
        super().__init__(name, help_, lock)
        self._vals: Dict[tuple, float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        k = _key(labels)
        with self._lock:
            self._vals[k] = self._vals.get(k, 0.0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_key(labels), 0.0)

    def total(self, where: Optional[Callable[[dict], bool]] = None) -> float:
        """Sum across label sets, optionally filtered by a predicate over
        the labels dict — the telemetry sampler's counter readout."""
        with self._lock:
            if where is None:
                return sum(self._vals.values())
            return sum(v for k, v in self._vals.items() if where(dict(k)))

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._vals.items())
        for k, v in items:
            out.append(f"{self.name}{_labels_str(self.const + k)} {_fmt(v)}")
        if not items:
            out.append(f"{self.name}{_labels_str(self.const)} 0")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._vals[_key(labels)] = float(value)


class _FnMetric(_Metric):
    """Scrape-time callback metric.  ``fn`` returns a number or a list of
    ``(labels_dict, value)`` pairs; a raising callback renders nothing —
    a scrape must never 500 because one provider hiccuped."""

    def __init__(self, name, help_, lock, fn: Callable, kind: str):
        super().__init__(name, help_, lock)
        self._fn = fn
        self.kind = kind

    def read_sum(self) -> Optional[float]:
        """Evaluate the callback now and collapse it to one number (label
        sets summed); ``None`` when the provider raises — same tolerance
        as ``render``.  Used by the telemetry sampler, never by scrapes."""
        try:
            val = self._fn()
        except Exception:  # noqa: BLE001 — same contract as render()
            return None
        if isinstance(val, (int, float)):
            return float(val)
        try:
            return float(sum(v for _, v in val))
        except Exception:  # noqa: BLE001
            return None

    def render(self) -> List[str]:
        try:
            val = self._fn()
        except Exception:  # noqa: BLE001 — scrape survives a sick provider
            return []
        out = self._header()
        if isinstance(val, (int, float)):
            out.append(f"{self.name}{_labels_str(self.const)} "
                       f"{_fmt(float(val))}")
        else:
            for labels, v in val:
                out.append(
                    f"{self.name}{_labels_str(self.const + _key(labels))} "
                    f"{_fmt(float(v))}")
        return out


class _BoundSeries:
    """A histogram series pre-resolved to its label set — the hot-path
    handle.  ``observe`` skips the per-call kwargs dict and label-key
    sort (the expensive half of a labeled observe), leaving lock +
    bisect + three increments (~0.6 µs)."""

    __slots__ = ("_lock", "_buckets", "_st")

    def __init__(self, lock, buckets, st):
        self._lock = lock
        self._buckets = buckets
        self._st = st

    def observe(self, value: float) -> None:
        ctx = current_trace_context()
        with self._lock:
            st = self._st
            i = bisect.bisect_left(self._buckets, value)
            st[0][i] += 1
            st[1] += value
            st[2] += 1
            if ctx is not None:
                st[3][i] = (ctx.trace_id, value, time.time())


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, lock, buckets):
        super().__init__(name, help_, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        # label-key -> [per-bucket counts (+1 overflow slot), sum, count,
        #               per-bucket last exemplar (trace_id, value, t) | None]
        self._series: Dict[tuple, list] = {}

    def _new_st(self) -> list:
        n = len(self.buckets) + 1
        return [[0] * n, 0.0, 0, [None] * n]

    def series(self, **labels) -> _BoundSeries:
        """The pre-bound handle for ``labels`` (created empty if new) —
        bind once at wiring time, observe cheaply per step."""
        k = _key(labels)
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = self._new_st()
        return _BoundSeries(self._lock, self.buckets, st)

    def observe(self, value: float, **labels) -> None:
        k = _key(labels)
        ctx = current_trace_context()
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = self._new_st()
            # le semantics: first bound with value <= bound
            i = bisect.bisect_left(self.buckets, value)
            st[0][i] += 1
            st[1] += value
            st[2] += 1
            if ctx is not None:
                st[3][i] = (ctx.trace_id, value, time.time())

    def count(self, **labels) -> int:
        with self._lock:
            st = self._series.get(_key(labels))
            return st[2] if st else 0

    def total_count(self) -> int:
        """Observations across every label set — the sampler's "how many
        dispatches happened" readout."""
        with self._lock:
            return sum(st[2] for st in self._series.values())

    def total_sum(self) -> float:
        """Summed observed values across every label set."""
        with self._lock:
            return float(sum(st[1] for st in self._series.values()))

    def render(self, exemplars: bool = False) -> List[str]:
        out = self._header()
        with self._lock:
            items = [(k, (list(st[0]), st[1], st[2], list(st[3])))
                     for k, st in sorted(self._series.items())]
        for k, (counts, total, n, exs) in items:
            ck = self.const + k
            cum = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                labels = ck + (("le", "%g" % bound),)
                out.append(f"{self.name}_bucket{_labels_str(labels)} {cum}"
                           f"{_exemplar_str(exs[i]) if exemplars else ''}")
            cum += counts[-1]
            out.append(
                f"{self.name}_bucket{_labels_str(ck + (('le', '+Inf'),))} "
                f"{cum}"
                f"{_exemplar_str(exs[-1]) if exemplars else ''}")
            out.append(f"{self.name}_sum{_labels_str(ck)} {_fmt(total)}")
            out.append(f"{self.name}_count{_labels_str(ck)} {n}")
        return out


class MetricsRegistry:
    """Named instruments + the text renderer behind ``GET /metrics``.

    One lock serves every instrument: contention is negligible (scrapes
    are rare, mutations are sub-µs) and a single lock cannot deadlock.
    Re-registering a name returns the existing instrument when the kind
    matches (idempotent binding) and replaces it otherwise.
    """

    def __init__(self, const_labels: Optional[dict] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._const: Tuple[Tuple[str, str], ...] = _key(const_labels or {})

    def set_const_labels(self, labels: Optional[dict]) -> None:
        """(Re)set the constant labels stamped on every rendered sample
        — cluster mode sets ``host``/``process`` here after the serving
        socket binds.  Single-process serving never calls this, keeping
        the text format byte-identical to the non-cluster build."""
        const = _key(labels or {})
        with self._lock:
            self._const = const
            for m in self._metrics.values():
                m.const = const

    def _register(self, cls, name, help_, *args):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None and type(existing) is cls \
                    and not issubclass(cls, _FnMetric):
                return existing
        m = cls(name, help_, self._lock, *args)
        with self._lock:
            m.const = self._const
            self._metrics[name] = m
        return m

    def counter(self, name: str, help_: str) -> Counter:
        return self._register(Counter, name, help_)

    def gauge(self, name: str, help_: str) -> Gauge:
        return self._register(Gauge, name, help_)

    def histogram(self, name: str, help_: str,
                  buckets=LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_, buckets)

    def gauge_fn(self, name: str, help_: str, fn: Callable) -> None:
        self._register(_FnMetric, name, help_, fn, "gauge")

    def counter_fn(self, name: str, help_: str, fn: Callable) -> None:
        self._register(_FnMetric, name, help_, fn, "counter")

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition; ``openmetrics=True`` is the
        negotiated variant that appends histogram exemplars and the
        ``# EOF`` terminator.  The default render path is untouched by
        exemplar capture — byte-identical to pre-exemplar builds."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            if openmetrics and isinstance(m, Histogram):
                lines.extend(m.render(exemplars=True))
            else:
                lines.extend(m.render())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"
