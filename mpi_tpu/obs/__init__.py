"""``mpi_tpu.obs`` — tracing, metrics, and profiling for the serve stack.

One :class:`Obs` object bundles the three channels the ISSUE-4 tentpole
names and is threaded through the layers as a single optional handle
(``SessionManager(obs=...)`` → batcher, engines, recovery, httpd):

* **spans/events** (:mod:`.trace`) — a request's lifecycle, end-to-end
  by shared request id: HTTP parse → session lock wait → batch window →
  ``ensure_compiled`` → device dispatch → ``block_until_ready`` →
  checkpoint write.  Ring-buffered always; streamed as JSONL with
  ``--trace-log``; dumped on any 500.
* **metrics** (:mod:`.metrics`) — push-style histograms/counters for the
  hot-path quantities (dispatch latency, batch occupancy, compile wall,
  checkpoint/restore time) plus scrape-time callbacks over state that
  already lives elsewhere (breaker/cache/queue/engine counters), all
  rendered as Prometheus text on ``GET /metrics``.
* **profiling** (:mod:`.profile`) — ``POST /debug/profile`` device
  traces and the compile-vs-execute regime breakdown on ``/stats``.

``obs=None`` everywhere means OFF: every instrumentation site guards on
the handle, so the uninstrumented path is the pre-PR-4 code path —
bit-identical results, no added syncs (``bench.py --serve-obs`` measures
the instrumented delta and holds it under 2%).
"""

from __future__ import annotations

from typing import Optional

from mpi_tpu.obs.ledger import UsageLedger
from mpi_tpu.obs.metrics import (
    COMPILE_BUCKETS, IO_BUCKETS, LATENCY_BUCKETS, OCCUPANCY_BUCKETS,
    MetricsRegistry,
)
from mpi_tpu.obs.trace import (
    Tracer, current_request_id, reset_request_id, set_request_id,
)

__all__ = [
    "Obs", "Tracer", "MetricsRegistry",
    "current_request_id", "set_request_id", "reset_request_id",
]


class Obs:
    """The observability bundle: one tracer + one metrics registry with
    the serve stack's instruments pre-registered (so every layer pokes
    attributes instead of re-declaring names, and `/metrics` has a
    stable schema whether or not traffic has touched a site yet)."""

    def __init__(self, trace_capacity: int = 4096,
                 trace_log: Optional[str] = None,
                 instance: Optional[dict] = None):
        self.tracer = Tracer(capacity=trace_capacity, log_path=trace_log)
        # ``instance`` (cluster mode's host/process identity) becomes
        # constant labels on every rendered sample; None renders nothing
        self.metrics = MetricsRegistry(const_labels=instance)
        # per-session/per-signature usage accounting (obs/ledger.py),
        # fed at the dispatch commit sites; process-local by design
        self.ledger = UsageLedger()
        m = self.metrics
        self.dispatch_latency = m.histogram(
            "mpi_tpu_dispatch_latency_seconds",
            "Device step wall time per call (mode=solo|batched|host)",
            LATENCY_BUCKETS)
        self.batch_occupancy = m.histogram(
            "mpi_tpu_batch_occupancy_boards",
            "Boards per coalesced step dispatch (B)",
            OCCUPANCY_BUCKETS)
        self.compile_wall = m.histogram(
            "mpi_tpu_compile_wall_seconds",
            "Wall time of each real XLA/Mosaic compile",
            COMPILE_BUCKETS)
        self.checkpoint_write = m.histogram(
            "mpi_tpu_checkpoint_write_seconds",
            "Session record write time (tmp+fsync+rename)",
            IO_BUCKETS)
        self.restore_replay = m.histogram(
            "mpi_tpu_restore_replay_seconds",
            "Per-session restore time (rebuild + deterministic replay)",
            IO_BUCKETS)
        self.lock_wait = m.histogram(
            "mpi_tpu_session_lock_wait_seconds",
            "Time a step spent waiting on its session lock",
            LATENCY_BUCKETS)
        self.http_requests = m.counter(
            "mpi_tpu_http_requests_total",
            "HTTP requests by method and status code")
        self.http_bytes_in = m.counter(
            "mpi_tpu_http_bytes_in_total",
            "Request body bytes read, by transport front")
        self.http_bytes_out = m.counter(
            "mpi_tpu_http_bytes_out_total",
            "Response body bytes written, by transport front")
        self.wire_encode = m.histogram(
            "mpi_tpu_wire_encode_seconds",
            "Grid payload encode wall (format=json|binary) per transport",
            IO_BUCKETS)
        self.wire_decode = m.histogram(
            "mpi_tpu_wire_decode_seconds",
            "Grid payload decode wall (format=json|binary) per transport",
            IO_BUCKETS)
        self.engine_failures = m.counter(
            "mpi_tpu_engine_failures_observed_total",
            "Engine dispatch failures seen by the step path")
        # viewport/sharded serving (ISSUE 20): windowed reads, dirty-tile
        # delta streams, per-shard device transfers
        self.viewport_bytes = m.counter(
            "mpi_tpu_viewport_bytes_total",
            "Windowed board-read payload bytes served, by transport front")
        self.delta_frames = m.counter(
            "mpi_tpu_delta_frames_total",
            "Stream frames pushed by kind (kind=key|delta)")
        self.shard_fetch = m.histogram(
            "mpi_tpu_shard_fetch_seconds",
            "Per-device-shard window transfer wall (viewport reads)",
            IO_BUCKETS)
        # pre-bound series handles for the step hot path: observing
        # through these skips the per-call label resolution (~2 µs →
        # ~0.6 µs), and binding them here makes the /metrics schema
        # stable from the first scrape (empty series still render).
        # Step counts are NOT push-counted — the engines' own
        # step_calls/batched_step_calls are scraped at render time
        # (mpi_tpu_engine_counters_total), so the hot path pays nothing
        # for them.
        # telemetry history + SLO engine (ISSUE 15): None until
        # arm_telemetry() — the unarmed scrape/trace stay byte-identical
        self.telemetry = None
        self.slo = None
        # flight recorder + drift detector + devmem sampler (ISSUE 19):
        # None until arm_flight() — same unarmed byte-identity contract
        self.flight = None
        self.anomaly = None
        self.devmem = None
        self.dispatch_solo = self.dispatch_latency.series(mode="solo")
        self.dispatch_batched = self.dispatch_latency.series(mode="batched")
        self.dispatch_host = self.dispatch_latency.series(mode="host")
        # tuned-plan dispatches observe through their own series (an
        # added plan="tuned" label): the existing three keep their exact
        # label sets, so dashboards and tests keyed on them never move
        self.dispatch_solo_tuned = self.dispatch_latency.series(
            mode="solo", plan="tuned")
        self.dispatch_batched_tuned = self.dispatch_latency.series(
            mode="batched", plan="tuned")
        self.occupancy_series = self.batch_occupancy.series()
        self.lock_wait_series = self.lock_wait.series()
        for fmt in ("json", "binary"):
            for front in ("threaded", "aio"):
                self.wire_encode.series(format=fmt, transport=front)
                self.wire_decode.series(format=fmt, transport=front)
        # same schema-stability discipline for the viewport families:
        # both delta kinds render (at 0) from the first scrape
        self.delta_frames.inc(0.0, kind="key")
        self.delta_frames.inc(0.0, kind="delta")
        self.shard_fetch_series = self.shard_fetch.series()

    # -- trace delegates -------------------------------------------------

    def span(self, name: str, **fields):
        return self.tracer.span(name, **fields)

    def event(self, name: str, dur_s: float = 0.0, t0=None, **fields):
        self.tracer.event(name, dur_s, t0, **fields)

    def phase_sink(self):
        """A ``PhaseTimer.span_sink`` callable: each finished phase
        becomes a trace event (name, start perf_counter, duration)."""
        def sink(phase: str, t0: float, dur_s: float) -> None:
            self.tracer.event(f"phase:{phase}", dur_s, t0)
        return sink

    # -- telemetry history + SLO engine (ISSUE 15) -----------------------

    def arm_telemetry(self, interval_s: float = 5.0, manager=None,
                      objectives=None, damp_evals: int = 3,
                      clock=None, start: bool = True):
        """Construct the sampler + SLO engine behind
        ``--telemetry-interval-s``.  Idempotent; ``start=False`` (tests)
        skips the daemon thread so ``sample_once``/``evaluate`` can be
        driven by hand against an injected ``clock``."""
        if self.telemetry is not None:
            return self.telemetry
        from mpi_tpu.obs.slo import SloEngine, default_objectives
        from mpi_tpu.obs.timeseries import TelemetryRecorder

        kw = {} if clock is None else {"clock": clock}
        tel = TelemetryRecorder(self.metrics, interval_s=interval_s, **kw)
        slo = SloEngine(objectives or default_objectives(), tel,
                        manager=manager, obs=self,
                        damp_evals=damp_evals, **kw)
        tel.after_sample = slo.evaluate
        tel.bind_metrics(self.metrics)
        slo.bind_metrics(self.metrics)
        self.telemetry = tel
        self.slo = slo
        if start:
            tel.start()
        return tel

    # -- flight recorder + anomaly profiling (ISSUE 19) ------------------

    def arm_flight(self, capacity: int = 1024, manager=None,
                   anomaly: bool = False,
                   profile_dir: Optional[str] = None,
                   devmem: bool = True, halo_probe: bool = True,
                   clock=None, **anomaly_kw):
        """Construct the per-dispatch flight recorder behind
        ``--flight-recorder`` (plus the drift detector behind
        ``--anomaly-detect`` and, when telemetry is already armed, the
        device-memory sampler).  Idempotent.  Call AFTER
        ``arm_telemetry`` — the devmem sample and the anomaly
        evaluation chain onto the telemetry ticker; without telemetry,
        tests drive ``anomaly.evaluate`` by hand."""
        if self.flight is not None:
            return self.flight
        from mpi_tpu.obs.flight import FlightRecorder

        fl = FlightRecorder(capacity=capacity, obs=self)
        fl.bind_metrics(self.metrics)
        self.flight = fl
        kw = {} if clock is None else {"clock": clock}
        if anomaly:
            from mpi_tpu.obs.anomaly import AnomalyDetector

            an = AnomalyDetector(self, profile_dir=profile_dir,
                                 **kw, **anomaly_kw)
            an.bind_metrics(self.metrics)
            self.anomaly = an
            fl.on_record = an.observe
        tel = self.telemetry
        if tel is not None:
            if devmem:
                from mpi_tpu.obs.devmem import DevMemSampler

                dm = DevMemSampler(self, manager=manager,
                                   halo_probe=halo_probe, **kw)
                dm.bind_metrics(self.metrics)
                self.devmem = dm
                tel.add_series("device_memory_bytes", "gauge",
                               dm.memory_total)
                if manager is not None:
                    tel.add_series(
                        "engine_cache_entries", "gauge",
                        lambda: (lambda st: st["size"]
                                 + st["batched"]["size"])(
                                     manager.cache.stats()))
            prev = tel.after_sample
            dm_, an_ = self.devmem, self.anomaly

            def _chain(now):
                if prev is not None:
                    prev(now)
                if dm_ is not None:
                    dm_.sample(now)
                if an_ is not None:
                    an_.evaluate(now)

            tel.after_sample = _chain
        return fl

    # -- manager binding -------------------------------------------------

    def bind_manager(self, manager) -> None:
        """Register scrape-time callbacks over the manager's live state.
        Idempotent (re-binding replaces the callbacks); values are READ
        at scrape time from their authoritative owners, never shadowed."""
        from mpi_tpu.obs.profile import _live_engines

        m = self.metrics
        cache = manager.cache

        m.gauge_fn("mpi_tpu_sessions", "Live sessions", lambda: len(manager))
        m.gauge_fn(
            "mpi_tpu_degraded_sessions",
            "Sessions currently served by the serial_np fallback",
            lambda: sum(1 for s in manager._session_list() if s.degraded))
        m.counter_fn(
            "mpi_tpu_degraded_sessions_total",
            "Sessions ever degraded to the serial_np fallback",
            lambda: manager.degraded_total)
        m.counter_fn(
            "mpi_tpu_engine_failures_total",
            "Engine dispatch failures (manager's authoritative count)",
            lambda: manager.engine_failures)
        m.counter_fn(
            "mpi_tpu_watchdog_timeouts_total",
            "Dispatches abandoned to the watchdog",
            lambda: manager.watchdog_timeouts)

        def _breaker_states():
            br = cache.breaker_stats()
            return [({"state": "open"}, len(br["open"])),
                    ({"state": "half_open"}, len(br["half_open"]))]

        m.gauge_fn("mpi_tpu_breaker_signatures",
                   "Plan signatures per breaker state", _breaker_states)
        m.counter_fn("mpi_tpu_breaker_trips_total",
                     "Times any signature's breaker opened",
                     lambda: cache.breaker_stats()["trips"])

        def _cache_events():
            st = cache.stats()
            return [({"cache": "engine", "event": k}, st[k])
                    for k in ("hits", "misses", "evictions")] + \
                   [({"cache": "batched", "event": k}, st["batched"][k])
                    for k in ("hits", "misses", "evictions")]

        m.counter_fn("mpi_tpu_cache_events_total",
                     "Engine/batched-stepper cache hits, misses, evictions",
                     _cache_events)
        m.gauge_fn("mpi_tpu_cache_size", "Cached compiled engines",
                   lambda: len(cache))

        def _engine_counters():
            engines = _live_engines(manager)
            return [
                ({"kind": "compiles"},
                 sum(e.compile_count for e in engines)),
                ({"kind": "batched_compiles"},
                 sum(e.batched_compile_count for e in engines)),
                ({"kind": "step_calls"},
                 sum(e.step_calls for e in engines)),
                ({"kind": "batched_step_calls"},
                 sum(e.batched_step_calls for e in engines)),
            ]

        m.counter_fn("mpi_tpu_engine_counters_total",
                     "Engine compile and dispatch counters (all engines)",
                     _engine_counters)
        m.gauge_fn("mpi_tpu_engine_compile_wall_seconds_total",
                   "Accumulated XLA compile wall across engines",
                   lambda: sum(getattr(e, "compile_wall_s", 0.0)
                               for e in _live_engines(manager)))

        if manager.batcher is not None:
            m.gauge_fn("mpi_tpu_batch_queue_depth",
                       "Step requests waiting in coalescing queues",
                       manager.batcher.queue_depth)

        dispatcher = getattr(manager, "dispatcher", None)
        if dispatcher is not None:
            # scrape-time callbacks over the dispatcher's authoritative
            # queue state — same no-shadow-counting rule as everything
            # else here; values match /stats' "async" section exactly
            m.gauge_fn("mpi_tpu_ticket_queue_depth",
                       "Async tickets waiting for the dispatch loop",
                       dispatcher.queue_depth)
            m.gauge_fn("mpi_tpu_tickets_pending",
                       "Async tickets enqueued but not yet resolved",
                       dispatcher.pending)
            m.counter_fn("mpi_tpu_tickets_completed_total",
                         "Async tickets resolved (done or error)",
                         lambda: dispatcher.tickets_completed)
            m.counter_fn("mpi_tpu_unit_rounds_total",
                         "Depth-1 device rounds executed by the dispatch "
                         "loop (chained, one sync per chain)",
                         lambda: dispatcher.unit_rounds)

        def _cells_per_sec():
            out = []
            for s in manager._session_list():
                tp = s.throughput()
                if tp["cell_updates_per_s"]:
                    out.append(({"session": s.id}, tp["cell_updates_per_s"]))
            return out

        m.gauge_fn("mpi_tpu_session_cells_per_second",
                   "Per-session steady-state cell updates per second",
                   _cells_per_sec)

        def _sparse_series(field):
            # scrape-time readout of each sparse session's dirty map; a
            # concurrent step may have donated the grid buffer out from
            # under us (Array deleted) — skip that session this scrape
            out = []
            for s in manager._session_list():
                eng = s.engine
                if eng is None or getattr(eng, "sparse_plan", None) is None:
                    continue
                try:
                    sa = eng.sparse_stats(s.grid)
                except Exception:
                    continue
                out.append(({"session": s.id}, sa[field]))
            return out

        m.gauge_fn("mpi_tpu_active_tiles",
                   "Dirty tiles the next sparse step must compute",
                   lambda: _sparse_series("active_tiles"))
        m.gauge_fn("mpi_tpu_active_fraction",
                   "Active fraction of the sparse tile map (0-1)",
                   lambda: _sparse_series("active_fraction"))
        m.counter_fn("mpi_tpu_trace_spans_total",
                     "Spans/events recorded by the tracer",
                     lambda: self.tracer.stats()["recorded"])

        # -- usage ledger (ISSUE 10): per-SIGNATURE series only — the
        # per-session rows stay on /usage so scrape cardinality is
        # bounded by distinct plans, never by tenant count
        ledger = self.ledger

        m.counter_fn("mpi_tpu_usage_device_seconds_total",
                     "Committed device sync wall per plan signature",
                     lambda: ledger.signature_series("device_s"))
        m.counter_fn("mpi_tpu_usage_syncs_total",
                     "Committed dispatches (device syncs) per plan "
                     "signature",
                     lambda: ledger.signature_series("syncs"))
        m.counter_fn("mpi_tpu_usage_generations_total",
                     "Generations advanced per plan signature",
                     lambda: ledger.signature_series("generations"))
        m.counter_fn("mpi_tpu_usage_cells_total",
                     "Cell-updates served per plan signature",
                     lambda: ledger.signature_series("cells"))
        m.counter_fn("mpi_tpu_usage_flops_total",
                     "Cost-card-derived FLOPs served per plan signature",
                     lambda: ledger.signature_series("flops"))

        def _cost_card_counts():
            counts = {"xla": 0, "opcount": 0}
            for e in _live_engines(manager):
                for c in e.cost_cards():
                    counts[c.source] = counts.get(c.source, 0) + 1
            return [({"source": k}, v) for k, v in counts.items()]

        m.gauge_fn("mpi_tpu_cost_cards",
                   "Captured executable cost cards by capture source",
                   _cost_card_counts)

        def _tuned_plans():
            counts = {"tuned": 0, "default": 0}
            for e in _live_engines(manager):
                k = "tuned" if getattr(e, "tuned_plan", None) else "default"
                counts[k] += 1
            return [({"plan": k}, v) for k, v in counts.items()]

        m.gauge_fn("mpi_tpu_tuned_plans",
                   "Live engines by plan provenance (tune-cache winner "
                   "applied vs default build)",
                   _tuned_plans)

        def _roofline_efficiency():
            # achieved cells/s (ledger) over the cost-model bound (the
            # captured cards' trip-count-safe ops/cell into the roof),
            # per live signature — computed at scrape time
            from mpi_tpu.obs.cost import (
                ops_per_cell_estimate, roof_ops_per_s,
            )

            roof = roof_ops_per_s()
            rows = ledger.signature_rows()
            out = []
            seen = set()
            for e in _live_engines(manager):
                label = getattr(e, "sig_label", None)
                if label is None or label in seen:
                    continue
                seen.add(label)
                row = rows.get(label)
                if not row or row["device_s"] <= 0:
                    continue
                opc = ops_per_cell_estimate(e.cost_cards(), e.config.cells)
                if opc is None:
                    continue
                bound = roof / opc
                out.append(({"sig": label},
                            (row["cells"] / row["device_s"]) / bound))
            return out

        m.gauge_fn("mpi_tpu_roofline_efficiency",
                   "Achieved cells/s over the cost-model roofline bound, "
                   "per plan signature",
                   _roofline_efficiency)

        # -- durable state plane (ISSUE 18): scrape-time readouts of the
        # StateStore's authoritative counters and state machine.  The
        # families are always present — a server without --state-dir
        # scrapes zeros/closed rather than dropping them, so dashboards
        # and the required-family gate see one stable schema.
        store = getattr(manager, "store", None)
        m.counter_fn(
            "mpi_tpu_checkpoint_bytes_total",
            "Durable bytes written, by form (full record envelopes "
            "vs appended journal entries)",
            lambda: [({"kind": "full"}, store.bytes_full if store else 0),
                     ({"kind": "delta"},
                      store.bytes_delta if store else 0)])
        m.counter_fn(
            "mpi_tpu_state_records_corrupt_total",
            "Persisted records quarantined for failing CRC/envelope "
            "validation at restore or adoption",
            lambda: store.corrupt_records if store else 0)
        m.gauge_fn(
            "mpi_tpu_persistence_state",
            "Persistence state machine: 0 closed (healthy), "
            "1 recovering (flushing backlog), 2 degraded",
            lambda: ({"closed": 0, "recovering": 1, "degraded": 2}
                     [store.persistence_state()["state"]] if store else 0))
        m.counter_fn(
            "mpi_tpu_journal_compactions_total",
            "Session journals compacted into a full record write",
            lambda: store.compactions if store else 0)

    # -- export ----------------------------------------------------------

    def render_metrics(self, openmetrics: bool = False) -> str:
        return self.metrics.render(openmetrics=openmetrics)

    def stats(self) -> dict:
        out = {"trace": self.tracer.stats()}
        if self.telemetry is not None:
            out["telemetry"] = self.telemetry.stats()
        if self.flight is not None:
            out["flight"] = self.flight.stats()
        if self.anomaly is not None:
            out["anomaly"] = self.anomaly.stats()
        if self.devmem is not None:
            out["devmem"] = self.devmem.stats()
        return out

    def close(self) -> None:
        if self.telemetry is not None:
            self.telemetry.stop()
        self.tracer.close()
