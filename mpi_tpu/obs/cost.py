"""Cost cards: a per-executable arithmetic cost model for the serve
stack (ISSUE 10 tentpole).

When an :class:`~mpi_tpu.backends.tpu.Engine` compiles a stepper (a real
miss under ``_compile_lock``), the serve layer captures one
:class:`CostCard` from the compiled artifact — XLA's
``cost_analysis()``/``memory_analysis()`` where the backend reports
them, falling back to counting ALU lane-ops in the traced jaxpr
(:mod:`mpi_tpu.obs.opcount`) where it does not (``source`` records
which).  Cards are keyed per (plan signature, depth, B): the engine IS
the signature (one compiled engine per :func:`~mpi_tpu.config.plan_signature`),
so the engine owns its cards and the ledger/`/usage` join them back to
signature rows at read time.

Capture only READS compiled artifacts and traced jaxprs — it never
changes what gets traced or lowered, so the IR verifier's baselines and
``--no-obs`` bit-identity are untouched.

Units, stated so the numbers read honestly: XLA's ``flops`` field counts
classic floating/integer ops; the opcount fallback counts VPU lane-ops
(the roofline currency).  For the bit-packed engines these agree to
within the SWAR packing factor; every consumer carries ``source`` so the
two are never silently mixed across a comparison.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Optional

# the measured VPU u32 lane-op throughput roof (perf/profile_ladder_g8
# chain measurement; tools/roofline.py --roof default).  The live
# roofline-efficiency gauge divides by this unless the server was given
# a measured roof for its actual device.
DEFAULT_ROOF_OPS_PER_S = 1.95e12


def roof_ops_per_s() -> float:
    """The ops/s roof the live roofline-efficiency readout divides by:
    ``MPI_TPU_ROOF_OPS_PER_S`` when set (a roof measured for THIS box,
    e.g. ``tools/roofline.py --measure-roof``), else the committed TPU
    chain measurement — on XLA:CPU the gauge then reads as 'fraction of
    the flagship TPU roof', which is the honest cross-platform number."""
    import os

    raw = os.environ.get("MPI_TPU_ROOF_OPS_PER_S")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_ROOF_OPS_PER_S


@dataclass(frozen=True)
class CostCard:
    """The arithmetic price of ONE execution of a compiled stepper."""

    sig_label: str              # compact plan tag (serve/cache.signature_label)
    depth: int                  # generations advanced per execution (n)
    batch: int                  # stacked boards (B); 0 = the solo executable
    flops: float                # est. FLOPs (or lane-ops, see source)
    bytes_accessed: float       # est. HBM bytes touched (0 if unreported)
    peak_memory_bytes: float    # arg + output + temp of the executable
    code_size_bytes: float      # generated code size (0 if unreported)
    source: str                 # "xla" | "opcount"

    @property
    def boards(self) -> int:
        """Boards advanced per execution (the solo executable runs 1)."""
        return self.batch if self.batch else 1

    def ops_per_cell(self, cells: int) -> float:
        """flops normalized per cell-update of one execution."""
        denom = float(cells) * max(self.depth, 1) * self.boards
        return self.flops / denom if denom else 0.0

    def as_dict(self) -> dict:
        return asdict(self)


def ops_per_cell_detail(cards, cells: int):
    """``(estimate, trip_count_suspect)`` for one signature's captured
    cards.  Depth-1 executables are preferred: XLA:CPU's
    ``cost_analysis`` counts a while-loop body ONCE, so depth>1
    programs under-report by their trip count; the depth-1 program has
    no loop to miscount.  When only depth>1 cards carry flops the min
    is still returned — but flagged ``trip_count_suspect=True`` instead
    of silently under-reporting (the opcount fallback recurses into
    loop bodies without multiplying by trip count either, so the flag
    applies to both sources).  ``(None, False)`` when no card carries
    flops."""
    vals = [c.ops_per_cell(cells) for c in cards if c.flops > 0]
    depth1 = [c.ops_per_cell(cells) for c in cards
              if c.flops > 0 and c.depth == 1]
    if depth1:
        return min(depth1), False
    if vals:
        return min(vals), True
    return None, False


def ops_per_cell_estimate(cards, cells: int):
    """The bare estimate (see :func:`ops_per_cell_detail`; callers that
    must distinguish a trip-count-suspect depth>1-only estimate use the
    detail form — ``/usage`` surfaces the flag)."""
    return ops_per_cell_detail(cards, cells)[0]


def _first_analysis(compiled):
    """``cost_analysis()`` returns a dict on new jaxlibs, a per-device
    list of dicts on the ones shipped here — normalize to one dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def capture_card(compiled, *, sig_label: Optional[str], depth: int,
                 batch: int, trace_thunk=None) -> CostCard:
    """Best-effort CostCard for a just-compiled executable.

    ``trace_thunk`` (optional) retraces the stepper and returns its
    closed jaxpr; it is only called when XLA reports no flops (the
    XLA:CPU builds here DO report them, but the field is not contractual
    across backends).  Raises only if both channels fail AND no thunk
    was given — callers treat any exception as "no card".
    """
    flops = bytes_accessed = None
    try:
        ca = _first_analysis(compiled)
        f = float(ca.get("flops", 0.0) or 0.0)
        if f > 0.0:
            flops = f
        b = float(ca.get("bytes accessed", 0.0) or 0.0)
        if b > 0.0:
            bytes_accessed = b
    except Exception:  # noqa: BLE001 — analysis support varies by backend
        pass
    peak = code = 0.0
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            peak = float(getattr(ma, "argument_size_in_bytes", 0)
                         + getattr(ma, "output_size_in_bytes", 0)
                         + getattr(ma, "temp_size_in_bytes", 0))
            code = float(getattr(ma, "generated_code_size_in_bytes", 0))
    except Exception:  # noqa: BLE001
        pass
    source = "xla"
    if flops is None:
        if trace_thunk is None:
            raise ValueError("cost_analysis reported no flops and no "
                             "trace_thunk was given")
        from mpi_tpu.obs.opcount import count_ops

        flops = count_ops(trace_thunk())
        source = "opcount"
    return CostCard(sig_label=sig_label or "unkeyed", depth=int(depth),
                    batch=int(batch), flops=float(flops),
                    bytes_accessed=float(bytes_accessed or 0.0),
                    peak_memory_bytes=peak, code_size_bytes=code,
                    source=source)
