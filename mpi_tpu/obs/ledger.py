"""The usage ledger: per-session and per-signature device-time metering
(ISSUE 10 tentpole).

One :class:`UsageLedger` hangs off the :class:`~mpi_tpu.obs.Obs` handle
and is fed at the same commit sites that emit the dispatch trace events
(``device_dispatch`` in the solo step path, ``batched_dispatch`` in the
microbatch leader, ``unit_round`` in the async dispatch loop,
``host_step`` on the serial fallback).  ``--no-obs`` means no ledger —
the step paths stay bit-identical to the pre-obs code.

Attribution rules (the tests in ``tests/test_usage.py`` hold them):

* one committed device sync = one :meth:`record` call, with the WHOLE
  sync's wall time (``t2 - t1``) — total device-seconds therefore
  reconcile exactly with the sum of dispatch-event durations;
* a batched dispatch splits that wall time EVENLY across its riders and
  records the amortization factor (rider shares sum to the leader's
  block time by construction);
* a failed batched/group attempt commits nothing here — the solo
  fallback re-enters the solo path, which records its own sync, so a
  fallback rider is never double-counted;
* an async unit-round chain is ONE sync (one ``block_until_ready`` per
  chain), however many depth-1 rounds it stacked;
* the ledger is PROCESS-LOCAL: restore-from-checkpoint replays grids,
  not spend — a restart starts metering from zero (documented in the
  README's cardinality/persistence policy).

FLOP attribution is cost-card-derived (``obs/cost.py``): callers pass
each rider's share, already amortized, so the ledger never needs to see
an engine.
"""

from __future__ import annotations

import threading

KINDS = ("solo", "batched", "unit", "host")


def merge_totals(totals_list) -> dict:
    """Exact sum of N :meth:`UsageLedger.totals` payloads — the cluster
    roll-up arithmetic (``GET /usage``'s ``cluster.totals``).  Each
    input is a *cumulative* snapshot, so callers sum the LATEST snapshot
    per node, never deltas: re-merging after a duplicate or late gossip
    digest is idempotent by construction.  Integer fields stay exact
    integers; unknown ``by_kind`` keys are carried through (a newer
    peer's kinds must not be silently dropped)."""
    out = {"syncs": 0, "device_s": 0.0, "host_s": 0.0, "generations": 0,
           "cells": 0, "flops": 0.0, "by_kind": {k: 0 for k in KINDS}}
    for totals in totals_list:
        if not totals:
            continue
        out["syncs"] += int(totals.get("syncs", 0))
        out["device_s"] += float(totals.get("device_s", 0.0))
        out["host_s"] += float(totals.get("host_s", 0.0))
        out["generations"] += int(totals.get("generations", 0))
        out["cells"] += int(totals.get("cells", 0))
        out["flops"] += float(totals.get("flops", 0.0))
        for kind, count in (totals.get("by_kind") or {}).items():
            out["by_kind"][kind] = out["by_kind"].get(kind, 0) + int(count)
    return out


def _row():
    return {
        "device_s": 0.0,            # this row's share of engine sync wall
        "host_s": 0.0,              # serial_np fallback wall (not device)
        "dispatches": {k: 0 for k in KINDS},
        "generations": 0,
        "cells": 0,                 # cell-updates advanced
        "flops": 0.0,               # cost-card-derived share
        "rides": 0,                 # participations in B>1 syncs
        "boards": 0,                # sum of B over those rides
    }


def _finish(row: dict) -> dict:
    out = dict(row, dispatches=dict(row["dispatches"]))
    out["mean_amortization"] = (row["boards"] / row["rides"]
                                if row["rides"] else 1.0)
    return out


class UsageLedger:
    """Thread-safe usage accumulator (the dispatch sites run on HTTP
    handler threads, the batch leader, and the async dispatch loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sessions = {}         # sid -> row
        self._signatures = {}       # sig_label -> row (+ "syncs")
        self.syncs = 0              # committed device syncs (host included)
        self.device_s = 0.0
        self.host_s = 0.0
        self.generations = 0
        self.cells = 0
        self.flops = 0.0
        self.by_kind = {k: 0 for k in KINDS}
        # post-dispatch settlement hook (admission control's quota gate):
        # called OUTSIDE the ledger lock with (kind, dur_s, riders) after
        # every committed sync.  None (the default) costs one attribute
        # read — unarmed behavior is unchanged.
        self.settle_hook = None

    def record(self, kind: str, sig_label, dur_s: float, riders) -> None:
        """One committed sync.  ``riders`` is a sequence of
        ``(sid, generations, cells_advanced, flops_share)``; ``dur_s``
        is the whole sync's wall and is split evenly across them."""
        if kind not in self.by_kind:
            raise ValueError(f"unknown dispatch kind {kind!r}")
        riders = list(riders)
        if not riders:
            return
        share = dur_s / len(riders)
        label = sig_label or "-"
        time_key = "host_s" if kind == "host" else "device_s"
        with self._lock:
            self.syncs += 1
            self.by_kind[kind] += 1
            if kind == "host":
                self.host_s += dur_s
            else:
                self.device_s += dur_s
            sig = self._signatures.setdefault(label, dict(_row(), syncs=0))
            sig["syncs"] += 1
            sig[time_key] += dur_s
            sig["dispatches"][kind] += 1
            if len(riders) > 1:
                sig["rides"] += 1
                sig["boards"] += len(riders)
            for sid, gens, cells, flops in riders:
                self.generations += gens
                self.cells += cells
                self.flops += flops
                sig["generations"] += gens
                sig["cells"] += cells
                sig["flops"] += flops
                row = self._sessions.setdefault(sid, _row())
                row[time_key] += share
                row["dispatches"][kind] += 1
                row["generations"] += gens
                row["cells"] += cells
                row["flops"] += flops
                if len(riders) > 1:
                    row["rides"] += 1
                    row["boards"] += len(riders)
        hook = self.settle_hook
        if hook is not None:
            hook(kind, dur_s, riders)

    # -- read side (usage endpoint, describe/stats, scrape callbacks) -----

    def totals(self) -> dict:
        with self._lock:
            return {
                "syncs": self.syncs,
                "device_s": self.device_s,
                "host_s": self.host_s,
                "generations": self.generations,
                "cells": self.cells,
                "flops": self.flops,
                "by_kind": dict(self.by_kind),
            }

    def session_row(self, sid: str):
        with self._lock:
            row = self._sessions.get(sid)
            return _finish(row) if row is not None else None

    def session_rows(self) -> dict:
        with self._lock:
            return {sid: _finish(row)
                    for sid, row in self._sessions.items()}

    def signature_rows(self) -> dict:
        with self._lock:
            return {label: _finish(row)
                    for label, row in self._signatures.items()}

    def signature_series(self, field: str):
        """Per-signature label series for a scrape-time counter/gauge
        callback — bounded cardinality (signatures, never sessions)."""
        with self._lock:
            return [({"sig": label}, row[field])
                    for label, row in self._signatures.items()]
