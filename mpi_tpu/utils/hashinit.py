"""Decomposition-invariant grid initialization.

The reference seeds each MPI rank with ``srand(rank)`` (``main.cpp:70``) and
the serial program with ``srand(seed)`` (``main_serial.cpp:36``), so the
initial state depends on the process count and the two programs never agree
(SURVEY.md §5.8 quirk #4).  This framework replaces sequential libc ``rand``
with a *counter-based* hash keyed on the global cell coordinate: cell (i, j)
is alive iff ``fmix32-chain(seed, i, j) % 3 == 0`` (P(alive) = 1/3, matching
the reference's ``rand() % 3 == 0`` density, ``main.cpp:69-73``).

Because the hash depends only on (seed, global i, global j), every backend —
numpy serial, native C++, single-chip TPU, and any shard of any device mesh —
computes bit-identical initial grids, which is what makes cross-backend
final-grid parity testable.  The native C++ engine implements the same
function; parity tests pin numpy == JAX == C++ equality.

The mixer is murmur3's 32-bit finalizer (public domain), applied twice with
the row/column keys folded in via odd multiplicative constants.
"""

from __future__ import annotations

import numpy as np

# Odd constants: golden-ratio Weyl constant and murmur3 finalizer constants.
_KI = 0x9E3779B1
_KJ = 0x85EBCA77
_M1 = 0x85EBCA6B
_M2 = 0xC2B2AE35


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    """murmur3 32-bit avalanche finalizer on uint32 arrays (wrapping)."""
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(_M1)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(_M2)
    h = h ^ (h >> np.uint32(16))
    return h


def cell_hash_np(seed: int, i: np.ndarray, j: np.ndarray) -> np.ndarray:
    """uint32 hash of (seed, i, j); i/j broadcastable integer arrays."""
    i = i.astype(np.uint32) * np.uint32(_KI)
    j = j.astype(np.uint32) * np.uint32(_KJ)
    h = _fmix32_np(np.uint32(seed) ^ i)
    return _fmix32_np(h ^ j)


def init_tile_np(
    rows: int,
    cols: int,
    seed: int,
    row_offset: int = 0,
    col_offset: int = 0,
) -> np.ndarray:
    """A (rows, cols) uint8 0/1 tile of the global grid starting at
    (row_offset, col_offset).  Decomposition-invariant: stitching tiles of
    any shape reproduces ``init_tile_np(R, C, seed)`` exactly."""
    i = np.arange(row_offset, row_offset + rows, dtype=np.uint32)[:, None]
    j = np.arange(col_offset, col_offset + cols, dtype=np.uint32)[None, :]
    h = cell_hash_np(seed, i, j)
    return (h % np.uint32(3) == 0).astype(np.uint8)


def _fmix32_jnp(h):
    import jax.numpy as jnp

    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(_M1)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(_M2)
    h = h ^ (h >> jnp.uint32(16))
    return h


def init_tile_jnp(
    rows: int,
    cols: int,
    seed: int,
    row_offset=0,
    col_offset=0,
):
    """JAX twin of :func:`init_tile_np`; traceable (offsets may be tracers,
    e.g. derived from ``lax.axis_index`` inside ``shard_map``)."""
    import jax.numpy as jnp

    i = (jnp.uint32(row_offset) + jnp.arange(rows, dtype=jnp.uint32))[:, None]
    j = (jnp.uint32(col_offset) + jnp.arange(cols, dtype=jnp.uint32))[None, :]
    i = i * jnp.uint32(_KI)
    j = j * jnp.uint32(_KJ)
    h = _fmix32_jnp(jnp.uint32(seed) ^ i)
    h = _fmix32_jnp(h ^ j)
    return (h % jnp.uint32(3) == 0).astype(jnp.uint8)
