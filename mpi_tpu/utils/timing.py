"""Three-phase timing + report files — schema-compatible with the
reference's observability channel (SURVEY.md §5.1/§5.5).

The reference brackets ``begin`` / ``check1`` (post-setup) / ``end`` with
``steady_clock`` and emits two append-mode reports from rank 0
(``/root/reference/main.cpp:310-365``): a human-readable
``<name>_detailed.out`` and a 12-column CSV ``<name>_compact.csv``
(``X,Y,#P,{full,nosetup,setup}×{single,avg,sum}``, microseconds).  Sweep
scripts pass ``first != 0`` on the first run to emit the CSV header once
(``run.sh:4-5``).

Kept identical here so existing reference tooling parses our CSVs, with
two deliberate fixes: durations are *labeled* as microseconds (the
reference prints µs with an "ms" suffix, quirk #6), and there is no 1 s
startup sleep polluting setup time (``main.cpp:150``).

On the TPU backend "setup" = mesh construction + XLA compilation (the
compile cache plays the role the reference's MPI topology setup played);
"nosetup" = steady-state stepping, which is what throughput is derived
from: cells/sec = rows·cols·iters / t_nosetup.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

CSV_HEADER = (
    "X,Y,#P,full single,full avg,full sum,nosetup single,nosetup avg,"
    "nosetup sum,setup single ,setup avg ,setup sum \n"
)


@dataclass
class PhaseTimer:
    """start() → [setup work] → setup_done() → [steady work] → finish().

    ``span_sink``: optional ``callable(phase, t_start, dur_s)`` invoked
    at ``finish()`` with the two phases ("setup", "steady") — the
    adapter ``mpi_tpu.obs.Obs.phase_sink`` returns turns them into trace
    events, so one-shot runs land in the same timeline as serve spans.
    """

    t_begin: float = field(default_factory=time.perf_counter)
    t_setup_done: float = 0.0
    t_end: float = 0.0
    span_sink: object = None

    def restart(self) -> None:
        self.t_begin = time.perf_counter()

    def setup_done(self) -> None:
        self.t_setup_done = time.perf_counter()

    def finish(self) -> None:
        self.t_end = time.perf_counter()
        if self.t_setup_done == 0.0:
            self.t_setup_done = self.t_begin
        if self.span_sink is not None:
            self.span_sink("setup", self.t_begin,
                           self.t_setup_done - self.t_begin)
            self.span_sink("steady", self.t_setup_done,
                           self.t_end - self.t_setup_done)

    @property
    def full_us(self) -> int:
        return int((self.t_end - self.t_begin) * 1e6)

    @property
    def setup_us(self) -> int:
        return int((self.t_setup_done - self.t_begin) * 1e6)

    @property
    def nosetup_us(self) -> int:
        return int((self.t_end - self.t_setup_done) * 1e6)

    def cells_per_sec(self, rows: int, cols: int, iters: int) -> float:
        ns = self.nosetup_us
        return rows * cols * iters / (ns / 1e6) if ns > 0 else 0.0


def gather_process_durations(timer: PhaseTimer):
    """Per-process ``[full, nosetup, setup]`` µs rows, allgathered across
    the process group — the analog of the reference's three
    ``MPI_Reduce(MPI_SUM)`` of per-rank durations to rank 0
    (``/root/reference/main.cpp:319-324``), except every process gets the
    table (allgather) so any of them could report.

    Returns None in single-process runs: one host drives every device in
    lockstep there, so per-process rows would all equal wall time anyway.
    Collective — in a multi-process run every process must call it."""
    import jax

    if jax.process_count() == 1:
        return None
    import numpy as np
    from jax.experimental import multihost_utils

    durs = np.array(
        [timer.full_us, timer.nosetup_us, timer.setup_us], dtype=np.int64
    )
    return np.asarray(multihost_utils.process_allgather(durs))


def write_reports(
    time_file: str,
    timer: PhaseTimer,
    rows: int,
    cols: int,
    processes: int,
    first: bool = False,
    out_dir: str = ".",
    all_durations=None,
    extra=None,
) -> None:
    """Append the reference-schema pair of reports.

    ``extra``: optional ordered mapping of column name → value appended
    AFTER the reference's 12 CSV columns (sweep harnesses add
    cells/s/device and weak-scaling efficiency); leading columns stay
    byte-compatible with the reference schema, and plain runs (no
    ``extra``) emit exactly the reference header.

    ``processes`` is the tile-writer count (devices/workers) reported in
    the #P column.  ``all_durations`` — a (P_proc, 3) array of per-process
    ``[full, nosetup, setup]`` µs from ``gather_process_durations`` — feeds
    the avg/sum columns the way the reference's ``MPI_Reduce`` did (single
    = process 0's time, the reference's rank 0).  Without it (single
    process) per-device durations are taken equal to wall time (single ==
    avg; sum = wall × P), which matches how SPMD devices spend time: all
    of them are driven for the whole run."""
    p = max(processes, 1)
    if all_durations is not None:
        import numpy as np

        a = np.asarray(all_durations, dtype=np.int64)
        singles = a[0]
        sums = a.sum(axis=0)
        avgs = sums // a.shape[0]
        triples = list(zip(singles.tolist(), avgs.tolist(), sums.tolist()))
    else:
        triples = [
            (d, d, d * p)
            for d in (timer.full_us, timer.nosetup_us, timer.setup_us)
        ]
    (full, full_a, full_s), (nos, nos_a, nos_s), (setup, setup_a, setup_s) = triples
    detailed = os.path.join(out_dir, f"{time_file}_detailed.out")
    with open(detailed, "a") as f:
        f.write("Timing results: microseconds\n")
        f.write(f"size:{rows} by {cols}\n")
        f.write(f"{p} Processors\n")
        for label, (single, avg, total) in zip(
            ("Full (with setup)", "Without setup", "Setup"), triples
        ):
            f.write(f"{label}\n")
            f.write(f"Single time (rank 0): {single}us\n")
            f.write(f"Avg single time: {avg}us\n")
            f.write(f"Summed time: {total}us\n")
        f.write(f"Throughput: {timer.cells_per_sec(rows, cols, 1):.0f} cells/sec/iter-unit\n")
        f.write("___________________________________________________\n\n")
        # a sweep dying mid-run must not lose rows already "written":
        # same durability discipline as serve/recovery.py's StateStore
        f.flush()
        os.fsync(f.fileno())
    compact = os.path.join(out_dir, f"{time_file}_compact.csv")
    with open(compact, "a") as f:
        if first:
            if extra:
                f.write(CSV_HEADER.rstrip("\n")
                        + "".join(f",{k}" for k in extra) + "\n")
            else:
                f.write(CSV_HEADER)
        row = (
            f"{rows},{cols},{p},{full},{full_a},{full_s},"
            f"{nos},{nos_a},{nos_s},{setup},{setup_a},{setup_s}"
        )
        if extra:
            row += "".join(f",{v}" for v in extra.values())
        f.write(row + "\n")
        f.flush()
        os.fsync(f.fileno())
