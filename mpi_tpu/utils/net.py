"""Small stdlib networking helpers shared by the multi-process test
harnesses (``tests/test_multihost.py``, ``tests/test_cluster.py``) and
``tools/cluster_smoke.py``.

The classic free-port idiom — bind port 0, read the assigned port,
close the socket, hand the number to a subprocess — is a probe-then-use
race: between the close and the subprocess's own bind, any other
process (including a sibling test) can claim the port.  There is no
race-free way to reserve a port for *another* process, so the helpers
here make the race survivable instead: :func:`free_port` keeps the
probe (it is still the best available guess), :func:`bind_collision`
recognizes the loser's error text, and callers retry the whole
launch-with-fresh-port sequence a bounded number of times.
"""

from __future__ import annotations

import socket

# How many probe-launch rounds a caller should attempt before giving
# up: collisions need another process to claim the port inside a
# millisecond-scale window, so even two losses in a row are rare.
PORT_RETRIES = 3

_COLLISION_MARKERS = (
    "address already in use",
    "errno 98",                 # EADDRINUSE (linux)
    "errno 48",                 # EADDRINUSE (macOS)
    "only one usage of each socket address",  # winsock text, for hygiene
)


def free_port(host: str = "localhost") -> int:
    """A currently-free TCP port on ``host`` (the probe half of the
    probe-then-use idiom — see the module docstring for why callers
    must still handle :func:`bind_collision` and retry)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def bind_collision(text: str) -> bool:
    """Does this stderr/exception text look like the port was claimed
    between the :func:`free_port` probe and the real bind?"""
    low = (text or "").lower()
    return any(marker in low for marker in _COLLISION_MARKERS)
