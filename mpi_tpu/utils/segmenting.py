"""Shared K-generation segmentation for evolution drivers.

Both the sharded steppers (``parallel/step.py``, K = generations per halo
exchange) and the single-device Pallas stepper (``ops/pallas_bitlife.py``,
K = temporally-blocked generations per HBM pass) advance a grid in
K-generation segments with a remainder segment — one implementation here
so the clamp/divmod/remainder logic cannot drift between them.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


def segmented_evolve(make_local, K: int, donate: bool = True):
    """evolve(grid, steps): scan ``steps // K`` K-generation segments plus
    a single (steps % K)-generation remainder segment.

    ``make_local(k)`` must return a function advancing a grid by ``k``
    generations; it is only invoked for segment lengths that actually run
    (short runs never trace unused depth).  The returned ``evolve`` is
    jitted with donated input, so ``evolve.lower(grid, steps)`` works for
    ahead-of-time segment compilation.

    ``donate=False``: for steppers that run NESTED inside another jitted
    wrapper which still reads the same input after calling them — the
    seam stitcher extracts its band from the PRE-step grid, then calls
    the base stepper on that grid.  A donation hint on the nested call
    lets XLA alias the base stepper's output onto the very buffer the
    band extraction reads; on a multi-device mesh the per-device
    programs race and a shard's input can be clobbered mid-read
    (observed as nondeterministic whole-shard corruption on the
    8-virtual-device CPU mesh).  The donation then belongs to the OUTER
    wrapper's jit alone — peak memory is unchanged.
    """
    deco = (
        functools.partial(jax.jit, static_argnames=("steps",),
                          donate_argnums=0)
        if donate else
        functools.partial(jax.jit, static_argnames=("steps",))
    )

    @deco
    def evolve(grid, steps: int):
        k = max(1, min(K, steps))
        full, rem = divmod(steps, k)
        if full:
            step_k = make_local(k)

            def body(g, _):
                return step_k(g), None

            grid, _ = lax.scan(body, grid, None, length=full)
        if rem:
            grid = make_local(rem)(grid)
        return grid

    return evolve


def segment_depths(segments, K: int):
    """The local-step depths ``segmented_evolve`` will actually trace for
    these segment lengths: each segment n runs ⌊n/k⌋ scans at depth
    k = min(K, n) plus one remainder step at depth n % k.  Lives beside
    ``segmented_evolve`` so the clamp/divmod plan cannot drift from the
    one consumer that predicts it (the TPU backend's compile-fallback
    used_pallas gate — a depth never traced must not mark the program
    Pallas-bearing, or a real XLA compile error pays a second identical
    compile under a misleading fallback note)."""
    depths = set()
    for n in set(segments):
        if n <= 0:
            continue
        k = max(1, min(K, n))
        depths.add(k)
        if n % k:
            depths.add(n % k)
    return depths
