"""Shared utilities: deterministic init, timing, validation, logging."""
