"""MPI_TPU_PLATFORM env hook — the working platform override.

The ambient interpreter may pin ``jax_platforms`` at startup (a
sitecustomize calling ``jax.config.update``), which the ``JAX_PLATFORMS``
env var cannot beat; only another config update can.  Entry points (cli,
bench) call :func:`apply_platform_override` before touching devices so
``MPI_TPU_PLATFORM=cpu`` reliably forces the CPU backend — used to fake
hosts with CPU processes (the reference's oversubscribed-mpirun trick,
``/root/reference/run.sh:4-5``) and for degraded benchmarking when the
TPU is unreachable.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    plat = os.environ.get("MPI_TPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
