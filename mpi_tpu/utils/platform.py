"""MPI_TPU_PLATFORM env hook — the working platform override.

The ambient interpreter may pin ``jax_platforms`` at startup (a
sitecustomize calling ``jax.config.update``), which the ``JAX_PLATFORMS``
env var cannot beat; only another config update can.  Entry points (cli,
bench) call :func:`apply_platform_override` before touching devices so
``MPI_TPU_PLATFORM=cpu`` reliably forces the CPU backend — used to fake
hosts with CPU processes (the reference's oversubscribed-mpirun trick,
``/root/reference/run.sh:4-5``) and for degraded benchmarking when the
TPU is unreachable.
"""

from __future__ import annotations

import os


def apply_platform_override():
    """Honor an explicit platform request from the environment; returns
    the platform string that was applied (None if no request).

    ``MPI_TPU_PLATFORM`` wins; a bare ``JAX_PLATFORMS`` is honored too —
    users reasonably expect JAX's own env var to work, and without the
    re-pin the ambient sitecustomize silently overrides it (on a dead
    TPU tunnel that turns a requested-CPU run into an indefinite
    backend-init hang)."""
    plat = os.environ.get("MPI_TPU_PLATFORM") or os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat or None


def force_fetch(g) -> None:
    """Synchronize on a ``jax.Array`` with a real device→host fetch.

    On the tunneled platform ``jax.block_until_ready`` can return before
    the device finishes, so a timed region closed with it reports
    physically impossible throughput; only an actual data fetch is a
    reliable barrier (bench.py's scalar-popcount fetch is the same
    idea).  One element is fetched from EVERY addressable shard — a
    single-shard fetch would only synchronize that shard's device — in
    one batched ``device_get`` so the high-latency transport is paid one
    round-trip, not one per shard."""
    import jax

    jax.device_get([
        s.data[(slice(0, 1),) * s.data.ndim]
        for s in g.addressable_shards
    ])


def probe_platform(timeout: float = 150.0):
    """The default JAX platform name ("tpu", "cpu", ...) probed in a
    subprocess with a hard timeout, or None if unreachable.

    A dead TPU tunnel hangs ``jax.devices()`` indefinitely with no error,
    and an in-process hang cannot be interrupted — every tool that wants
    the real device must probe this way before touching JAX itself.

    The probe subprocess applies the same ``MPI_TPU_PLATFORM`` override as
    the callers' measurement children, so probe and measurement always
    resolve the platform identically."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from mpi_tpu.utils.platform import apply_platform_override; "
             "apply_platform_override(); "
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout, cwd=repo,
        )
        if proc.returncode != 0:
            return None
        return proc.stdout.strip().splitlines()[-1]
    except (subprocess.TimeoutExpired, IndexError):
        return None
