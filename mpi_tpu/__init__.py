"""mpi_tpu — a TPU-native stencil / cellular-automaton framework.

A from-scratch re-design of the capabilities of the reference MPI
Game-of-Life code (``/root/reference``, see ``SURVEY.md``): spatial domain
decomposition with ghost-cell (halo) exchange, a serial oracle, snapshot
I/O + visualization, and a benchmarking harness — rebuilt TPU-first:

* the per-cell B3/S23 update (reference ``main.cpp:79-103``) becomes a
  vectorized separable window-sum on the VPU (``ops/stencil.py``), with a
  fused Pallas kernel (``ops/pallas_stencil.py``) as the hot path;
* the MPI halo exchange (reference ``main.cpp:36-65``) becomes
  ``jax.lax.ppermute`` shifts inside ``shard_map`` over an ICI device
  mesh (``parallel/halo.py``);
* the 2D Cartesian process mesh (reference ``main.cpp:239-261``) becomes
  a ``jax.sharding.Mesh`` (``parallel/mesh.py``);
* the serial C++ oracle and the native multi-worker runtime live in
  ``backends/native`` (C++, loaded via ctypes) — the native layer the
  reference implements with MPI.

Everything shares one decomposition-invariant initialization
(``utils/hashinit.py``) so serial, native-C++, and TPU backends produce
bit-identical grids for the same configuration.
"""

from mpi_tpu.config import GolConfig
from mpi_tpu.models.rules import (
    Rule,
    LIFE,
    HIGHLIFE,
    SEEDS,
    DAY_AND_NIGHT,
    BOSCO,
    rule_from_name,
)

__version__ = "0.1.0"

__all__ = [
    "GolConfig",
    "Rule",
    "LIFE",
    "HIGHLIFE",
    "SEEDS",
    "DAY_AND_NIGHT",
    "BOSCO",
    "rule_from_name",
]
