"""Command-line front end — the reference's CLI contract
(``rows cols iteration_gap iterations [time_file] [first]``,
``/root/reference/main.cpp:171-223``) with the ``--backend`` switch the
north star asks for, plus flags for everything the reference hardcoded.

Examples::

    python -m mpi_tpu.cli 1024 1024 100 1000 --backend tpu
    python -m mpi_tpu.cli 64 64 10 50 --backend serial --save --out-dir /tmp/run
    python -m mpi_tpu.cli 64 64 10 50 --backend cpp-par --workers 8 --save
    python -m mpi_tpu.cli 64 64 10 100 --resume 2026-01-01-00-00-00@50

Every backend produces bit-identical grids and the same ``.gol`` dump
format, so ``tools/gol_visualization.py`` works on any run, and
``<time_file>_compact.csv`` keeps the reference's 12-column schema for
sweep tooling.
"""

from __future__ import annotations

import argparse
import sys
import time as _time
from typing import List, Optional, Tuple

import numpy as np

from mpi_tpu import golio
from mpi_tpu.config import ConfigError, GolConfig, plan_segments
from mpi_tpu.models.rules import rule_from_name
from mpi_tpu.utils.timing import PhaseTimer, write_reports


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_tpu",
        description="TPU-native Game-of-Life / stencil engine "
        "(serial, native C++, and TPU backends).",
    )
    p.add_argument("rows", type=int)
    p.add_argument("cols", type=int)
    p.add_argument("iteration_gap", type=int,
                   help="iterations between snapshots (reference: file_jump)")
    p.add_argument("iterations", type=int)
    p.add_argument("time_file", nargs="?", default=None,
                   help="basename for timing reports (default: run name)")
    p.add_argument("first", nargs="?", type=int, default=0,
                   help="nonzero: write the CSV header (sweep convention)")
    p.add_argument("--backend", choices=["tpu", "serial", "cpp", "cpp-par"],
                   default="tpu")
    p.add_argument("--boundary", choices=["periodic", "dead"], default="periodic")
    p.add_argument("--rule", default="life",
                   help="life|highlife|seeds|daynight|bosco or B3/S23 / "
                   "R5,B34-45,S33-57 syntax")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--save", action="store_true",
                   help="write .gol snapshots every iteration_gap steps")
    p.add_argument("--snapshot-format", choices=["auto", "gol", "golp"],
                   default="auto",
                   help="snapshot tile format: gol = reference-compatible "
                   "tab-separated text (~2 bytes/cell), golp = packed "
                   "binary (1 bit/cell — a 65536^2 snapshot drops from "
                   "~8.6 GB to ~537 MB); auto picks text for small tiles "
                   "and golp above %d cells/tile. Resume and the "
                   "visualizer read both." % golio.GOLP_THRESHOLD)
    p.add_argument("--out-dir", default=".")
    p.add_argument("--mesh", default=None, metavar="IxJ",
                   help="TPU device mesh shape, e.g. 2x4 (default: auto)")
    p.add_argument("--workers", type=int, default=0,
                   help="cpp-par worker threads (default: auto)")
    p.add_argument("--comm-every", default="1", metavar="K",
                   help="tpu backend: generations per halo exchange (1..16), "
                   "or 'auto' to choose K and overlap from the mesh "
                   "geometry plus a one-shot measured collective latency "
                   "(parallel/policy.py; single-device runs keep K=1). "
                   "K > 1 exchanges a K-deep ghost ring and runs K local "
                   "generations between collectives (communication-avoiding; "
                   "the deep-halo optimization the reference's per-step "
                   "barrier+exchange loop leaves out, main.cpp:291-305); on "
                   "a single TPU device with the packed (SWAR) engine K is "
                   "the Pallas kernel's temporal-blocking depth "
                   "(generations per HBM round-trip)")
    p.add_argument("--overlap", action="store_true",
                   help="tpu backend: "
                   "overlap the ppermute halo exchange with interior "
                   "compute (edge bands recomputed from the halo and "
                   "stitched in; the comm/compute overlap the reference's "
                   "barrier-then-exchange loop forgoes, main.cpp:297-299)")
    p.add_argument("--sparse", type=int, default=0, metavar="T",
                   help="tpu backend: activity-gated sparse stepping with "
                   "TxT dirty tiles (ops/activity.py) — skip tiles that "
                   "provably cannot change (bit-identical; an order of "
                   "magnitude on mostly-quiescent boards, automatic "
                   "hysteresis fallback to dense when the board is busy). "
                   "T must divide the grid; multiple of 32 on the packed "
                   "engines. 0 = dense (default)")
    p.add_argument("--name", default=None, help="run name (default: timestamp)")
    p.add_argument("--strict", action="store_true",
                   help="enforce the reference's validation rules "
                   "(square grid, square mesh, tile >= 4)")
    p.add_argument("--resume", default=None, metavar="NAME@ITER",
                   help="resume from snapshot ITER of run NAME; 'iterations' "
                   "then counts additional steps")
    p.add_argument("--multihost", action="store_true",
                   help="join a multi-host TPU slice via "
                   "jax.distributed.initialize() (launch one process per "
                   "host; the mpirun analog, reference gol.pbs)")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multihost coordinator address (default: "
                   "auto-detect from the cluster environment)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="multihost process-group size (with --coordinator)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's rank in the group (with --coordinator)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR "
                   "(tpu backend; the framework's jax-native answer to the "
                   "reference's chrono timing blocks)")
    p.add_argument("--quiet", action="store_true")
    return p


def _parse_mesh(s: Optional[str]) -> Optional[Tuple[int, int]]:
    if s is None:
        return None
    try:
        a, b = s.lower().split("x")
        return int(a), int(b)
    except ValueError:
        raise ConfigError(f"--mesh must look like 2x4, got {s!r}")


def _log(quiet: bool, msg: str) -> None:
    # per-phase liveness lines, the role of the reference's per-rank cout
    # checkpoints (main.cpp:263,279,281,366)
    if not quiet:
        print(f"[mpi_tpu] {msg}", flush=True)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # subcommand dispatch ahead of the positional-args parser (which
        # would read "serve" as rows); the one-shot contract is untouched
        from mpi_tpu.serve.cli import serve_main

        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    try:
        return _run(args)
    except (ConfigError, ValueError) as e:
        # fail fast on all hosts — the MPI_Abort analog (rule-string parse
        # errors surface as ValueError from rule_from_name)
        print(f"error: {e}", file=sys.stderr)
        return 2


def _run(args) -> int:
    import os

    if args.multihost and args.backend != "tpu":
        # the process group is the TPU slice; the other backends are
        # single-process by construction
        raise ConfigError(
            f"--multihost applies to the tpu backend only "
            f"(got backend={args.backend!r})"
        )
    from mpi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    if args.multihost:
        # must precede any other jax usage (the backend reads the process
        # group at initialization; the reference's MPI_Init analog)
        import jax

        if args.coordinator:
            jax.distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
            )
        else:
            jax.distributed.initialize()
        _log(args.quiet,
             f"multihost: process {jax.process_index()}/{jax.process_count()}, "
             f"{jax.local_device_count()} local of {jax.device_count()} devices")
    rule = rule_from_name(args.rule)
    mesh_shape = _parse_mesh(args.mesh)
    auto_comm = args.comm_every == "auto"
    if auto_comm and args.backend != "tpu":
        raise ConfigError("--comm-every auto applies to the tpu backend only")
    try:
        comm_every = 1 if auto_comm else int(args.comm_every)
    except ValueError:
        raise ConfigError(
            f"--comm-every must be an integer or 'auto', got {args.comm_every!r}"
        )
    config = GolConfig(
        rows=args.rows,
        cols=args.cols,
        steps=args.iterations,
        snapshot_every=args.iteration_gap if args.save else 0,
        seed=args.seed,
        rule=rule,
        boundary=args.boundary,
        backend=args.backend,
        mesh_shape=mesh_shape,
        out_dir=args.out_dir,
        workers=args.workers,
        comm_every=comm_every,
        overlap=args.overlap,
        sparse_tile=args.sparse,
    )
    if args.strict:
        # backend-independent checks (square grid, any typed --mesh) fail
        # here, before any side effect — no out-dir creation, no snapshot
        # load, no device init (jax.devices can hang on a dead tunnel);
        # the effective auto-chosen decomposition is re-checked below.
        config.validate_strict()

    os.makedirs(args.out_dir, exist_ok=True)
    if args.name:
        name = args.name
    elif args.multihost:
        # per-host timestamps can straddle a second boundary and split the
        # run across names; derive a deterministic name from the config
        name = f"run-{args.rows}x{args.cols}-{args.iterations}-s{args.seed}"
    else:
        name = _time.strftime("%Y-%m-%d-%H-%M-%S")
    timer = PhaseTimer()

    initial = None
    start_iter = 0
    if args.resume:
        try:
            rname, riter = args.resume.rsplit("@", 1)
            start_iter = int(riter)
        except ValueError:
            raise ConfigError(f"--resume must look like NAME@ITER, got {args.resume!r}")
        try:
            srows, scols, _, _, _ = golio.read_master(
                golio.master_path(args.out_dir, rname))
        except FileNotFoundError as e:
            raise ConfigError(f"cannot resume {args.resume!r}: {e}")
        if (srows, scols) != (config.rows, config.cols):
            raise ConfigError(
                f"snapshot {rname}@{start_iter} is {(srows, scols)}, "
                f"run asks for {(config.rows, config.cols)}"
            )
        if args.multihost:
            # no host materializes (or even reads) the global grid: the
            # runner calls this per addressable shard, each host touching
            # only the tile files that intersect its shards
            def initial(r0, r1, c0, c1, _rn=rname, _it=start_iter):
                return golio.assemble_region(
                    args.out_dir, _rn, _it, r0, r1, c0, c1)
        else:
            try:
                initial = golio.load_snapshot(args.out_dir, rname, start_iter)
            except FileNotFoundError as e:
                raise ConfigError(f"cannot resume {args.resume!r}: {e}")
        name = args.name or rname
        _log(args.quiet, f"resumed {rname}@{start_iter}")

    total_iter = start_iter + config.steps

    # processes in the master header = number of tile writers
    if config.backend in ("serial", "cpp"):
        processes = 1
        tiles_shape = (1, 1)
        effective_mesh = (1, 1)
    elif config.backend == "cpp-par":
        from mpi_tpu.backends.cpp import plan_tiles

        tiles_shape = plan_tiles((config.rows, config.cols), config.workers, rule.radius)
        processes = tiles_shape[0] * tiles_shape[1]
        effective_mesh = tiles_shape
    else:
        from mpi_tpu.backends.tpu import device_count

        if mesh_shape is None:
            from mpi_tpu.parallel.mesh import choose_mesh_shape

            effective_mesh = choose_mesh_shape(device_count())
        else:
            effective_mesh = mesh_shape
        processes = effective_mesh[0] * effective_mesh[1]
    if auto_comm and config.backend == "tpu":
        import dataclasses

        from mpi_tpu.parallel.policy import resolve_auto

        auto_mesh = None
        if processes > 1:
            from mpi_tpu.parallel.mesh import make_mesh

            auto_mesh = make_mesh(effective_mesh)
        k, ov = resolve_auto(config, effective_mesh, mesh=auto_mesh)
        config = dataclasses.replace(config, comm_every=k,
                                     overlap=ov or config.overlap)
        _log(args.quiet,
             f"comm policy auto: comm_every={k}, overlap={config.overlap}")
    if args.strict:
        # judged against the decomposition that will actually run, not just
        # an explicit --mesh (reference rules, main.cpp:194-200)
        config.validate_strict(effective_mesh)

    def _is_report_writer() -> bool:
        # multihost: process 0 is the reference's "rank 0" reporter —
        # every host writing would double-append on a shared filesystem
        if not args.multihost:
            return True
        import jax

        return jax.process_index() == 0

    # every host writes the master manifest: the content is identical and
    # the write idempotent ("w" mode), and per-host-disk deployments need
    # it locally for resume's read_master — only the append-mode timing
    # reports must stay single-writer
    golio.write_master(
        args.out_dir, name, config.rows, config.cols,
        args.iteration_gap, total_iter, processes,
    )
    _log(args.quiet, f"run {name}: {config.rows}x{config.cols} x{config.steps} steps, "
         f"rule={rule}, boundary={config.boundary}, backend={config.backend}, "
         f"processes={processes}")

    def host_snapshot(grid: np.ndarray, iteration: int, tiles_shape) -> None:
        ti, tj = tiles_shape
        tr, tc = grid.shape[0] // ti, grid.shape[1] // tj
        tiles = [
            (grid[i * tr : (i + 1) * tr, j * tc : (j + 1) * tc], i * tr, j * tc)
            for i in range(ti)
            for j in range(tj)
        ]
        golio.write_snapshot_tiles(args.out_dir, name, iteration, tiles,
                                   fmt=args.snapshot_format)

    if config.backend == "tpu":
        import contextlib

        from mpi_tpu.backends.tpu import run_tpu

        def cb(iteration, tiles):
            # tiles carry globally-unique pids (multi-host: each host
            # writes only its addressable shards)
            for pid, tile, r0, c0 in tiles:
                golio.write_tile_fmt(args.out_dir, name, iteration, pid,
                                     tile, r0, c0, fmt=args.snapshot_format)
            # Every host prunes tiles whose pid is not in the CURRENT
            # global writer set: a rerun of the same config-derived name
            # with fewer writers must not leave old tiles for assemble to
            # merge.  Stale pids in the current set are simply overwritten
            # by their owner; dead pids are safe to remove from any host
            # (per-host local disks each see only their own leftovers, and
            # remove_stale_tiles tolerates shared-filesystem races).
            golio.remove_stale_tiles(
                args.out_dir, name, iteration, range(processes)
            )

        profile_ctx = contextlib.nullcontext()
        if args.profile:
            import jax

            profile_ctx = jax.profiler.trace(args.profile)
        with profile_ctx:
            final = run_tpu(
                config,
                timer=timer,
                snapshot_cb=cb if args.save else None,
                initial=initial,
                start_iteration=start_iter,
            )
    else:
        if config.backend == "serial":
            from mpi_tpu.backends.serial_np import evolve_np as _evolve

            def engine(g, n):
                return _evolve(g, n, rule, config.boundary)
        elif config.backend == "cpp":
            from mpi_tpu.backends.cpp import evolve_cpp

            def engine(g, n):
                return evolve_cpp(g, n, rule, config.boundary)
        else:  # cpp-par
            from mpi_tpu.backends.cpp import evolve_par_cpp

            def engine(g, n):
                return evolve_par_cpp(g, n, rule, config.boundary, tiles=tiles_shape)

        if config.backend in ("cpp", "cpp-par"):
            # building/loading the native library is setup, like XLA compile
            from mpi_tpu.backends.cpp import load_library

            load_library()
        if initial is None:
            from mpi_tpu.utils.hashinit import init_tile_np

            grid = init_tile_np(config.rows, config.cols, config.seed)
        else:
            grid = initial
        timer.setup_done()
        it = start_iter
        if args.save and it == 0:
            host_snapshot(grid, 0, tiles_shape)
        for n in plan_segments(config.steps, args.iteration_gap if args.save else 0):
            grid = engine(grid, n)
            it += n
            if args.save:
                host_snapshot(grid, it, tiles_shape)
        timer.finish()
        final = grid

    time_file = args.time_file or name
    all_durs = None
    if args.multihost:
        # collective: every process participates in the gather (the
        # MPI_Reduce analog), even though only process 0 reports
        from mpi_tpu.utils.timing import gather_process_durations

        all_durs = gather_process_durations(timer)
    if _is_report_writer():
        write_reports(
            time_file, timer, config.rows, config.cols, processes,
            first=bool(args.first), out_dir=args.out_dir,
            all_durations=all_durs,
        )
    cps = timer.cells_per_sec(config.rows, config.cols, config.steps)
    _log(args.quiet,
         f"done: setup {timer.setup_us / 1e6:.2f}s, steady {timer.nosetup_us / 1e6:.2f}s, "
         f"{cps / 1e9:.3f} G cell-updates/s; population "
         f"{int(final.sum()) if final is not None else 'n/a (multihost)'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
