""".gol snapshot file format — the cross-backend contract.

Format (wire-compatible with the reference so one visualizer serves every
backend; defined by ``/root/reference/main_serial.cpp:74-113`` and consumed
by ``/root/reference/gol_visualization.py``):

* master file ``<name>.gol``: one line ``rows cols iteration_gap iterations
  processes``;
* per-tile files ``<name>_<iteration>_<pid>.gol``: two metadata lines
  ``firstRow lastRow`` / ``firstCol lastCol`` (inclusive global coordinates),
  then the tile interior as tab-separated 0/1 rows (trailing tab per row,
  exactly as the reference's ``ostream_iterator`` emits).

Improvements over the reference (SURVEY.md §5.4): snapshots are portable
(no hardcoded cluster path, ``main.cpp:110``), actually enabled (the
reference pins ``save_file=0``, ``main.cpp:208``), and **readable back** —
the reference has no resume path; ``load_snapshot`` makes
checkpoint/restart real.

Packed binary tiles (``.golp``, VERDICT r2 item 3): the text format costs
~2 bytes/cell — a 65536² snapshot is ~8.6 GB of tabs, unusable at the
production scale ``gol.batch.sh`` advertises.  ``.golp`` keeps the same
per-tile file layout (same naming, same inclusive-coordinate header) but
stores the body as ``np.packbits`` rows — 1 bit/cell, ~537 MB at 65536².
Readers (``read_tile``/``assemble``/the visualizer) sniff per file, so a
run may mix formats; writers pick text below ``GOLP_THRESHOLD`` cells for
reference-tooling compatibility and packed above it (``fmt="auto"``).
"""

from __future__ import annotations

import os
import re
from typing import List, Tuple

import numpy as np


GOLP_MAGIC = b"GOLP1\n"
# auto format: text at/below this many cells per tile (keeps small runs
# readable by reference-era tooling), packed binary above it
GOLP_THRESHOLD = 1 << 24


def master_path(out_dir: str, name: str) -> str:
    return os.path.join(out_dir, f"{name}.gol")


def tile_path(out_dir: str, name: str, iteration: int, pid: int) -> str:
    return os.path.join(out_dir, f"{name}_{iteration}_{pid}.gol")


def tile_path_packed(out_dir: str, name: str, iteration: int, pid: int) -> str:
    return os.path.join(out_dir, f"{name}_{iteration}_{pid}.golp")


def find_tile_path(out_dir: str, name: str, iteration: int, pid: int) -> str:
    """The on-disk tile file for (iteration, pid), whichever format it was
    written in.  Writers keep one canonical file per pid (rewrites remove
    the other format), so at most one should exist; if both somehow do —
    a writer crashed between its atomic write and removing the other
    format — the *newer* one wins: tiles are written complete (temp +
    ``os.replace``), so mtime order is write order and a stale format
    cannot shadow a fresh rewrite.  (Equal timestamps — possible only
    through timestamp-preserving restores or a coarse-mtime filesystem —
    resolve to the text side, an arbitrary but deterministic choice.)"""
    packed = tile_path_packed(out_dir, name, iteration, pid)
    text = tile_path(out_dir, name, iteration, pid)
    try:
        pt = os.stat(packed).st_mtime_ns
    except FileNotFoundError:
        return text
    try:
        tt = os.stat(text).st_mtime_ns
    except FileNotFoundError:
        return packed
    return text if tt >= pt else packed


def write_master(
    out_dir: str, name: str, rows: int, cols: int,
    iteration_gap: int, iterations: int, processes: int,
) -> str:
    """The manifest the visualizer reads (reference ``setUpProgram``,
    ``main_serial.cpp:97-113``)."""
    path = master_path(out_dir, name)
    # atomic replace: under multihost every process writes the manifest
    # (per-host disks need it locally) while a lagging process may still
    # be read_master-ing it for resume — readers must never see a
    # truncated/torn file
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{rows} {cols} {iteration_gap} {iterations} {processes}\n")
    os.replace(tmp, path)
    return path


def read_master(path: str) -> Tuple[int, int, int, int, int]:
    with open(path) as f:
        parts = f.readline().split()
    if len(parts) != 5:
        raise ValueError(f"malformed master .gol header in {path!r}: {parts}")
    rows, cols, gap, iters, procs = map(int, parts)
    return rows, cols, gap, iters, procs


def write_tile(
    out_dir: str, name: str, iteration: int, pid: int,
    tile: np.ndarray, first_row: int, first_col: int,
) -> str:
    rows, cols = tile.shape
    path = tile_path(out_dir, name, iteration, pid)
    # temp + atomic replace: a reader (or a crash) can never observe a
    # truncated tile at the canonical path
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{first_row} {first_row + rows - 1}\n")
        f.write(f"{first_col} {first_col + cols - 1}\n")
        for r in tile:
            # trailing tab matches the reference's ostream_iterator output
            f.write("\t".join("1" if v else "0" for v in r) + "\t\n")
    os.replace(tmp, path)
    return path


def write_tile_packed(
    out_dir: str, name: str, iteration: int, pid: int,
    tile: np.ndarray, first_row: int, first_col: int,
) -> str:
    """1-bit/cell binary tile: magic, the same two coordinate lines as the
    text format, then ``np.packbits`` rows (each row padded to a whole
    byte, MSB-first within a byte)."""
    rows, cols = tile.shape
    path = tile_path_packed(out_dir, name, iteration, pid)
    body = np.packbits(np.asarray(tile, dtype=np.uint8), axis=1)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(GOLP_MAGIC)
        f.write(f"{first_row} {first_row + rows - 1}\n".encode())
        f.write(f"{first_col} {first_col + cols - 1}\n".encode())
        f.write(body.tobytes())
    os.replace(tmp, path)
    return path


def _is_packed(path: str) -> bool:
    if path.endswith(".golp"):
        return True
    with open(path, "rb") as f:
        return f.read(len(GOLP_MAGIC)) == GOLP_MAGIC


def read_tile_header(path: str) -> Tuple[int, int, int, int]:
    """Just the (firstRow, lastRow, firstCol, lastCol) metadata — lets
    callers test intersection without parsing the tile body."""
    with open(path, "rb") as f:
        first = f.readline()
        if first == GOLP_MAGIC:
            first = f.readline()
        r0, r1 = map(int, first.split())
        c0, c1 = map(int, f.readline().split())
    return r0, r1, c0, c1


def read_tile(path: str) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    """Either tile format, sniffed by magic (not extension — a ``.golp``
    copied to a ``.gol`` name still reads)."""
    if _is_packed(path):
        return _read_tile_packed(path)
    with open(path) as f:
        r0, r1 = map(int, f.readline().split())
        c0, c1 = map(int, f.readline().split())
        data = [line.split() for line in f if line.strip()]
    tile = np.array(data, dtype=np.uint8)
    expect = (r1 - r0 + 1, c1 - c0 + 1)
    if tile.shape != expect:
        raise ValueError(f"{path!r}: tile shape {tile.shape} != metadata {expect}")
    return tile, (r0, r1, c0, c1)


def _read_tile_packed(path: str) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    with open(path, "rb") as f:
        if f.readline() != GOLP_MAGIC:
            raise ValueError(f"{path!r}: not a .golp tile (bad magic)")
        r0, r1 = map(int, f.readline().split())
        c0, c1 = map(int, f.readline().split())
        body = f.read()
    rows, cols = r1 - r0 + 1, c1 - c0 + 1
    row_bytes = (cols + 7) // 8
    if len(body) != rows * row_bytes:
        raise ValueError(
            f"{path!r}: body is {len(body)} bytes, metadata implies "
            f"{rows}x{row_bytes}"
        )
    packed = np.frombuffer(body, dtype=np.uint8).reshape(rows, row_bytes)
    return np.unpackbits(packed, axis=1)[:, :cols], (r0, r1, c0, c1)


def list_snapshot_iterations(out_dir: str, name: str) -> List[int]:
    """Iterations for which tile files exist (pid 0 as the witness)."""
    pat = re.compile(re.escape(name) + r"_(\d+)_0\.golp?$")
    out = {
        int(m.group(1))
        for fn in os.listdir(out_dir or ".")
        if (m := pat.match(fn))
    }
    return sorted(out)


def iteration_tile_pids(out_dir: str, name: str, iteration: int) -> List[int]:
    """pids of the tile files actually present for one iteration."""
    pat = re.compile(re.escape(name) + "_" + str(iteration) + r"_(\d+)\.golp?$")
    pids = {
        int(m.group(1))
        for fn in os.listdir(out_dir or ".")
        if (m := pat.match(fn))
    }
    return sorted(pids)


def assemble(out_dir: str, name: str, iteration: int) -> np.ndarray:
    """Stitch all per-process tiles of one iteration into the global grid
    (what the reference visualizer does at ``gol_visualization.py:18-34``).

    Tiles are discovered from the files present rather than the master's
    ``processes`` field: a resumed run may write a different tile count per
    iteration (e.g. a 4-worker native run resumed on a 1-chip TPU), and the
    master header can only record one value.
    """
    rows, cols, _, _, _ = read_master(master_path(out_dir, name))
    return assemble_region(out_dir, name, iteration, 0, rows, 0, cols)


def load_snapshot(out_dir: str, name: str, iteration: int) -> np.ndarray:
    """Checkpoint-restart entry: the global grid at a saved iteration."""
    return assemble(out_dir, name, iteration)


def assemble_region(
    out_dir: str, name: str, iteration: int,
    r0: int, r1: int, c0: int, c1: int,
) -> np.ndarray:
    """Assemble one sub-rectangle (inclusive-exclusive rows [r0, r1), cols
    [c0, c1)) of a saved iteration, reading only the tile files that
    intersect it — the multihost resume path: each host loads exactly its
    addressable shards without ever materializing the global grid."""
    pids = iteration_tile_pids(out_dir, name, iteration)
    if not pids:
        raise ValueError(f"snapshot {name}@{iteration}: no tile files found")
    region = np.zeros((r1 - r0, c1 - c0), dtype=np.uint8)
    seen = np.zeros(region.shape, dtype=bool)
    for pid in pids:
        path = find_tile_path(out_dir, name, iteration, pid)
        # header first: skip the (potentially huge) body of tiles that
        # don't intersect the requested region
        tr0, tr1, tc0, tc1 = read_tile_header(path)
        ir0, ir1 = max(r0, tr0), min(r1, tr1 + 1)
        ic0, ic1 = max(c0, tc0), min(c1, tc1 + 1)
        if ir0 >= ir1 or ic0 >= ic1:
            continue
        tile, _ = read_tile(path)
        region[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = tile[
            ir0 - tr0 : ir1 - tr0, ic0 - tc0 : ic1 - tc0]
        seen[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = True
    if not seen.all():
        raise ValueError(
            f"snapshot {name}@{iteration}: tiles cover only "
            f"{int(seen.sum())}/{seen.size} cells of region "
            f"[{r0}:{r1}, {c0}:{c1}]"
        )
    return region


def remove_stale_tiles(out_dir: str, name: str, iteration: int, keep_pids) -> None:
    """Remove tiles of pids outside ``keep_pids`` at this iteration — a
    rerun/resume that rewrites an iteration with fewer writers must not
    leave old tiles behind for ``assemble`` to silently merge.  keep_pids
    must be the set of ALL pids current writers will produce (across every
    host, in multihost runs); concurrent removal by several hosts on a
    shared filesystem is tolerated."""
    keep = set(keep_pids)
    for pid in iteration_tile_pids(out_dir, name, iteration):
        if pid not in keep:
            for path in (tile_path(out_dir, name, iteration, pid),
                         tile_path_packed(out_dir, name, iteration, pid)):
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass  # other format / another host already removed it


def write_tile_fmt(
    out_dir: str, name: str, iteration: int, pid: int,
    tile: np.ndarray, first_row: int, first_col: int, fmt: str = "auto",
) -> str:
    """One tile in the selected format ("gol", "golp", or "auto" = packed
    above GOLP_THRESHOLD cells), removing the other format's file for the
    same pid so rewrites leave exactly one canonical tile.  The new tile
    lands atomically (temp + ``os.replace``) *before* the stale format is
    removed, so a complete tile exists on disk at every instant; if a
    crash between the two leaves both formats, ``find_tile_path``'s
    mtime tiebreak still resolves to the fresh one."""
    if fmt not in ("auto", "gol", "golp"):
        raise ValueError(f"unknown snapshot format {fmt!r}")
    packed = fmt == "golp" or (fmt == "auto" and tile.size > GOLP_THRESHOLD)
    if packed:
        path = write_tile_packed(out_dir, name, iteration, pid,
                                 tile, first_row, first_col)
        other = tile_path(out_dir, name, iteration, pid)
    else:
        path = write_tile(out_dir, name, iteration, pid,
                          tile, first_row, first_col)
        other = tile_path_packed(out_dir, name, iteration, pid)
    try:
        os.remove(other)
    except FileNotFoundError:
        pass
    return path


def write_snapshot_tiles(
    out_dir: str, name: str, iteration: int,
    tiles: List[Tuple[np.ndarray, int, int]],
    fmt: str = "auto",
) -> None:
    """Write one iteration's snapshot as per-process tiles.
    tiles: list of (tile_array, first_row, first_col), pid = list index."""
    for pid, (tile, r0, c0) in enumerate(tiles):
        write_tile_fmt(out_dir, name, iteration, pid, tile, r0, c0, fmt)
    remove_stale_tiles(out_dir, name, iteration, range(len(tiles)))
