""".gol snapshot file format — the cross-backend contract.

Format (wire-compatible with the reference so one visualizer serves every
backend; defined by ``/root/reference/main_serial.cpp:74-113`` and consumed
by ``/root/reference/gol_visualization.py``):

* master file ``<name>.gol``: one line ``rows cols iteration_gap iterations
  processes``;
* per-tile files ``<name>_<iteration>_<pid>.gol``: two metadata lines
  ``firstRow lastRow`` / ``firstCol lastCol`` (inclusive global coordinates),
  then the tile interior as tab-separated 0/1 rows (trailing tab per row,
  exactly as the reference's ``ostream_iterator`` emits).

Improvements over the reference (SURVEY.md §5.4): snapshots are portable
(no hardcoded cluster path, ``main.cpp:110``), actually enabled (the
reference pins ``save_file=0``, ``main.cpp:208``), and **readable back** —
the reference has no resume path; ``load_snapshot`` makes
checkpoint/restart real.
"""

from __future__ import annotations

import os
import re
from typing import List, Tuple

import numpy as np


def master_path(out_dir: str, name: str) -> str:
    return os.path.join(out_dir, f"{name}.gol")


def tile_path(out_dir: str, name: str, iteration: int, pid: int) -> str:
    return os.path.join(out_dir, f"{name}_{iteration}_{pid}.gol")


def write_master(
    out_dir: str, name: str, rows: int, cols: int,
    iteration_gap: int, iterations: int, processes: int,
) -> str:
    """The manifest the visualizer reads (reference ``setUpProgram``,
    ``main_serial.cpp:97-113``)."""
    path = master_path(out_dir, name)
    # atomic replace: under multihost every process writes the manifest
    # (per-host disks need it locally) while a lagging process may still
    # be read_master-ing it for resume — readers must never see a
    # truncated/torn file
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{rows} {cols} {iteration_gap} {iterations} {processes}\n")
    os.replace(tmp, path)
    return path


def read_master(path: str) -> Tuple[int, int, int, int, int]:
    with open(path) as f:
        parts = f.readline().split()
    if len(parts) != 5:
        raise ValueError(f"malformed master .gol header in {path!r}: {parts}")
    rows, cols, gap, iters, procs = map(int, parts)
    return rows, cols, gap, iters, procs


def write_tile(
    out_dir: str, name: str, iteration: int, pid: int,
    tile: np.ndarray, first_row: int, first_col: int,
) -> str:
    rows, cols = tile.shape
    path = tile_path(out_dir, name, iteration, pid)
    with open(path, "w") as f:
        f.write(f"{first_row} {first_row + rows - 1}\n")
        f.write(f"{first_col} {first_col + cols - 1}\n")
        for r in tile:
            # trailing tab matches the reference's ostream_iterator output
            f.write("\t".join("1" if v else "0" for v in r) + "\t\n")
    return path


def read_tile_header(path: str) -> Tuple[int, int, int, int]:
    """Just the (firstRow, lastRow, firstCol, lastCol) metadata — lets
    callers test intersection without parsing the tile body."""
    with open(path) as f:
        r0, r1 = map(int, f.readline().split())
        c0, c1 = map(int, f.readline().split())
    return r0, r1, c0, c1


def read_tile(path: str) -> Tuple[np.ndarray, Tuple[int, int, int, int]]:
    with open(path) as f:
        r0, r1 = map(int, f.readline().split())
        c0, c1 = map(int, f.readline().split())
        data = [line.split() for line in f if line.strip()]
    tile = np.array(data, dtype=np.uint8)
    expect = (r1 - r0 + 1, c1 - c0 + 1)
    if tile.shape != expect:
        raise ValueError(f"{path!r}: tile shape {tile.shape} != metadata {expect}")
    return tile, (r0, r1, c0, c1)


def list_snapshot_iterations(out_dir: str, name: str) -> List[int]:
    """Iterations for which tile files exist (pid 0 as the witness)."""
    pat = re.compile(re.escape(name) + r"_(\d+)_0\.gol$")
    out = []
    for fn in os.listdir(out_dir or "."):
        m = pat.match(fn)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def iteration_tile_pids(out_dir: str, name: str, iteration: int) -> List[int]:
    """pids of the tile files actually present for one iteration."""
    pat = re.compile(re.escape(name) + "_" + str(iteration) + r"_(\d+)\.gol$")
    pids = []
    for fn in os.listdir(out_dir or "."):
        m = pat.match(fn)
        if m:
            pids.append(int(m.group(1)))
    return sorted(pids)


def assemble(out_dir: str, name: str, iteration: int) -> np.ndarray:
    """Stitch all per-process tiles of one iteration into the global grid
    (what the reference visualizer does at ``gol_visualization.py:18-34``).

    Tiles are discovered from the files present rather than the master's
    ``processes`` field: a resumed run may write a different tile count per
    iteration (e.g. a 4-worker native run resumed on a 1-chip TPU), and the
    master header can only record one value.
    """
    rows, cols, _, _, _ = read_master(master_path(out_dir, name))
    return assemble_region(out_dir, name, iteration, 0, rows, 0, cols)


def load_snapshot(out_dir: str, name: str, iteration: int) -> np.ndarray:
    """Checkpoint-restart entry: the global grid at a saved iteration."""
    return assemble(out_dir, name, iteration)


def assemble_region(
    out_dir: str, name: str, iteration: int,
    r0: int, r1: int, c0: int, c1: int,
) -> np.ndarray:
    """Assemble one sub-rectangle (inclusive-exclusive rows [r0, r1), cols
    [c0, c1)) of a saved iteration, reading only the tile files that
    intersect it — the multihost resume path: each host loads exactly its
    addressable shards without ever materializing the global grid."""
    pids = iteration_tile_pids(out_dir, name, iteration)
    if not pids:
        raise ValueError(f"snapshot {name}@{iteration}: no tile files found")
    region = np.zeros((r1 - r0, c1 - c0), dtype=np.uint8)
    seen = np.zeros(region.shape, dtype=bool)
    for pid in pids:
        path = tile_path(out_dir, name, iteration, pid)
        # header first: skip the (potentially huge) tab-separated body of
        # tiles that don't intersect the requested region
        tr0, tr1, tc0, tc1 = read_tile_header(path)
        ir0, ir1 = max(r0, tr0), min(r1, tr1 + 1)
        ic0, ic1 = max(c0, tc0), min(c1, tc1 + 1)
        if ir0 >= ir1 or ic0 >= ic1:
            continue
        tile, _ = read_tile(path)
        region[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = tile[
            ir0 - tr0 : ir1 - tr0, ic0 - tc0 : ic1 - tc0]
        seen[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = True
    if not seen.all():
        raise ValueError(
            f"snapshot {name}@{iteration}: tiles cover only "
            f"{int(seen.sum())}/{seen.size} cells of region "
            f"[{r0}:{r1}, {c0}:{c1}]"
        )
    return region


def remove_stale_tiles(out_dir: str, name: str, iteration: int, keep_pids) -> None:
    """Remove tiles of pids outside ``keep_pids`` at this iteration — a
    rerun/resume that rewrites an iteration with fewer writers must not
    leave old tiles behind for ``assemble`` to silently merge.  keep_pids
    must be the set of ALL pids current writers will produce (across every
    host, in multihost runs); concurrent removal by several hosts on a
    shared filesystem is tolerated."""
    keep = set(keep_pids)
    for pid in iteration_tile_pids(out_dir, name, iteration):
        if pid not in keep:
            try:
                os.remove(tile_path(out_dir, name, iteration, pid))
            except FileNotFoundError:
                pass  # another host already removed it


def write_snapshot_tiles(
    out_dir: str, name: str, iteration: int,
    tiles: List[Tuple[np.ndarray, int, int]],
) -> None:
    """Write one iteration's snapshot as per-process tiles.
    tiles: list of (tile_array, first_row, first_col), pid = list index."""
    for pid, (tile, r0, c0) in enumerate(tiles):
        write_tile(out_dir, name, iteration, pid, tile, r0, c0)
    remove_stale_tiles(out_dir, name, iteration, range(len(tiles)))
