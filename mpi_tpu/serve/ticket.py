"""Async ticketed stepping — the pipelined dispatch loop (PR 5).

The sync step path holds its HTTP worker thread through
``block_until_ready``, so the MicroBatcher can only coalesce requests
that happen to collide inside a 2 ms window while their callers block.
This module decouples the two halves: ``POST /step`` with
``{"async": true}`` enqueues a :class:`Ticket` and returns immediately;
a per-:class:`~mpi_tpu.serve.session.SessionManager` dispatch loop owns
device submission, so JAX's async dispatch overlaps HTTP
parse/serialize and checkpoint writes with device execution, and
``GET /result/<ticket>`` (or its blocking ``?wait=1`` variant) delivers
the eventual outcome — which may be a structured 503, because tickets
carry the exact PR-3 deadline/watchdog/breaker semantics: a ticket's
budget starts at enqueue, and an expired queued ticket is drained with
:class:`~mpi_tpu.serve.session.DeadlineError` without ever dispatching.

**Heterogeneous-depth (unit-step) scheduling.**  The sync batcher keys
its queues on ``(plan_signature, depth)``, so a depth-3 and a depth-1
request never share a dispatch.  The dispatch loop instead decomposes a
depth-k ticket into k *unit steps* scheduled round-by-round: each round
takes the head ticket of every session, groups the engine-backed heads
by engine, and advances each group through a **cohort-chunked chain**
of depth-1 dispatches: boards sorted by remaining depth advance
together — stacked ``[B, ...]`` vmapped dispatches when B >= 2
(``Engine.step_batched`` at depth 1), a donation-safe
``Engine.step_units`` chain when alone — up to the shallowest cohort's
depth, finished lanes peel off, and the narrower stack continues, with
ONE sync at the end of the whole chain.  Mixed-depth sessions therefore
share dispatches for as long as their remaining depths overlap, every
head ticket finishes its full depth in one round (a {1, 16} mix costs
one sync, not sixteen), and only the depth-1 executables (the one depth
every session precompiles) are ever needed.

In-order completion per session is structural: one dispatch loop, one
FIFO queue per session, only the head ticket ever runs.  Generations
stay monotonic and commits (generation bump + checkpoint) happen only
after the chain's ``block_until_ready`` returns, so a ``kill -9``
mid-flight restores to the last *completed* dispatch, never past it.

Failure discipline mirrors the MicroBatcher: any group-chain failure
counts ONE engine failure against the signature's breaker, then every
ticket in the group falls back to the solo step path —
``SessionManager.step`` with the ticket's original enqueue deadline —
which owns retry/backoff, breaker re-check, degradation, and the
watchdog.  Batching never changes results; it only removes dispatches.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from mpi_tpu.obs.trace import (
    current_request_id, reset_request_id, set_request_id,
)
from mpi_tpu.obs.tracectx import (
    current_trace_context, reset_trace_context, set_trace_context,
)


class TicketQueueFullError(RuntimeError):
    """The async queue is at its bound (``--async-queue-max``) —
    backpressure, not a bug.  Maps to HTTP 503: retry later."""


class Ticket:
    """One enqueued async step.  ``status`` moves pending -> done|error
    exactly once; ``event`` wakes ``?wait=1`` pollers.  ``deadline``
    (a ``session._Deadline``) started counting at enqueue.  ``rid``
    carries the enqueuing request's id across the thread hop to the
    dispatch loop, same as the MicroBatcher's ``_Entry.rid``; ``tctx``
    persists the minting trace context the same way, so the spans the
    dispatch loop records for this ticket stitch under the enqueuing
    request wherever it entered the cluster."""

    __slots__ = ("id", "sid", "steps", "remaining", "deadline", "status",
                 "result", "error", "event", "rid", "tctx",
                 "enqueued_mono", "done_mono", "unit_rounds",
                 "max_batched", "callbacks", "qos", "cost")

    def __init__(self, tid: str, sid: str, steps: int, deadline,
                 qos: str = "standard", cost: float = 0.0):
        self.id = tid
        self.sid = sid
        self.steps = int(steps)
        self.remaining = int(steps)
        self.deadline = deadline
        # admission-control tags: priority class and the CostCard
        # estimate (ops) used for head-of-line ordering.  Unarmed
        # servers leave the defaults and never read them.
        self.qos = qos
        self.cost = float(cost)
        self.status = "pending"
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.rid = current_request_id()
        self.tctx = current_trace_context()
        self.enqueued_mono = time.monotonic()
        self.done_mono: Optional[float] = None
        self.unit_rounds = 0            # device rounds this ticket rode in
        self.max_batched = 0            # widest batch it shared (0 = solo)
        self.callbacks: List = []       # resolution callbacks (aio waiters)


class AsyncDispatcher:
    """The per-manager dispatch loop plus its ticket table.

    Thread model: ``submit``/``get``/gauge callbacks run on HTTP worker
    threads and touch shared state only under ``_cv``; the single
    dispatch-loop thread (started lazily on the first submit, daemon) is
    the only mutator of the per-session queues between rounds and the
    only caller of device work.  Lock order is session.lock -> _cv
    (commit counters update while session locks are held); nothing ever
    acquires a session lock while holding ``_cv``.

    Counters are the authoritative source for the ``/stats`` ``async``
    section and the scrape-time ticket gauges — no shadow counting.
    """

    def __init__(self, manager, window_s: float = 0.002,
                 queue_max: int = 1024, retain: int = 4096,
                 ticket_ttl_s: float = 600.0):
        self.manager = manager
        self.window_s = max(0.0, float(window_s))
        if queue_max < 1:
            raise ValueError(f"async queue_max must be >= 1, got {queue_max}")
        self.queue_max = int(queue_max)
        # resolved-ticket retention: a resolved ticket stays resolvable
        # for ticket_ttl_s seconds (0 disables the clock), with `retain`
        # as the hard size cap either way — bursty small-ticket traffic
        # is bounded by BOTH time and count, not count alone
        self.retain = max(1, int(retain))
        self.ticket_ttl_s = max(0.0, float(ticket_ttl_s))
        self._cv = threading.Condition()
        self._inbox: List[Ticket] = []              # enqueued, unadmitted
        self._per_session: Dict[str, List[Ticket]] = {}     # admitted FIFO
        self._tickets: Dict[str, Ticket] = {}
        self._done_order: deque = deque()           # resolved-ticket eviction
        self._completed_by_sid: Dict[str, int] = {}
        self._next = 0
        # appended to every allocated ticket id ("@<node-tag>" in
        # cluster mode, set by SessionManager.attach_cluster): any
        # front reads the suffix to route GET /result to the owner
        self.id_suffix = ""
        self._thread: Optional[threading.Thread] = None
        self.tickets_enqueued = 0
        self.tickets_completed = 0
        self.tickets_expired = 0        # drained by deadline, pre- or mid-flight
        self.group_dispatches = 0       # watchdogged unit-round chains
        self.unit_rounds = 0            # depth-1 rounds executed (chain links)
        self.board_rounds = 0           # boards x rounds (occupancy numerator)
        self.max_occupancy = 0
        self.solo_tickets = 0           # tickets routed to the solo step path
        self.batched_fallbacks = 0      # group chains that fell back solo

    # -- client side (HTTP worker threads) ---------------------------------

    def submit(self, sid: str, steps: int, deadline,
               qos: str = "standard", cost: float = 0.0) -> Ticket:
        with self._cv:
            depth = (len(self._inbox)
                     + sum(len(q) for q in self._per_session.values()))
            if depth >= self.queue_max:
                raise TicketQueueFullError(
                    f"async queue full ({depth} tickets queued, bound "
                    f"{self.queue_max}); retry later or raise "
                    f"--async-queue-max")
            self._next += 1
            ticket = Ticket(f"t{self._next}{self.id_suffix}", sid, steps,
                            deadline, qos=qos, cost=cost)
            self._tickets[ticket.id] = ticket
            self._inbox.append(ticket)
            self.tickets_enqueued += 1
            if self._thread is None:
                # lazily started: a sync-only server never runs the loop
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="mpi_tpu-dispatch")
                self._thread.start()
            self._cv.notify()
        return ticket

    def get(self, tid: str) -> Ticket:
        with self._cv:
            ticket = self._tickets.get(tid)
        if ticket is None:
            raise KeyError(tid)
        return ticket

    # -- authoritative gauges (scraped + /stats + describe) ----------------

    def queue_depth(self) -> int:
        """Tickets waiting for the dispatch loop (not yet in a round)."""
        with self._cv:
            return (len(self._inbox)
                    + sum(len(q) for q in self._per_session.values()))

    def pending(self) -> int:
        """Tickets enqueued but not yet resolved (includes in-dispatch)."""
        with self._cv:
            return sum(1 for t in self._tickets.values()
                       if t.status == "pending")

    def depth_by_class(self) -> Dict[str, int]:
        """Waiting tickets per priority class (the admission queue-depth
        gauge; every ticket is ``standard`` on an unarmed server)."""
        counts: Dict[str, int] = {}
        with self._cv:
            for t in self._inbox:
                counts[t.qos] = counts.get(t.qos, 0) + 1
            for q in self._per_session.values():
                for t in q:
                    counts[t.qos] = counts.get(t.qos, 0) + 1
        return counts

    def queued_for(self, sid: str) -> int:
        with self._cv:
            return (sum(1 for t in self._inbox if t.sid == sid)
                    + len(self._per_session.get(sid, ())))

    def pending_for(self, sid: str) -> int:
        with self._cv:
            return sum(1 for t in self._tickets.values()
                       if t.sid == sid and t.status == "pending")

    def completed_for(self, sid: str) -> int:
        with self._cv:
            return self._completed_by_sid.get(sid, 0)

    def stats(self) -> dict:
        with self._cv:
            self._evict_locked()        # TTL fires on scrape too, so an
            rounds = self.unit_rounds   # idle server still sheds tickets
            return {
                "queue_depth": (len(self._inbox)
                                + sum(len(q)
                                      for q in self._per_session.values())),
                "tickets_pending": sum(1 for t in self._tickets.values()
                                       if t.status == "pending"),
                "tickets_enqueued": self.tickets_enqueued,
                "tickets_completed": self.tickets_completed,
                "tickets_expired": self.tickets_expired,
                "group_dispatches": self.group_dispatches,
                "unit_rounds": rounds,
                "board_rounds": self.board_rounds,
                "avg_occupancy": (round(self.board_rounds / rounds, 3)
                                  if rounds else None),
                "max_occupancy": self.max_occupancy,
                "solo_tickets": self.solo_tickets,
                "batched_fallbacks": self.batched_fallbacks,
                "window_ms": self.window_s * 1e3,
                "queue_max": self.queue_max,
                "ticket_ttl_s": self.ticket_ttl_s,
                "tickets_retained": len(self._done_order),
            }

    def reset_stats(self) -> None:
        """Zero the throughput counters (the async bench warms compiles,
        then measures a clean window).  The ticket table is untouched —
        resolved tickets must stay resolvable."""
        with self._cv:
            self.group_dispatches = 0
            self.unit_rounds = 0
            self.board_rounds = 0
            self.max_occupancy = 0
            self.solo_tickets = 0
            self.batched_fallbacks = 0

    # -- completion --------------------------------------------------------

    def _complete(self, ticket: Ticket, result=None, error=None) -> None:
        with self._cv:
            if ticket.status != "pending":
                return
            ticket.status = "done" if error is None else "error"
            ticket.result = result
            ticket.error = error
            ticket.done_mono = time.monotonic()
            self.tickets_completed += 1
            self._completed_by_sid[ticket.sid] = (
                self._completed_by_sid.get(ticket.sid, 0) + 1)
            self._done_order.append((ticket.id, ticket.done_mono))
            self._evict_locked()
            callbacks, ticket.callbacks = ticket.callbacks, []
        ticket.event.set()
        # resolution callbacks fire AFTER the event, outside _cv, possibly
        # with session locks held (the group commit loop) — a callback
        # must only flip flags and wake a selector, never block.  This is
        # how the aio front wakes exactly the sockets parked on this
        # ticket instead of burning a thread per waiter.
        for fn in callbacks:
            try:
                fn(ticket)
            except Exception:  # noqa: BLE001 — a waiter must not fail commit
                pass

    def on_resolve(self, tid: str, fn) -> bool:
        """Register ``fn(ticket)`` to run when ``tid`` resolves.  If the
        ticket is already resolved, ``fn`` runs synchronously here and
        False is returned (nothing was parked); True means parked.
        Unknown tickets raise ``KeyError`` (the 404-after-restart
        contract).  Same non-blocking rules as above."""
        with self._cv:
            ticket = self._tickets.get(tid)
            if ticket is None:
                raise KeyError(tid)
            if ticket.status == "pending":
                ticket.callbacks.append(fn)
                return True
        fn(ticket)
        return False

    def cancel_resolve(self, tid: str, fn) -> None:
        """Best-effort unpark (a waiter's wait budget expired first)."""
        with self._cv:
            ticket = self._tickets.get(tid)
            if ticket is not None:
                try:
                    ticket.callbacks.remove(fn)
                except ValueError:
                    pass

    def _evict_locked(self) -> None:  # lint: disable=lock-discipline -- caller holds _cv (_locked suffix contract)
        """Age out the oldest RESOLVED tickets: anything beyond the
        ``retain`` size cap, plus anything older than ``ticket_ttl_s``
        (0 = no clock).  A pending ticket is never evicted — its id must
        resolve.  Caller holds ``_cv``."""
        cutoff = (time.monotonic() - self.ticket_ttl_s
                  if self.ticket_ttl_s else None)
        while self._done_order and (
                len(self._done_order) > self.retain
                or (cutoff is not None and self._done_order[0][1] <= cutoff)):
            tid, _ = self._done_order.popleft()
            self._tickets.pop(tid, None)

    # -- the dispatch loop -------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._inbox and not self._per_session:
                    self._cv.wait()
                fresh_burst = not self._per_session
            if fresh_burst and self.window_s:
                # admission window: let a burst of enqueues land before
                # the first round, so its tickets share the first batch
                time.sleep(self.window_s)
            with self._cv:
                inbox, self._inbox = self._inbox, []
                for t in inbox:
                    self._per_session.setdefault(t.sid, []).append(t)
            try:
                self._run_round()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # a scheduler bug must not strand every pending ticket;
                # the round's heads get the error, the loop continues
                print(f"note: async dispatch round failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                traceback.print_exc(file=sys.stderr)
                with self._cv:
                    heads = [q[0] for q in self._per_session.values() if q]
                for t in heads:
                    self._complete(t, error=RuntimeError(
                        f"async dispatch round failed: "
                        f"{type(e).__name__}: {e}"))

    def _run_round(self) -> None:
        from mpi_tpu.serve.session import DeadlineError

        manager = self.manager
        admission = getattr(manager, "admission", None)
        with self._cv:
            for sid in list(self._per_session):
                q = self._per_session[sid]
                while q and q[0].status != "pending":
                    q.pop(0)
                if not q:
                    del self._per_session[sid]
            all_heads = [q[0] for q in self._per_session.values()]
            if admission is None or not all_heads:
                heads = sorted(all_heads, key=lambda t: t.sid)
            else:
                # cost-aware class scheduling: the weighted picker names
                # the class served this round (interactive > standard >
                # bulk, smooth 4:2:1 — no class with queued work
                # starves), and within the class the cheapest estimated
                # work (CostCard ops) runs first so a bulk mega-board
                # never rides ahead of viewport traffic
                cls = admission.picker.pick(
                    list({t.qos for t in all_heads}))
                heads = sorted((t for t in all_heads if t.qos == cls),
                               key=lambda t: (t.cost, t.sid))
        # deadline drain first: the budget started at enqueue, and an
        # expired ticket must never dispatch (a queued one) nor advance
        # further (a partially-advanced one)
        runnable = []
        for t in heads:
            if t.deadline.expired():
                with self._cv:
                    self.tickets_expired += 1
                done = t.steps - t.remaining
                self._complete(t, error=DeadlineError(
                    f"ticket {t.id} exceeded its "
                    f"{t.deadline.seconds:.3g}s budget while queued "
                    f"({done} of {t.steps} steps dispatched; the session "
                    f"survives)"))
                if manager.obs is not None:
                    # drained on the loop thread: re-enter the minting
                    # context so the expiry is greppable by trace id
                    ttoken = (set_trace_context(t.tctx)
                              if t.tctx is not None else None)
                    try:
                        manager.obs.event("ticket_expired", sid=t.sid,
                                          ticket=t.id, dispatched=done,
                                          rid=t.rid)
                    finally:
                        if ttoken is not None:
                            reset_trace_context(ttoken)
            else:
                runnable.append(t)
        groups: Dict[int, list] = {}
        solos: List[Ticket] = []
        for t in runnable:
            try:
                session = manager.get(t.sid)
            except KeyError as e:
                self._complete(t, error=e)
                continue
            if (session.engine is None or session.plan_sig is None
                    or not manager.cache.breaker_allows(session.plan_sig)):
                # host backends, degraded boards, and quarantined plans
                # take the solo path — manager.step owns breaker
                # handling (degrade or 503) exactly as the sync path does
                solos.append(t)
            else:
                groups.setdefault(id(session.engine),
                                  []).append((t, session))
        for group in groups.values():
            solos.extend(self._run_group(group))
        for t in solos:
            self._run_solo(t)

    def _run_group(self, group) -> List[Ticket]:
        """One cohort-chunked chain for the head tickets sharing an
        engine: boards sorted by remaining depth advance together in
        stacked depth-1 dispatches up to the shallowest cohort's depth,
        finished lanes peel off, and the narrower stack continues —
        every head ticket completes in ONE chain with ONE sync at the
        end.  (The previous ``r = min(remaining)`` round rule made a
        {1, 16} depth mix re-sync for every depth-1 arrival — 16 syncs
        for the deep ticket; cohort lookahead keeps it at one per
        round.)  Returns the tickets that must fall back to the solo
        path (run by the caller AFTER the session locks here are
        released — the solo path takes them itself)."""
        import jax

        from mpi_tpu.serve.session import (
            _Deadline, _watchdog_call, DeadlineError,
        )

        manager = self.manager
        obs = manager.obs
        group.sort(key=lambda ts: ts[1].id)
        engine = group[0][1].engine
        # the watchdog budget for the shared chain is the tightest
        # participant's remaining budget — a timeout fails the chain and
        # every ticket re-tries solo under its OWN deadline
        finite = [t.deadline.remaining() for t, _ in group
                  if t.deadline.seconds is not None]
        deadline = _Deadline(min(finite) if finite else None)
        for _, s in group:
            s.lock.acquire()
        try:
            for t, s in group:
                if s.closed or s.engine is None:
                    self._complete(t, error=KeyError(s.id))
            live = [(t, s) for t, s in group
                    if not (s.closed or s.engine is None)]
            if not live:
                return []
            # ascending remaining depth = the cohort peel order
            live.sort(key=lambda ts: (ts[0].remaining, ts[1].id))
            B = len(live)
            rem = [t.remaining for t, _ in live]
            chain = rem[-1]             # deepest cohort = chain length
            sig = live[0][1].plan_sig
            t1 = time.perf_counter()

            def work():  # lint: disable=lock-discipline -- _run_group holds every participant's session.lock around the chain
                if B == 1:
                    s = live[0][1]
                    s.engine.ensure_compiled(s.grid, 1)
                    g = engine.step_units(s.grid, rem[0])
                    jax.block_until_ready(g)
                    return [g]
                finals = [None] * B
                grids = [s.grid for _, s in live]
                lanes = list(range(B))  # still running, ascending rem
                done = 0                # generations advanced so far
                while lanes:
                    target = rem[lanes[0]]
                    if len(lanes) == 1:
                        i = lanes[0]
                        engine.ensure_compiled(grids[i], 1)
                        grids[i] = engine.step_units(grids[i],
                                                     target - done)
                    else:
                        Bc = len(lanes)
                        stepper, _hit = manager.cache.get_or_build_batched(
                            sig, Bc,
                            lambda Bc=Bc: engine.batched_stepper(Bc))
                        stacked = engine.stack_grids(
                            [grids[i] for i in lanes])
                        engine.ensure_compiled_batched(stacked, 1)
                        for _ in range(target - done):
                            stacked = stepper(stacked, 1)
                        for i, g in zip(lanes,
                                        engine.unstack_grids(stacked)):
                            grids[i] = g
                    done = target
                    nxt = []
                    for i in lanes:
                        if rem[i] == done:
                            finals[i] = grids[i]
                        else:
                            nxt.append(i)
                    lanes = nxt
                jax.block_until_ready(finals)
                return finals

            try:
                boards = _watchdog_call(work, deadline,
                                        f"unit_round[B={B},chain={chain}]")
            except Exception as e:  # noqa: BLE001 — solo fallback decides
                manager._engine_failure(live[0][1], sig, e,
                                        timeout=isinstance(e, DeadlineError))
                with self._cv:
                    self.batched_fallbacks += 1
                return [t for t, _ in live]
            t2 = time.perf_counter()
            if obs is not None:
                # every rider's trace context rides as a *link* — the
                # shared round is related to each minting request, not
                # parented under any one of them
                links = [t.tctx.link() for t, _ in live
                         if t.tctx is not None]
                obs.event("unit_round", t2 - t1, t1, B=B, rounds=chain,
                          cohorts=len(set(rem)),
                          sids=[s.id for _, s in live],
                          request_ids=[t.rid for t, _ in live],
                          **({"links": links} if links else {}))
                obs.occupancy_series.observe(B)
                (obs.dispatch_batched if B > 1
                 else obs.dispatch_solo).observe(t2 - t1)
                # usage ledger: the whole chain is ONE sync (one
                # block_until_ready), however many depth-1 rounds it
                # stacked; FLOPs estimate from the chain's opening
                # (depth-1, B) executable, per board-generation — the
                # cohort peel shrinks B mid-chain, which this ignores
                card = engine.cost_card(1, B if B > 1 else 0)
                pbg = (card.flops / card.boards
                       if card is not None else 0.0)
                obs.ledger.record(
                    "unit", engine.sig_label, t2 - t1,
                    [(s.id, t.remaining,
                      t.remaining * s.config.cells,
                      pbg * t.remaining) for t, s in live])
                fl = obs.flight
                if fl is not None:
                    fl.record("unit_round", engine=engine, steps=chain,
                              batch=B, device_s=t2 - t1,
                              sessions=[s.id for _, s in live],
                              request_ids=[t.rid for t, _ in live],
                              links=links or None)
            per_board = (t2 - t1) / B
            for (t, s), grid in zip(live, boards):
                adv = t.remaining       # cohort chains run to completion
                s.grid = grid
                s.generation += adv
                s.steady_s += per_board
                if B > 1:
                    s.batched_steps += 1
                # commit under the submitter's request id AND trace
                # context so the checkpoint write's span carries both
                # (loop thread)
                token = set_request_id(t.rid)
                ttoken = (set_trace_context(t.tctx)
                          if t.tctx is not None else None)
                try:
                    manager._checkpoint(s)
                finally:
                    if ttoken is not None:
                        reset_trace_context(ttoken)
                    reset_request_id(token)
                manager._notify_step(s)
                t.remaining = 0
                t.unit_rounds += adv
                t.max_batched = max(t.max_batched, B if B > 1 else 0)
                self._complete(t, result={
                    "id": s.id, "generation": s.generation,
                    "steps": t.steps, "async": True,
                    "unit_rounds": t.unit_rounds,
                    "max_batched": t.max_batched})
            manager._mark_dispatch_ok()
            manager.cache.record_success(sig)
            with self._cv:
                self.group_dispatches += 1
                self.unit_rounds += chain
                self.board_rounds += sum(rem)
                self.max_occupancy = max(self.max_occupancy, B)
            return []
        finally:
            for _, s in group:
                s.lock.release()

    def _run_solo(self, ticket: Ticket) -> None:
        """The solo path: ``manager.step`` with the ticket's original
        enqueue deadline, bypassing the sync MicroBatcher (one loop
        thread can never coalesce with itself) but keeping every PR-3
        semantic — breaker check, degrade, retry/backoff, watchdog —
        and chaining the remaining depth as donation-safe unit steps."""
        manager = self.manager
        with self._cv:
            self.solo_tickets += 1
        token = set_request_id(ticket.rid)
        ttoken = (set_trace_context(ticket.tctx)
                  if ticket.tctx is not None else None)
        try:
            res = dict(manager.step(ticket.sid, ticket.remaining,
                                    _deadline=ticket.deadline,
                                    _use_batcher=False, _unit=True))
            res["steps"] = ticket.steps
            res["async"] = True
            res["unit_rounds"] = ticket.unit_rounds + ticket.remaining
            res["max_batched"] = ticket.max_batched
            ticket.unit_rounds += ticket.remaining
            ticket.remaining = 0
            self._complete(ticket, result=res)
        except Exception as e:  # noqa: BLE001 — delivered via the ticket
            if isinstance(e, _deadline_error_type()):
                with self._cv:
                    self.tickets_expired += 1
            self._complete(ticket, error=e)
        finally:
            if ttoken is not None:
                reset_trace_context(ttoken)
            reset_request_id(token)


def _deadline_error_type():
    from mpi_tpu.serve.session import DeadlineError

    return DeadlineError
