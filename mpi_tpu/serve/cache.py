"""LRU cache of compiled engines, keyed by plan signature.

Compilation is the expensive part of a board's life (the reference's
whole setup phase); two boards whose plans agree on everything the traced
program depends on (``mpi_tpu.config.plan_signature``) can share one
:class:`~mpi_tpu.backends.tpu.Engine` and its compiled segment table.
The cache makes "create a second board of the same shape" cost zero new
XLA compiles — the acceptance criterion ``tests/test_serve.py`` asserts
via the counters here plus ``Engine.compile_count``.

A second, batched sub-cache rides along for the microbatch scheduler
(``serve/batch.py``): vmapped batched steppers keyed by
``(plan_signature, B)`` with their own hit/miss/eviction counters, so a
second coalesced batch of the same signature and width reuses the
stepper handle (and, through ``Engine``'s per-``(depth, B)`` executable
table, costs zero new XLA compiles).

The cache also owns the per-signature **circuit breakers** (PR 3): a
plan signature that keeps failing is *quarantined* here — the natural
home, because the signature IS the unit that shares one compiled
engine, so every session riding a sick engine trips (and is protected
by) the same breaker.  ``breaker_threshold`` consecutive failures open
the breaker; ``breaker_cooldown_s`` later it goes half-open and admits
one trial dispatch (success closes it, failure re-opens).  The session
layer consults ``breaker_allows`` before engine dispatches and degrades
affected sessions to the ``serial_np`` oracle while the breaker is
open.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Tuple


class _Breaker:
    """Per-signature failure state (guarded by the cache lock)."""

    __slots__ = ("failures", "opened_at", "trips")

    def __init__(self):
        self.failures = 0
        self.opened_at = None           # monotonic time the breaker opened
        self.trips = 0


def signature_label(signature: tuple) -> str:
    """A compact human-readable tag for a plan signature (stats/healthz
    payloads must not ship a page of Rule repr per breaker)."""
    try:
        rows, cols, rule, boundary, backend, mesh = signature[:6]
        return (f"{rows}x{cols}/{backend}/{boundary}/"
                f"mesh{mesh[0]}x{mesh[1]}/{rule}")
    except Exception:  # noqa: BLE001 — labels are cosmetic, never fatal
        return str(signature)[:120]


class EngineCache:
    """Size-bounded LRU of ``signature -> engine`` with hit/miss/eviction
    counters (surfaced on ``/stats``).

    ``get_or_build`` runs the factory INSIDE the lock: concurrent create
    requests for the same signature must not both pay the compile — the
    second waits and hits.  Builds for different signatures serialize
    too; acceptable for a cache whose values each take seconds of XLA
    time to build (a per-signature lock table would only help the case
    where two *different* expensive plans arrive in the same instant).
    """

    def __init__(self, max_size: int = 8, *, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        if breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got {breaker_threshold}")
        if breaker_cooldown_s < 0:
            raise ValueError(
                f"breaker_cooldown_s must be >= 0, got {breaker_cooldown_s}")
        self.max_size = max_size
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._breakers: dict = {}
        # remote-open quarantines (cluster gossip): signature LABEL ->
        # {"peer", "expires"}.  Labels, not signature tuples — a peer
        # cannot ship a Rule object over the wire, and signature_label
        # is deterministic across processes for identical plans.
        self._remote_open: dict = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.batched_hits = 0
        self.batched_misses = 0
        self.batched_evictions = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        # batched steppers are far cheaper than engines (a handle over an
        # engine the main table already holds), but the bound still keeps
        # a signature churn from growing the table without limit; one
        # entry per (signature, B) — 4 widths per signature by default
        self.batched_max_size = max_size * 4
        self._batched: "OrderedDict[tuple, object]" = OrderedDict()

    def get_or_build(self, signature: tuple,
                     factory: Callable[[], object]) -> Tuple[object, bool]:
        """(engine, hit).  On miss the factory's engine is inserted and the
        least-recently-used entry beyond ``max_size`` is dropped (its
        compiled executables are freed when the last session using it
        lets go — sessions hold their own reference, so eviction never
        yanks an engine out from under a live board)."""
        with self._lock:
            eng = self._entries.get(signature)
            if eng is not None:
                self._entries.move_to_end(signature)
                self.hits += 1
                return eng, True
            self.misses += 1
            eng = factory()
            self._entries[signature] = eng
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
                self.evictions += 1
            return eng, False

    def get_or_build_batched(self, signature: tuple, B: int,
                             factory: Callable[[], object]) -> Tuple[object, bool]:
        """(stepper, hit) for the batched sub-cache, keyed
        ``(signature, B)`` — same inside-the-lock factory discipline as
        :meth:`get_or_build` (concurrent coalesced batches of one shape
        must not both build), same LRU beyond ``batched_max_size``."""
        key = (signature, int(B))
        with self._lock:
            stepper = self._batched.get(key)
            if stepper is not None:
                self._batched.move_to_end(key)
                self.batched_hits += 1
                return stepper, True
            self.batched_misses += 1
            stepper = factory()
            self._batched[key] = stepper
            while len(self._batched) > self.batched_max_size:
                self._batched.popitem(last=False)
                self.batched_evictions += 1
            return stepper, False

    def engines(self) -> list:
        """A snapshot of the cached engines (the obs layer aggregates
        their compile/dispatch counters at scrape time — live sessions
        may hold evicted engines beyond these, which the caller unions
        in)."""
        with self._lock:
            return list(self._entries.values())

    # -- circuit breaker ---------------------------------------------------

    def record_failure(self, signature: tuple) -> bool:
        """Count one engine failure against ``signature``; returns True
        when the breaker is (now) open — i.e. the signature is
        quarantined and the caller should degrade instead of retrying."""
        with self._lock:
            st = self._breakers.get(signature)
            if st is None:
                st = self._breakers[signature] = _Breaker()
            st.failures += 1
            if st.failures >= self.breaker_threshold:
                if st.opened_at is None:
                    st.trips += 1
                # (re)opening refreshes the cooldown clock, so a failed
                # half-open trial buys a full fresh cooldown
                st.opened_at = time.monotonic()
                return True
            return st.opened_at is not None

    def record_success(self, signature: tuple) -> None:
        """A successful engine dispatch closes the breaker and zeroes the
        consecutive-failure count (consecutive means consecutive)."""
        with self._lock:
            st = self._breakers.get(signature)
            if st is not None:
                st.failures = 0
                st.opened_at = None

    def breaker_state(self, signature: tuple) -> str:
        """'closed' | 'open' | 'half_open' (open, cooldown elapsed — one
        trial dispatch is admitted)."""
        with self._lock:
            return self._breaker_state_locked(signature)

    def _breaker_state_locked(self, signature: tuple) -> str:  # lint: disable=lock-discipline -- caller holds self._lock (_locked suffix contract)
        st = self._breakers.get(signature)
        if st is None or st.opened_at is None:
            return "closed"
        if time.monotonic() - st.opened_at >= self.breaker_cooldown_s:
            return "half_open"
        return "open"

    def breaker_allows(self, signature: tuple) -> bool:
        """May the caller dispatch on this signature's engine?  True when
        closed or half-open (the trial); False while open — locally OR
        on a gossiping peer (a sibling's poisoned plan is quarantined
        here before this process burns its own retries).  Remote opens
        have no half-open trial: only the origin dispatches trials, and
        its close propagates by the label leaving its next digest."""
        if self.breaker_state(signature) == "open":
            return False
        with self._lock:
            st = self._remote_open.get(signature_label(signature))
            return st is None or st["expires"] <= time.monotonic()

    def set_remote_open(self, peer: str, labels, ttl_s: float) -> None:
        """Replace ``peer``'s remote-open label set (one gossip digest's
        worth).  Replacement — not accumulation — is what makes the
        origin's breaker CLOSE propagate: a label absent from the next
        digest is dropped here.  ``ttl_s`` bounds how long a quarantine
        outlives its origin's last heartbeat."""
        now = time.monotonic()
        expires = now + max(0.0, float(ttl_s))
        with self._lock:
            self._remote_open = {
                lb: st for lb, st in self._remote_open.items()
                if st["peer"] != peer and st["expires"] > now
            }
            for lb in labels:
                self._remote_open[str(lb)] = {"peer": peer,
                                              "expires": expires}

    def breaker_stats(self) -> dict:
        with self._lock:
            open_, half = [], []
            trips = failures = 0
            for sig, st in self._breakers.items():
                trips += st.trips
                failures += st.failures
                state = self._breaker_state_locked(sig)
                if state == "open":
                    open_.append(signature_label(sig))
                elif state == "half_open":
                    half.append(signature_label(sig))
            now = time.monotonic()
            remote = sorted(lb for lb, st in self._remote_open.items()
                            if st["expires"] > now)
            return {
                "threshold": self.breaker_threshold,
                "cooldown_s": self.breaker_cooldown_s,
                "tracked_signatures": len(self._breakers),
                "trips": trips,
                "consecutive_failures": failures,
                "open": sorted(open_),
                "half_open": sorted(half),
                # quarantines learned from peers — kept apart from
                # "open" so gossip digests (which send "open") never
                # re-announce another node's state
                "remote_open": remote,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, signature: tuple) -> bool:
        with self._lock:
            return signature in self._entries

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._entries),
                "max_size": self.max_size,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "batched": {
                    "size": len(self._batched),
                    "max_size": self.batched_max_size,
                    "hits": self.batched_hits,
                    "misses": self.batched_misses,
                    "evictions": self.batched_evictions,
                },
            }
