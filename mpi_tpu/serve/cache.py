"""LRU cache of compiled engines, keyed by plan signature.

Compilation is the expensive part of a board's life (the reference's
whole setup phase); two boards whose plans agree on everything the traced
program depends on (``mpi_tpu.config.plan_signature``) can share one
:class:`~mpi_tpu.backends.tpu.Engine` and its compiled segment table.
The cache makes "create a second board of the same shape" cost zero new
XLA compiles — the acceptance criterion ``tests/test_serve.py`` asserts
via the counters here plus ``Engine.compile_count``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Tuple


class EngineCache:
    """Size-bounded LRU of ``signature -> engine`` with hit/miss/eviction
    counters (surfaced on ``/stats``).

    ``get_or_build`` runs the factory INSIDE the lock: concurrent create
    requests for the same signature must not both pay the compile — the
    second waits and hits.  Builds for different signatures serialize
    too; acceptable for a cache whose values each take seconds of XLA
    time to build (a per-signature lock table would only help the case
    where two *different* expensive plans arrive in the same instant).
    """

    def __init__(self, max_size: int = 8):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        self.max_size = max_size
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()

    def get_or_build(self, signature: tuple,
                     factory: Callable[[], object]) -> Tuple[object, bool]:
        """(engine, hit).  On miss the factory's engine is inserted and the
        least-recently-used entry beyond ``max_size`` is dropped (its
        compiled executables are freed when the last session using it
        lets go — sessions hold their own reference, so eviction never
        yanks an engine out from under a live board)."""
        with self._lock:
            eng = self._entries.get(signature)
            if eng is not None:
                self._entries.move_to_end(signature)
                self.hits += 1
                return eng, True
            self.misses += 1
            eng = factory()
            self._entries[signature] = eng
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
                self.evictions += 1
            return eng, False

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, signature: tuple) -> bool:
        with self._lock:
            return signature in self._entries

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._entries),
                "max_size": self.max_size,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
