"""LRU cache of compiled engines, keyed by plan signature.

Compilation is the expensive part of a board's life (the reference's
whole setup phase); two boards whose plans agree on everything the traced
program depends on (``mpi_tpu.config.plan_signature``) can share one
:class:`~mpi_tpu.backends.tpu.Engine` and its compiled segment table.
The cache makes "create a second board of the same shape" cost zero new
XLA compiles — the acceptance criterion ``tests/test_serve.py`` asserts
via the counters here plus ``Engine.compile_count``.

A second, batched sub-cache rides along for the microbatch scheduler
(``serve/batch.py``): vmapped batched steppers keyed by
``(plan_signature, B)`` with their own hit/miss/eviction counters, so a
second coalesced batch of the same signature and width reuses the
stepper handle (and, through ``Engine``'s per-``(depth, B)`` executable
table, costs zero new XLA compiles).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Tuple


class EngineCache:
    """Size-bounded LRU of ``signature -> engine`` with hit/miss/eviction
    counters (surfaced on ``/stats``).

    ``get_or_build`` runs the factory INSIDE the lock: concurrent create
    requests for the same signature must not both pay the compile — the
    second waits and hits.  Builds for different signatures serialize
    too; acceptable for a cache whose values each take seconds of XLA
    time to build (a per-signature lock table would only help the case
    where two *different* expensive plans arrive in the same instant).
    """

    def __init__(self, max_size: int = 8):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        self.max_size = max_size
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.batched_hits = 0
        self.batched_misses = 0
        self.batched_evictions = 0
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        # batched steppers are far cheaper than engines (a handle over an
        # engine the main table already holds), but the bound still keeps
        # a signature churn from growing the table without limit; one
        # entry per (signature, B) — 4 widths per signature by default
        self.batched_max_size = max_size * 4
        self._batched: "OrderedDict[tuple, object]" = OrderedDict()

    def get_or_build(self, signature: tuple,
                     factory: Callable[[], object]) -> Tuple[object, bool]:
        """(engine, hit).  On miss the factory's engine is inserted and the
        least-recently-used entry beyond ``max_size`` is dropped (its
        compiled executables are freed when the last session using it
        lets go — sessions hold their own reference, so eviction never
        yanks an engine out from under a live board)."""
        with self._lock:
            eng = self._entries.get(signature)
            if eng is not None:
                self._entries.move_to_end(signature)
                self.hits += 1
                return eng, True
            self.misses += 1
            eng = factory()
            self._entries[signature] = eng
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
                self.evictions += 1
            return eng, False

    def get_or_build_batched(self, signature: tuple, B: int,
                             factory: Callable[[], object]) -> Tuple[object, bool]:
        """(stepper, hit) for the batched sub-cache, keyed
        ``(signature, B)`` — same inside-the-lock factory discipline as
        :meth:`get_or_build` (concurrent coalesced batches of one shape
        must not both build), same LRU beyond ``batched_max_size``."""
        key = (signature, int(B))
        with self._lock:
            stepper = self._batched.get(key)
            if stepper is not None:
                self._batched.move_to_end(key)
                self.batched_hits += 1
                return stepper, True
            self.batched_misses += 1
            stepper = factory()
            self._batched[key] = stepper
            while len(self._batched) > self.batched_max_size:
                self._batched.popitem(last=False)
                self.batched_evictions += 1
            return stepper, False

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, signature: tuple) -> bool:
        with self._lock:
            return signature in self._entries

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._entries),
                "max_size": self.max_size,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "batched": {
                    "size": len(self._batched),
                    "max_size": self.batched_max_size,
                    "hits": self.batched_hits,
                    "misses": self.batched_misses,
                    "evictions": self.batched_evictions,
                },
            }
