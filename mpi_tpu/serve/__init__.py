"""``mpi_tpu.serve`` — persistent multi-session engine service.

The batch engine (``run_tpu``) pays plan + XLA/Mosaic compile on every
invocation and drives exactly one board.  This package keeps the process
alive instead: an :class:`EngineCache` memoizes compiled steppers by plan
signature (``mpi_tpu.config.plan_signature``), a :class:`SessionManager`
owns N independent boards with device-resident state between requests,
and a stdlib-only HTTP front end (``httpd``) exposes the session verbs —
the serving layer the ROADMAP's north star needs on top of the batch
engine.  ``mpi_tpu serve`` (``serve/cli.py``) wires it together.

A :class:`MicroBatcher` (``serve/batch.py``) sits on the step path:
concurrent same-signature same-depth steps are coalesced into one stacked
``[B, ...]`` dispatch through the engine's vmapped batched stepper,
amortizing the fixed per-dispatch tunnel cost (PERF.md: ~68 ms) across B
boards.  Batching is transparent — results are bitwise identical to solo
stepping and any batched-path failure falls back to the solo path.

The fault-tolerance layer (PR 3) rides the same stack: crash-safe
checkpoint/restore (``serve/recovery.py`` + ``--state-dir``), request
deadlines with a dispatch watchdog, a per-plan-signature circuit breaker
that degrades sick engines to the bit-identical ``serial_np`` oracle,
and deterministic fault injection (``serve/faults.py``) to drive every
recovery path under test.

Async ticketed stepping (PR 5, ``serve/ticket.py``) decouples HTTP from
device submission: ``POST /step`` with ``{"async": true}`` returns a
ticket immediately and a per-manager dispatch loop owns the device,
decomposing depth-k tickets into unit steps so mixed-depth sessions
share stacked dispatches (occupancy bounded by concurrency, not depth
agreement).  Tickets carry the full deadline/breaker/watchdog
semantics; the sync path is untouched.

Observability (PR 4, ``mpi_tpu.obs``) threads through every layer as an
optional :class:`~mpi_tpu.obs.Obs` handle (``SessionManager(obs=...)``):
request-id-tagged trace spans, Prometheus-text ``GET /metrics``, and
``POST /debug/profile`` device captures — all off (and off the hot
path) when the handle is None.

The serving edge (PR 7) splits transport from semantics: request
routing/validation/error mapping live in a front-end-agnostic
:class:`~mpi_tpu.serve.transport.AppCore`; ``serve/wire.py`` defines the
binary grid frame both checkpoint records and the HTTP fronts share
(negotiated via ``application/x-gol-grid``); and two fronts drive the
core — the default byte-compatible threaded JSON server
(``serve/httpd.py``) and a selectors event loop (``serve/aio.py``,
``--front aio``) that parks idle ticket waiters as sockets and pushes
chunked binary frames on ``GET /stream/<sid>``.
"""

from mpi_tpu.serve.batch import MicroBatcher
from mpi_tpu.serve.cache import EngineCache
from mpi_tpu.serve.faults import FaultInjector, FaultPlan, InjectedFault
from mpi_tpu.serve.recovery import StateStore
from mpi_tpu.serve.session import (
    DeadlineError,
    EngineStepError,
    EngineUnavailableError,
    SessionManager,
)
from mpi_tpu.serve.ticket import AsyncDispatcher, Ticket, TicketQueueFullError
from mpi_tpu.serve.httpd import make_server
from mpi_tpu.serve.transport import AppCore
from mpi_tpu.serve.wire import WireError, decode_frame, encode_frame

__all__ = [
    "EngineCache", "MicroBatcher", "SessionManager", "make_server",
    "StateStore", "FaultInjector", "FaultPlan", "InjectedFault",
    "DeadlineError", "EngineStepError", "EngineUnavailableError",
    "AsyncDispatcher", "Ticket", "TicketQueueFullError",
    "AppCore", "WireError", "encode_frame", "decode_frame",
]
