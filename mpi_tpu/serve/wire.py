"""Binary wire protocol for grid payloads — the serving stack's ONE codec.

The JSON front ships a 4096x4096 board as ~16.8 MB of '0'/'1' row
strings (snapshot) or, on the persistence path, as base64 of
``np.packbits`` (+33% inflation plus a decode copy).  This module is the
single packbits core both paths share, plus a self-describing binary
*frame* the HTTP fronts negotiate via ``Accept``/``Content-Type:
application/x-gol-grid``: a fixed little-endian header followed by the
raw packed payload — 1 bit per cell on the wire, no base64, no JSON
framing, decodable with one ``struct.unpack_from`` and one
``np.frombuffer`` (no copy until ``unpackbits``).

Frame layout (32-byte header, little-endian, then the payload)::

    offset  size  field
    0       4     magic            b"GOLW"
    4       1     version          1 (classic) or 2 (windowed)
    5       1     flags            bit 0: generation field is meaningful
                                   bit 1: window extension present (v2)
                                   bit 2: payload is a dirty-tile delta
    6       2     boundary id      0 unknown, 1 periodic, 2 dead
    8       4     rule id          crc32 of str(rule); 0 unknown
    12      4     rows             window height for v2
    16      4     cols             window width for v2
    20      8     generation
    28      4     payload length   must equal ceil(rows*cols/8)
                                   (v1 and non-delta v2)

Version-2 frames (the viewport serving plane) extend the header by 16
bytes::

    32      4     x0               window origin row on the board
    36      4     y0               window origin column on the board
    40      4     board rows       full-board height
    44      4     board cols       full-board width

so a consumer knows both what slice it received and how big the world
it came from is.  A v2 frame whose :data:`FLAG_DELTA` bit is set
carries dirty tiles instead of a packed window: the payload is a
``<I`` tile count followed by, per tile, a 16-byte ``r0,c0,rows,cols``
head (window-relative) and ``ceil(rows*cols/8)`` packed bits —
:func:`apply_delta` folds them into the previous window.  v1 frames
are byte-identical to every prior release and remain the default
encoding (:func:`encode_frame`).

The rule/boundary ids are *tags*, not negotiation: the payload's meaning
is fixed by rows x cols packed row-major bits; the ids let a consumer
sanity-check which world a frame came from without a side channel.
Every malformed input — short buffer, wrong magic/version, a header
whose dimensions exceed :data:`MAX_CELLS` or disagree with the payload
length, trailing garbage — raises :class:`WireError` (a ``ValueError``,
so the HTTP layer maps it to a structured 400).

``serve/recovery.py``'s ``encode_grid``/``decode_grid`` are thin JSON
wrappers over :func:`pack_grid`/:func:`unpack_grid`, so checkpoint
records and wire frames can never disagree about packing
(``tests/test_wire.py`` pins old-record compatibility).
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"GOLW"
VERSION = 1
VERSION_WINDOW = 2
FLAG_GENERATION = 0x01
FLAG_WINDOW = 0x02
FLAG_DELTA = 0x04

# magic, version, flags, boundary id, rule id, rows, cols, generation,
# payload length — 32 bytes, no padding ("<" disables alignment)
HEADER = struct.Struct("<4sBBHIIIQI")
HEADER_LEN = HEADER.size
assert HEADER_LEN == 32

# v2 window extension: x0, y0, board rows, board cols
WINDOW_EXT = struct.Struct("<IIII")
HEADER_V2_LEN = HEADER_LEN + WINDOW_EXT.size
assert HEADER_V2_LEN == 48

# delta payload framing: tile count, then per tile r0, c0, rows, cols
# (window-relative) followed by the tile's flat-packed bits
_TILE_COUNT = struct.Struct("<I")
_TILE_HEAD = struct.Struct("<IIII")

# A frame header may promise at most this many cells (a 65536^2 board is
# 2^32; one binade of headroom).  Anything larger is an oversized-header
# attack or corruption, rejected before any allocation is sized off it.
MAX_CELLS = 1 << 34

GRID_MEDIA_TYPE = "application/x-gol-grid"
STREAM_MEDIA_TYPE = "application/x-gol-grid-stream"

_BOUNDARY_IDS = {"periodic": 1, "dead": 2}
_BOUNDARY_NAMES = {v: k for k, v in _BOUNDARY_IDS.items()}


class WireError(ValueError):
    """A malformed binary frame (bad magic/version/geometry/length).
    Maps to HTTP 400 — the client sent garbage, the session is fine."""


# -- the shared packbits core (recovery's JSON wrappers sit on these) ----


def pack_grid(grid: np.ndarray) -> bytes:
    """Row-major 1-bit packing of a 0/1 grid: ceil(rows*cols/8) bytes."""
    arr = np.asarray(grid, dtype=np.uint8)
    if arr.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {arr.shape}")
    return np.packbits(arr, axis=None).tobytes()


def unpack_grid(raw: bytes, rows: int, cols: int) -> np.ndarray:
    """Inverse of :func:`pack_grid` for a known geometry."""
    rows, cols = int(rows), int(cols)
    need = payload_len(rows, cols)
    if len(raw) != need:
        raise WireError(
            f"packed payload is {len(raw)} bytes, {rows}x{cols} needs {need}")
    bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8),
                         count=rows * cols)
    return bits.reshape(rows, cols)


def payload_len(rows: int, cols: int) -> int:
    return (rows * cols + 7) // 8


# -- header tags ---------------------------------------------------------


def boundary_id(boundary: Optional[str]) -> int:
    return _BOUNDARY_IDS.get(boundary, 0) if boundary else 0


def boundary_name(bid: int) -> Optional[str]:
    return _BOUNDARY_NAMES.get(int(bid))


def rule_id(rule) -> int:
    """A stable 32-bit tag for a rule: crc32 of its canonical string
    (``str(Rule)`` round-trips through ``rule_from_name``).  0 = none."""
    if rule is None:
        return 0
    tag = zlib.crc32(str(rule).encode("utf-8")) & 0xFFFFFFFF
    return tag or 1                     # 0 is reserved for "unspecified"


# -- frames --------------------------------------------------------------


def encode_frame(grid: np.ndarray, *, generation: Optional[int] = None,
                 rule=None, boundary: Optional[str] = None) -> bytes:
    """One self-describing binary frame for ``grid``.  ``generation=None``
    leaves the field 0 with :data:`FLAG_GENERATION` clear (a consumer
    must not trust it); board writes use the flag to mean "set the
    session's generation to this"."""
    arr = np.asarray(grid, dtype=np.uint8)
    if arr.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {arr.shape}")
    rows, cols = arr.shape
    flags = 0 if generation is None else FLAG_GENERATION
    payload = pack_grid(arr)
    header = HEADER.pack(MAGIC, VERSION, flags, boundary_id(boundary),
                         rule_id(rule), rows, cols,
                         0 if generation is None else int(generation),
                         len(payload))
    return header + payload


def encode_window_frame(grid: np.ndarray, *, x0: int, y0: int,
                        board_shape: Tuple[int, int],
                        generation: Optional[int] = None,
                        rule=None, boundary: Optional[str] = None) -> bytes:
    """A v2 frame carrying one packed window of a larger board.  The
    payload is the window's cells only — O(viewport) bytes no matter
    how big the board is."""
    arr = np.asarray(grid, dtype=np.uint8)
    if arr.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {arr.shape}")
    rows, cols = arr.shape
    brows, bcols = int(board_shape[0]), int(board_shape[1])
    flags = FLAG_WINDOW | (0 if generation is None else FLAG_GENERATION)
    payload = pack_grid(arr)
    header = HEADER.pack(MAGIC, VERSION_WINDOW, flags, boundary_id(boundary),
                         rule_id(rule), rows, cols,
                         0 if generation is None else int(generation),
                         len(payload))
    ext = WINDOW_EXT.pack(int(x0), int(y0), brows, bcols)
    return header + ext + payload


def encode_delta_frame(tiles, *, window: Tuple[int, int, int, int],
                       board_shape: Tuple[int, int],
                       generation: Optional[int] = None,
                       rule=None, boundary: Optional[str] = None) -> bytes:
    """A v2 dirty-tile delta frame: ``tiles`` is a list of
    ``(r0, c0, tile)`` with window-relative origins; only those cells
    ride the wire.  An empty list is legal — a quiescent generation is
    a 53-byte heartbeat, which is the whole point."""
    x0, y0, h, w = (int(v) for v in window)
    brows, bcols = int(board_shape[0]), int(board_shape[1])
    flags = (FLAG_WINDOW | FLAG_DELTA
             | (0 if generation is None else FLAG_GENERATION))
    parts = [_TILE_COUNT.pack(len(tiles))]
    for r0, c0, tile in tiles:
        arr = np.asarray(tile, dtype=np.uint8)
        tr, tc = arr.shape
        if r0 < 0 or c0 < 0 or r0 + tr > h or c0 + tc > w:
            raise WireError(
                f"delta tile {tr}x{tc}@({r0},{c0}) escapes the "
                f"{h}x{w} window")
        parts.append(_TILE_HEAD.pack(int(r0), int(c0), tr, tc))
        parts.append(pack_grid(arr))
    payload = b"".join(parts)
    header = HEADER.pack(MAGIC, VERSION_WINDOW, flags, boundary_id(boundary),
                         rule_id(rule), h, w,
                         0 if generation is None else int(generation),
                         len(payload))
    ext = WINDOW_EXT.pack(x0, y0, brows, bcols)
    return header + ext + payload


def _decode_tiles(payload, rows: int, cols: int):
    """Parse a delta payload into ``[(r0, c0, tile), ...]``; every byte
    must be accounted for."""
    view = memoryview(payload)
    if len(view) < _TILE_COUNT.size:
        raise WireError("truncated delta payload (no tile count)")
    (count,) = _TILE_COUNT.unpack_from(view, 0)
    pos = _TILE_COUNT.size
    tiles = []
    for _ in range(count):
        if len(view) - pos < _TILE_HEAD.size:
            raise WireError("truncated delta tile head")
        r0, c0, tr, tc = _TILE_HEAD.unpack_from(view, pos)
        pos += _TILE_HEAD.size
        if tr < 1 or tc < 1 or r0 + tr > rows or c0 + tc > cols:
            raise WireError(
                f"delta tile {tr}x{tc}@({r0},{c0}) escapes the "
                f"{rows}x{cols} window")
        nbytes = payload_len(tr, tc)
        if len(view) - pos < nbytes:
            raise WireError("truncated delta tile payload")
        tiles.append((r0, c0,
                      unpack_grid(view[pos:pos + nbytes].tobytes(), tr, tc)))
        pos += nbytes
    if pos != len(view):
        raise WireError(
            f"trailing garbage after delta tiles: {len(view) - pos} bytes")
    return tiles


def apply_delta(window_grid: np.ndarray, tiles) -> np.ndarray:
    """Fold a delta frame's tiles into the previous window state — the
    client half of delta-stream reconstruction.  Returns a new array;
    the input is not mutated."""
    out = np.array(window_grid, dtype=np.uint8, copy=True)
    for r0, c0, tile in tiles:
        out[r0:r0 + tile.shape[0], c0:c0 + tile.shape[1]] = tile
    return out


DELTA_TILE = 64


def diff_tiles(prev: np.ndarray, cur: np.ndarray,
               tile: int = DELTA_TILE):
    """The dirty-tile set between two equal-shape window grids —
    ``[(r0, c0, subgrid), ...]`` with window-relative origins, one
    entry per ``tile``-sized block whose cells changed.  The producer
    half of the delta stream (:func:`apply_delta` is the consumer)."""
    a = np.asarray(prev, dtype=np.uint8)
    b = np.asarray(cur, dtype=np.uint8)
    if a.shape != b.shape:
        raise WireError(
            f"delta base shape {a.shape} does not match {b.shape}")
    changed = a != b
    rows, cols = b.shape
    out = []
    for r0 in range(0, rows, tile):
        r1 = min(r0 + tile, rows)
        for c0 in range(0, cols, tile):
            c1 = min(c0 + tile, cols)
            if changed[r0:r1, c0:c1].any():
                out.append((r0, c0, b[r0:r1, c0:c1]))
    return out


def header_len_of(buf) -> Optional[int]:
    """The full header length of the frame starting at ``buf``, from
    its magic+version prefix alone — or None when fewer than 5 bytes
    are available (wait for more).  A bad magic or unknown version
    raises: the stream is corrupt, not merely short."""
    view = memoryview(buf)
    if len(view) < 5:
        return None
    magic = bytes(view[:4])
    if magic != MAGIC:
        raise WireError(f"bad frame magic {magic!r} (expected {MAGIC!r})")
    version = view[4]
    if version == VERSION:
        return HEADER_LEN
    if version == VERSION_WINDOW:
        return HEADER_V2_LEN
    raise WireError(f"unsupported frame version {version} "
                    f"(expected {VERSION} or {VERSION_WINDOW})")


def parse_header(buf) -> Dict:
    """Validate and decode the header at the start of ``buf`` (32 bytes
    for v1, 48 for v2).

    Returns the meta dict (rows/cols/generation/flags/ids plus
    ``payload_len`` and ``frame_len``) without touching the payload —
    the streaming reassembly entry point: peek the header, wait for
    ``frame_len`` bytes, then :func:`decode_frame` the exact slice."""
    view = memoryview(buf)
    if len(view) < HEADER_LEN:
        raise WireError(
            f"truncated frame header: {len(view)} of {HEADER_LEN} bytes")
    (magic, version, flags, bid, rid, rows, cols, generation,
     plen) = HEADER.unpack_from(view, 0)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {bytes(magic)!r} "
                        f"(expected {MAGIC!r})")
    if version not in (VERSION, VERSION_WINDOW):
        raise WireError(f"unsupported frame version {version} "
                        f"(expected {VERSION} or {VERSION_WINDOW})")
    header_len = HEADER_LEN if version == VERSION else HEADER_V2_LEN
    if rows < 1 or cols < 1:
        raise WireError(f"frame geometry must be positive, got {rows}x{cols}")
    if rows * cols > MAX_CELLS:
        raise WireError(
            f"oversized frame header: {rows}x{cols} exceeds the "
            f"{MAX_CELLS}-cell bound")
    is_delta = bool(flags & FLAG_DELTA)
    window = None
    board_rows, board_cols = rows, cols
    if version == VERSION_WINDOW:
        if len(view) < HEADER_V2_LEN:
            raise WireError(
                f"truncated v2 frame header: {len(view)} of "
                f"{HEADER_V2_LEN} bytes")
        x0, y0, board_rows, board_cols = WINDOW_EXT.unpack_from(
            view, HEADER_LEN)
        if board_rows < 1 or board_cols < 1:
            raise WireError(
                f"board geometry must be positive, got "
                f"{board_rows}x{board_cols}")
        if board_rows * board_cols > MAX_CELLS:
            raise WireError(
                f"oversized board header: {board_rows}x{board_cols} "
                f"exceeds the {MAX_CELLS}-cell bound")
        if x0 >= board_rows or y0 >= board_cols:
            raise WireError(
                f"window origin ({x0},{y0}) is off the "
                f"{board_rows}x{board_cols} board")
        window = (x0, y0, rows, cols)
    elif is_delta:
        raise WireError("delta flag on a v1 frame")
    if is_delta:
        if plen < _TILE_COUNT.size or plen > payload_len(rows, cols) \
                + _TILE_COUNT.size + rows * cols * _TILE_HEAD.size:
            raise WireError(
                f"implausible delta payload length {plen} for a "
                f"{rows}x{cols} window")
    else:
        need = payload_len(rows, cols)
        if plen != need:
            raise WireError(
                f"frame payload length {plen} disagrees with geometry "
                f"{rows}x{cols} (expected {need})")
    return {
        "version": version,
        "flags": flags,
        "boundary_id": bid,
        "boundary": boundary_name(bid),
        "rule_id": rid,
        "rows": rows,
        "cols": cols,
        "generation": generation,
        "has_generation": bool(flags & FLAG_GENERATION),
        "is_delta": is_delta,
        "window": window,
        "board_rows": board_rows,
        "board_cols": board_cols,
        "payload_len": plen,
        "header_len": header_len,
        "frame_len": header_len + plen,
    }


def decode_frame(buf) -> Tuple[Optional[np.ndarray], Dict]:
    """(grid, meta) from exactly one frame.  The buffer must hold the
    frame and nothing else — trailing bytes are rejected (an HTTP body
    is one frame; streams carve exact slices via :func:`parse_header`).
    A delta frame decodes to ``(None, meta)`` with the parsed tiles in
    ``meta["tiles"]`` — fold them with :func:`apply_delta`."""
    meta = parse_header(buf)
    view = memoryview(buf)
    if len(view) < meta["frame_len"]:
        raise WireError(
            f"truncated frame: {len(view)} of {meta['frame_len']} bytes")
    if len(view) > meta["frame_len"]:
        raise WireError(
            f"trailing garbage after frame: {len(view) - meta['frame_len']} "
            f"extra bytes")
    payload = view[meta["header_len"]:meta["frame_len"]]
    if meta["is_delta"]:
        meta["tiles"] = _decode_tiles(payload, meta["rows"], meta["cols"])
        return None, meta
    grid = unpack_grid(payload.tobytes(), meta["rows"], meta["cols"])
    return grid, meta


def split_frames(buf: bytes) -> Tuple[List[Tuple[np.ndarray, Dict]], bytes]:
    """Carve every complete frame off the front of ``buf`` — the client
    half of stream reassembly (chunked transfer does not promise that
    chunk boundaries align with frames, or even that a whole header
    arrives in one read).  Returns (frames, remainder); a malformed
    header raises, a merely-incomplete tail — including a header split
    across reads — does not."""
    out: List[Tuple[np.ndarray, Dict]] = []
    pos = 0
    while True:
        hlen = header_len_of(buf[pos:pos + 5])
        if hlen is None or len(buf) - pos < hlen:
            break                       # header incomplete: wait for bytes
        meta = parse_header(buf[pos:pos + hlen])
        if len(buf) - pos < meta["frame_len"]:
            break
        out.append(decode_frame(buf[pos:pos + meta["frame_len"]]))
        pos += meta["frame_len"]
    return out, buf[pos:]
