"""Binary wire protocol for grid payloads — the serving stack's ONE codec.

The JSON front ships a 4096x4096 board as ~16.8 MB of '0'/'1' row
strings (snapshot) or, on the persistence path, as base64 of
``np.packbits`` (+33% inflation plus a decode copy).  This module is the
single packbits core both paths share, plus a self-describing binary
*frame* the HTTP fronts negotiate via ``Accept``/``Content-Type:
application/x-gol-grid``: a fixed little-endian header followed by the
raw packed payload — 1 bit per cell on the wire, no base64, no JSON
framing, decodable with one ``struct.unpack_from`` and one
``np.frombuffer`` (no copy until ``unpackbits``).

Frame layout (32-byte header, little-endian, then the payload)::

    offset  size  field
    0       4     magic            b"GOLW"
    4       1     version          1
    5       1     flags            bit 0: generation field is meaningful
    6       2     boundary id      0 unknown, 1 periodic, 2 dead
    8       4     rule id          crc32 of str(rule); 0 unknown
    12      4     rows
    16      4     cols
    20      8     generation
    28      4     payload length   must equal ceil(rows*cols/8)

The rule/boundary ids are *tags*, not negotiation: the payload's meaning
is fixed by rows x cols packed row-major bits; the ids let a consumer
sanity-check which world a frame came from without a side channel.
Every malformed input — short buffer, wrong magic/version, a header
whose dimensions exceed :data:`MAX_CELLS` or disagree with the payload
length, trailing garbage — raises :class:`WireError` (a ``ValueError``,
so the HTTP layer maps it to a structured 400).

``serve/recovery.py``'s ``encode_grid``/``decode_grid`` are thin JSON
wrappers over :func:`pack_grid`/:func:`unpack_grid`, so checkpoint
records and wire frames can never disagree about packing
(``tests/test_wire.py`` pins old-record compatibility).
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"GOLW"
VERSION = 1
FLAG_GENERATION = 0x01

# magic, version, flags, boundary id, rule id, rows, cols, generation,
# payload length — 32 bytes, no padding ("<" disables alignment)
HEADER = struct.Struct("<4sBBHIIIQI")
HEADER_LEN = HEADER.size
assert HEADER_LEN == 32

# A frame header may promise at most this many cells (a 65536^2 board is
# 2^32; one binade of headroom).  Anything larger is an oversized-header
# attack or corruption, rejected before any allocation is sized off it.
MAX_CELLS = 1 << 34

GRID_MEDIA_TYPE = "application/x-gol-grid"
STREAM_MEDIA_TYPE = "application/x-gol-grid-stream"

_BOUNDARY_IDS = {"periodic": 1, "dead": 2}
_BOUNDARY_NAMES = {v: k for k, v in _BOUNDARY_IDS.items()}


class WireError(ValueError):
    """A malformed binary frame (bad magic/version/geometry/length).
    Maps to HTTP 400 — the client sent garbage, the session is fine."""


# -- the shared packbits core (recovery's JSON wrappers sit on these) ----


def pack_grid(grid: np.ndarray) -> bytes:
    """Row-major 1-bit packing of a 0/1 grid: ceil(rows*cols/8) bytes."""
    arr = np.asarray(grid, dtype=np.uint8)
    if arr.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {arr.shape}")
    return np.packbits(arr, axis=None).tobytes()


def unpack_grid(raw: bytes, rows: int, cols: int) -> np.ndarray:
    """Inverse of :func:`pack_grid` for a known geometry."""
    rows, cols = int(rows), int(cols)
    need = payload_len(rows, cols)
    if len(raw) != need:
        raise WireError(
            f"packed payload is {len(raw)} bytes, {rows}x{cols} needs {need}")
    bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8),
                         count=rows * cols)
    return bits.reshape(rows, cols)


def payload_len(rows: int, cols: int) -> int:
    return (rows * cols + 7) // 8


# -- header tags ---------------------------------------------------------


def boundary_id(boundary: Optional[str]) -> int:
    return _BOUNDARY_IDS.get(boundary, 0) if boundary else 0


def boundary_name(bid: int) -> Optional[str]:
    return _BOUNDARY_NAMES.get(int(bid))


def rule_id(rule) -> int:
    """A stable 32-bit tag for a rule: crc32 of its canonical string
    (``str(Rule)`` round-trips through ``rule_from_name``).  0 = none."""
    if rule is None:
        return 0
    tag = zlib.crc32(str(rule).encode("utf-8")) & 0xFFFFFFFF
    return tag or 1                     # 0 is reserved for "unspecified"


# -- frames --------------------------------------------------------------


def encode_frame(grid: np.ndarray, *, generation: Optional[int] = None,
                 rule=None, boundary: Optional[str] = None) -> bytes:
    """One self-describing binary frame for ``grid``.  ``generation=None``
    leaves the field 0 with :data:`FLAG_GENERATION` clear (a consumer
    must not trust it); board writes use the flag to mean "set the
    session's generation to this"."""
    arr = np.asarray(grid, dtype=np.uint8)
    if arr.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {arr.shape}")
    rows, cols = arr.shape
    flags = 0 if generation is None else FLAG_GENERATION
    payload = pack_grid(arr)
    header = HEADER.pack(MAGIC, VERSION, flags, boundary_id(boundary),
                         rule_id(rule), rows, cols,
                         0 if generation is None else int(generation),
                         len(payload))
    return header + payload


def parse_header(buf) -> Dict:
    """Validate and decode the 32-byte header at the start of ``buf``.

    Returns the meta dict (rows/cols/generation/flags/ids plus
    ``payload_len`` and ``frame_len``) without touching the payload —
    the streaming reassembly entry point: peek the header, wait for
    ``frame_len`` bytes, then :func:`decode_frame` the exact slice."""
    view = memoryview(buf)
    if len(view) < HEADER_LEN:
        raise WireError(
            f"truncated frame header: {len(view)} of {HEADER_LEN} bytes")
    (magic, version, flags, bid, rid, rows, cols, generation,
     plen) = HEADER.unpack_from(view, 0)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {bytes(magic)!r} "
                        f"(expected {MAGIC!r})")
    if version != VERSION:
        raise WireError(f"unsupported frame version {version} "
                        f"(expected {VERSION})")
    if rows < 1 or cols < 1:
        raise WireError(f"frame geometry must be positive, got {rows}x{cols}")
    if rows * cols > MAX_CELLS:
        raise WireError(
            f"oversized frame header: {rows}x{cols} exceeds the "
            f"{MAX_CELLS}-cell bound")
    need = payload_len(rows, cols)
    if plen != need:
        raise WireError(
            f"frame payload length {plen} disagrees with geometry "
            f"{rows}x{cols} (expected {need})")
    return {
        "version": version,
        "flags": flags,
        "boundary_id": bid,
        "boundary": boundary_name(bid),
        "rule_id": rid,
        "rows": rows,
        "cols": cols,
        "generation": generation,
        "has_generation": bool(flags & FLAG_GENERATION),
        "payload_len": plen,
        "frame_len": HEADER_LEN + plen,
    }


def decode_frame(buf) -> Tuple[np.ndarray, Dict]:
    """(grid, meta) from exactly one frame.  The buffer must hold the
    frame and nothing else — trailing bytes are rejected (an HTTP body
    is one frame; streams carve exact slices via :func:`parse_header`)."""
    meta = parse_header(buf)
    view = memoryview(buf)
    if len(view) < meta["frame_len"]:
        raise WireError(
            f"truncated frame: {len(view)} of {meta['frame_len']} bytes")
    if len(view) > meta["frame_len"]:
        raise WireError(
            f"trailing garbage after frame: {len(view) - meta['frame_len']} "
            f"extra bytes")
    grid = unpack_grid(view[HEADER_LEN:meta["frame_len"]].tobytes(),
                       meta["rows"], meta["cols"])
    return grid, meta


def split_frames(buf: bytes) -> Tuple[List[Tuple[np.ndarray, Dict]], bytes]:
    """Carve every complete frame off the front of ``buf`` — the client
    half of stream reassembly (chunked transfer does not promise that
    chunk boundaries align with frames).  Returns (frames, remainder);
    a malformed header raises, a merely-incomplete tail does not."""
    out: List[Tuple[np.ndarray, Dict]] = []
    pos = 0
    while len(buf) - pos >= HEADER_LEN:
        meta = parse_header(buf[pos:pos + HEADER_LEN])
        if len(buf) - pos < meta["frame_len"]:
            break
        out.append(decode_frame(buf[pos:pos + meta["frame_len"]]))
        pos += meta["frame_len"]
    return out, buf[pos:]
