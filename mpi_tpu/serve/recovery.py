"""Crash-safe session persistence — the serve layer's durable state plane.

A ``kill -9`` of ``mpi_tpu serve`` must not lose live boards, and a torn
write, a flipped bit, or a full disk must not lose them either.  The
paper's design makes the recovery half cheap: stepping is deterministic
from ``(spec, seed)`` and every engine is bit-identical to the
``serial_np`` oracle (PARITY.md), so a session is fully described by its
*spec*, its *generation*, and (as an optimization bounding replay
length) an occasional packed grid snapshot.  This module persists
exactly that, in three durability layers:

**Checksummed record envelopes (v2).**  Each session's full record
lives in ``<sid>.json`` as a binary envelope — a fixed header (magic
``GOLS``, version, payload length) plus a CRC-framed UTF-8 JSON payload,
the same frame discipline as the GOLW wire format (``serve/wire.py``).
A record that fails its CRC (bit rot, a torn ``os.replace``) is
*detected*, never silently decoded.  v1 records (plain JSON, the PR-3
format) are recognized by their leading ``{`` and still load; the first
save after a restore rewrites them as v2 — the auto-upgrade path
MIGRATION.md documents.

**Append-only journals.**  Between full record writes, every committed
step appends one CRC-framed entry to ``<sid>.journal``: a ``mark``
(generation advance only — replay is deterministic), or a content entry
(``rows`` = the whole packed board, ``delta`` = only the packed rows
that changed since the last content entry).  A crash mid-append loses
at most the torn tail entry; the reader stops at the first frame that
fails its CRC.  The journal compacts (one full record write, journal
truncated) when it exceeds ``journal_max_bytes`` or
``journal_max_age_s``.

**A last-good chain.**  Every full record write rotates the previous
head to ``<sid>.json.1`` (→ ``.json.2``, up to ``keep`` ancestors) with
its journal alongside (``<sid>.journal.1`` …).  Restore walks the chain
head-first: a corrupt candidate is quarantined to ``<sid>.corrupt-<n>``
(with a structured stderr warning, like the PR-14 routing-table reset
path) and the walk falls back to the newest verifiable ancestor, then
replays every journal from that depth up to the live one — content
``delta`` entries chain across journal generations because a compaction
record's snapshot is by construction the previous journal's last
content state.

**IO fault choke point.**  Every byte this module writes goes through
:meth:`StateStore._io` — one method covering ``write``/``fsync``/
``replace`` — where the fault DSL's ``io-write``/``io-fsync``/
``io-replace`` sites (``serve/faults.py``) can make the write raise,
tear at a fraction, report ``ENOSPC``, or stall.  Every durability
claim above is asserted under those injected faults.

**Graceful degradation.**  An IO failure moves the store's persistence
state machine ``closed → degraded``: while degraded (and the bounded
exponential backoff has not elapsed) writes fast-fail without touching
the disk and the affected sessions are queued as *pending*.  When the
backoff elapses the next write is the probe; success moves to
``recovering`` while the pending backlog is flushed (full snapshots),
then back to ``closed``.  The serve layer surfaces the state in
``/healthz`` and ``/stats``, sizes ``Retry-After`` from
:meth:`StateStore.retry_in_s`, and — in cluster mode — gossips the
degraded bit so failover never adopts from a node whose recent
checkpoints are known-unwritten.

What does NOT persist (by design): compiled engines (rebuilt lazily on
the first touch, softened by the persistent XLA cache), breaker state
and counters (a restart is the escape hatch a breaker exists to
approximate), and any in-flight step (the client saw an error or a dead
connection, never a commit).  Async tickets (PR 5) keep the same commit
discipline: the dispatch loop persists only AFTER a unit-round chain's
``block_until_ready`` returns, so a ``kill -9`` with tickets in flight
restores to the last completed dispatch.
"""

from __future__ import annotations

import base64
import errno
import json
import os
import re
import struct
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from mpi_tpu.serve import wire

RECORD_VERSION = 2
JOURNAL_VERSION = 1

# record envelope: magic, version, flags, reserved, payload_len, crc32
_REC_MAGIC = b"GOLS"
_REC_HEADER = struct.Struct("<4sBBHII")
# journal entry: magic, version, kind, reserved, generation, payload_len, crc
_JRN_MAGIC = b"GOLJ"
_JRN_HEADER = struct.Struct("<4sBBHQII")
_J_MARK, _J_ROWS, _J_DELTA, _J_SHARD = 0, 1, 2, 3
_J_KINDS = {_J_MARK: "mark", _J_ROWS: "rows", _J_DELTA: "delta",
            _J_SHARD: "shard"}
_ROWS_HEAD = struct.Struct("<II")       # rows, cols
_DELTA_HEAD = struct.Struct("<III")     # rows, cols, changed-row count
# shard content entry: board rows/cols, shard origin r0/c0, shard
# rows/cols, then the shard's flat-packed bits (the same packing as a
# record snapshot's "packed" field, so shard journal entries and shard
# snapshot records can never pack differently)
_SHARD_HEAD = struct.Struct("<IIIIII")
_MAX_PAYLOAD = 1 << 30                  # sanity bound on declared lengths

# persistence state machine backoff: 0.5 s doubling, capped
_BACKOFF_BASE_S = 0.5
_BACKOFF_CAP_S = 30.0


class RecordCorrupt(ValueError):
    """A persisted record or journal frame failed validation (bad magic,
    torn payload, CRC mismatch, malformed JSON) — the restore path
    quarantines and falls back; it never decodes a corrupt frame."""


class StorageDegradedError(OSError):
    """Raised by the store's fast-fail path while persistence is
    degraded (the disk failed and the retry backoff has not elapsed)
    and by the serve layer's ``--state-degrade readonly|shed`` gate.
    The transport maps it to a structured 503 with ``Retry-After``
    sized by ``retry_after_s``."""

    def __init__(self, msg: str, retry_after_s: float = _BACKOFF_BASE_S):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


def encode_grid(grid: np.ndarray) -> dict:
    """A JSON-safe packed snapshot of a 0/1 uint8 grid — a base64
    wrapper over the one packbits core (``serve/wire.py``), so records
    and binary wire frames can never pack differently.  The bytes are
    unchanged from PR 3: existing ``--state-dir`` records decode
    bit-identically (pinned by ``tests/test_wire.py``)."""
    arr = np.asarray(grid, dtype=np.uint8)
    rows, cols = arr.shape
    return {
        "rows": int(rows),
        "cols": int(cols),
        "packed": base64.b64encode(wire.pack_grid(arr)).decode("ascii"),
    }


def encode_grid_shards(tiles, rows: int, cols: int) -> dict:
    """A shard-dimension snapshot: each device shard's tile packed
    independently, so checkpoint and restore stream shard-by-shard and
    never hold one (rows, cols) ndarray.  ``tiles`` is
    ``[(r0, c0, tile_ndarray), ...]`` in board coordinates."""
    return {
        "rows": int(rows),
        "cols": int(cols),
        "shards": [
            {
                "r0": int(r0),
                "c0": int(c0),
                "rows": int(t.shape[0]),
                "cols": int(t.shape[1]),
                "packed": base64.b64encode(wire.pack_grid(t)).decode("ascii"),
            }
            for r0, c0, t in tiles
        ],
    }


def decode_grid(snap: dict) -> np.ndarray:
    rows, cols = int(snap["rows"]), int(snap["cols"])
    if "shards" in snap:
        grid = np.zeros((rows, cols), dtype=np.uint8)
        for sh in snap["shards"]:
            r0, c0 = int(sh["r0"]), int(sh["c0"])
            tr, tc = int(sh["rows"]), int(sh["cols"])
            grid[r0:r0 + tr, c0:c0 + tc] = wire.unpack_grid(
                base64.b64decode(sh["packed"]), tr, tc)
        return grid
    return wire.unpack_grid(base64.b64decode(snap["packed"]), rows, cols)


def snapshot_loader(snap: dict):
    """A region loader ``f(r0, r1, c0, c1) -> uint8`` over a snapshot
    dict — the restore-side half of per-shard checkpointing: a sharded
    engine's ``init_grid`` pulls each device shard's region through
    this, decoding only the stored shards that intersect it, so restore
    never materializes the full board on one host.  Legacy full-grid
    snapshots decode once, lazily."""
    if "shards" in snap:
        shards = [
            (int(sh["r0"]), int(sh["c0"]), int(sh["rows"]), int(sh["cols"]),
             sh["packed"])
            for sh in snap["shards"]
        ]

        def load(r0: int, r1: int, c0: int, c1: int) -> np.ndarray:
            out = np.zeros((r1 - r0, c1 - c0), dtype=np.uint8)
            for sr0, sc0, srows, scols, packed in shards:
                ir0, ir1 = max(r0, sr0), min(r1, sr0 + srows)
                ic0, ic1 = max(c0, sc0), min(c1, sc0 + scols)
                if ir0 >= ir1 or ic0 >= ic1:
                    continue
                tile = wire.unpack_grid(base64.b64decode(packed),
                                        srows, scols)
                out[ir0 - r0:ir1 - r0, ic0 - c0:ic1 - c0] = \
                    tile[ir0 - sr0:ir1 - sr0, ic0 - sc0:ic1 - sc0]
            return out

        return load
    cache = {}

    def load_full(r0: int, r1: int, c0: int, c1: int) -> np.ndarray:
        if "grid" not in cache:
            cache["grid"] = decode_grid(snap)
        return cache["grid"][r0:r1, c0:c1]

    return load_full


# -- envelope / journal frame codecs ---------------------------------------


def _rec_encode(rec: dict) -> bytes:
    payload = json.dumps(rec).encode("utf-8")
    h0 = _REC_HEADER.pack(_REC_MAGIC, RECORD_VERSION, 0, 0, len(payload), 0)
    crc = zlib.crc32(h0 + payload) & 0xFFFFFFFF
    return _REC_HEADER.pack(_REC_MAGIC, RECORD_VERSION, 0, 0,
                            len(payload), crc) + payload


def _rec_validate(rec, want_v) -> dict:
    if (not isinstance(rec, dict)
            or rec.get("v") != want_v
            or not isinstance(rec.get("id"), str)
            or not isinstance(rec.get("spec"), dict)
            or not isinstance(rec.get("generation"), int)):
        raise RecordCorrupt("malformed session record")
    return rec


def _rec_decode(raw: bytes) -> dict:
    """Decode one record file's bytes — v2 envelope or legacy v1 JSON
    (detected by the leading ``{``).  Raises :class:`RecordCorrupt` on
    any validation failure."""
    if not raw:
        raise RecordCorrupt("empty record file")
    if raw[:1] == b"{":                 # v1: plain JSON, no envelope
        try:
            rec = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise RecordCorrupt(f"unparseable v1 record: {e}") from e
        return _rec_validate(rec, 1)
    if len(raw) < _REC_HEADER.size:
        raise RecordCorrupt(f"truncated record header ({len(raw)} bytes)")
    magic, ver, flags, _res, plen, crc = _REC_HEADER.unpack_from(raw)
    if magic != _REC_MAGIC:
        raise RecordCorrupt(f"bad record magic {magic!r}")
    if ver != RECORD_VERSION:
        raise RecordCorrupt(f"unknown record version {ver}")
    if plen > _MAX_PAYLOAD:
        raise RecordCorrupt(f"implausible record payload length {plen}")
    payload = raw[_REC_HEADER.size:]
    if len(payload) != plen:
        raise RecordCorrupt(
            f"torn record ({len(payload)} of {plen} payload bytes)")
    h0 = _REC_HEADER.pack(magic, ver, flags, _res, plen, 0)
    if zlib.crc32(h0 + payload) & 0xFFFFFFFF != crc:
        raise RecordCorrupt("record CRC mismatch")
    try:
        rec = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise RecordCorrupt(f"unparseable record payload: {e}") from e
    return _rec_validate(rec, RECORD_VERSION)


def _jrn_encode(kind: int, generation: int, payload: bytes) -> bytes:
    h0 = _JRN_HEADER.pack(_JRN_MAGIC, JOURNAL_VERSION, kind, 0,
                          generation, len(payload), 0)
    crc = zlib.crc32(h0 + payload) & 0xFFFFFFFF
    return _JRN_HEADER.pack(_JRN_MAGIC, JOURNAL_VERSION, kind, 0,
                            generation, len(payload), crc) + payload


def _jrn_scan(raw: bytes) -> Tuple[List[Tuple[int, int, bytes]], int, bool]:
    """Parse a journal's bytes into ``(entries, good_bytes, torn)``:
    every leading CRC-verified frame, the byte offset they end at, and
    whether trailing bytes were abandoned (a torn tail — the expected
    shape after a crash mid-append)."""
    entries: List[Tuple[int, int, bytes]] = []
    off = 0
    n = len(raw)
    while off + _JRN_HEADER.size <= n:
        magic, ver, kind, _res, gen, plen, crc = _JRN_HEADER.unpack_from(
            raw, off)
        if magic != _JRN_MAGIC or ver != JOURNAL_VERSION \
                or plen > _MAX_PAYLOAD:
            break
        end = off + _JRN_HEADER.size + plen
        if end > n:
            break                       # torn payload
        payload = raw[off + _JRN_HEADER.size:end]
        h0 = _JRN_HEADER.pack(magic, ver, kind, _res, gen, plen, 0)
        if zlib.crc32(h0 + payload) & 0xFFFFFFFF != crc:
            break
        entries.append((kind, gen, payload))
        off = end
    return entries, off, off != n


def _pack_rows(arr: np.ndarray) -> np.ndarray:
    """Per-row packbits (rows x ceil(cols/8)) — the journal's content
    domain, so a delta can address whole packed rows."""
    return np.packbits(np.asarray(arr, dtype=np.uint8), axis=1)


def _unpack_rows(packed: np.ndarray, cols: int) -> np.ndarray:
    return np.unpackbits(packed, axis=1)[:, :cols].astype(np.uint8)


class _ChainState:
    """The working content state of a journal replay: a per-row packed
    matrix (full-board entries) and/or a per-shard tile map (shard
    entries) plus the generations they describe."""

    __slots__ = ("packed", "rows", "cols", "gen", "content_gen", "touched",
                 "shards")

    def __init__(self, packed, rows, cols, gen, content_gen, shards=None):
        self.packed = packed            # (rows, ceil(cols/8)) u8 or None
        self.rows = rows
        self.cols = cols
        self.gen = gen
        self.content_gen = content_gen
        self.touched = False            # any content entry applied?
        # {(r0, c0): (srows, scols, flat_packed_bytes)} — shard-mode
        # content; coexists with ``packed`` only across a mode switch
        # (old full record + new shard commits), where assembly overlays
        # the tiles on the unpacked base
        self.shards = shards

    def apply(self, kind: int, gen: int, payload: bytes) -> bool:
        """Fold one journal entry; False means the chain is broken at
        this entry (stop the replay, keep what was recovered)."""
        if gen < self.gen:
            return True                 # superseded by a newer record
        if kind == _J_MARK:
            self.gen = gen
            return True
        if kind == _J_ROWS:
            if len(payload) < _ROWS_HEAD.size:
                return False
            rows, cols = _ROWS_HEAD.unpack_from(payload)
            nbytes = rows * ((cols + 7) // 8)
            if rows < 1 or cols < 1 or len(payload) != _ROWS_HEAD.size + nbytes:
                return False
            self.packed = np.frombuffer(
                payload, dtype=np.uint8, offset=_ROWS_HEAD.size,
            ).reshape(rows, (cols + 7) // 8).copy()
            self.rows, self.cols = rows, cols
            self.shards = None          # a full-board entry supersedes tiles
            self.gen = self.content_gen = gen
            self.touched = True
            return True
        if kind == _J_DELTA:
            if self.packed is None or len(payload) < _DELTA_HEAD.size:
                return False
            rows, cols, count = _DELTA_HEAD.unpack_from(payload)
            if rows != self.rows or cols != self.cols:
                return False
            rb = (cols + 7) // 8
            want = _DELTA_HEAD.size + count * (4 + rb)
            if count > rows or len(payload) != want:
                return False
            if count:
                idx = np.frombuffer(payload, dtype="<u4",
                                    offset=_DELTA_HEAD.size, count=count)
                if int(idx.max()) >= rows:
                    return False
                data = np.frombuffer(
                    payload, dtype=np.uint8,
                    offset=_DELTA_HEAD.size + 4 * count,
                ).reshape(count, rb)
                self.packed[idx.astype(np.int64)] = data
            self.gen = self.content_gen = gen
            self.touched = True
            return True
        if kind == _J_SHARD:
            if len(payload) < _SHARD_HEAD.size:
                return False
            brows, bcols, r0, c0, srows, scols = _SHARD_HEAD.unpack_from(
                payload)
            nbytes = (srows * scols + 7) // 8
            if (srows < 1 or scols < 1 or brows < 1 or bcols < 1
                    or r0 + srows > brows or c0 + scols > bcols
                    or len(payload) != _SHARD_HEAD.size + nbytes):
                return False
            if self.rows and (brows != self.rows or bcols != self.cols):
                return False
            if self.shards is None:
                self.shards = {}
            self.shards[(r0, c0)] = (srows, scols,
                                     payload[_SHARD_HEAD.size:])
            self.rows, self.cols = brows, bcols
            self.gen = self.content_gen = gen
            self.touched = True
            return True
        return False                    # unknown kind: future version

    def snapshot(self) -> dict:
        """The replay result as a record snapshot dict (no generation
        key — the caller stamps ``content_gen``).  Pure shard mode
        emits a shard-form snapshot; a mode mix (full base overlaid
        with shard tiles) assembles and re-encodes full."""
        if self.shards and self.packed is None:
            return {
                "rows": int(self.rows),
                "cols": int(self.cols),
                "shards": [
                    {"r0": int(r0), "c0": int(c0), "rows": int(sr),
                     "cols": int(sc),
                     "packed": base64.b64encode(pk).decode("ascii")}
                    for (r0, c0), (sr, sc, pk) in sorted(self.shards.items())
                ],
            }
        if self.shards:
            grid = _unpack_rows(self.packed, self.cols)
            for (r0, c0), (sr, sc, pk) in sorted(self.shards.items()):
                grid[r0:r0 + sr, c0:c0 + sc] = wire.unpack_grid(
                    bytes(pk), sr, sc)
            return encode_grid(grid)
        return encode_grid(_unpack_rows(self.packed, self.cols))


class _JournalTrack:
    """Per-sid append-side state: the last journaled content (packed
    per-row) deltas diff against, and the live journal's durable size/
    age for compaction triggers.  Guarded by the owning session's lock
    (the same discipline as ``save``)."""

    __slots__ = ("prev", "gen", "size", "entries", "opened", "prev_shards")

    def __init__(self, prev, gen, prev_shards=None):
        self.prev = prev                # packed per-row content or None
        self.gen = gen
        self.size = 0                   # durable (fsynced) journal bytes
        self.entries = 0
        self.opened = time.monotonic()
        # {(r0, c0): flat_packed_bytes} — the last journaled per-shard
        # content, so a shard commit appends only the tiles that changed
        self.prev_shards = prev_shards


class StateStore:
    """One durable record chain per session under ``state_dir``.

    Record payload shape (v2 envelope; v1 was the same dict as bare
    JSON)::

        {"v": 2, "id": "s3", "spec": {...create body...},
         "generation": 41,
         "snapshot": {"generation": 32, "rows": ..., "cols": ...,
                      "packed": "<base64 np.packbits>"} | null}

    ``save``/``commit_step`` are called with the owning session's lock
    held (generation and snapshot must leave the lock together — the
    same torn-read discipline as the live snapshot verb), so the store's
    own lock only guards counters, the persistence state machine, and
    the shared tmp-name sequence.
    """

    def __init__(self, state_dir: str, checkpoint_every: int = 64, *,
                 journal: bool = True,
                 journal_max_bytes: int = 1 << 20,
                 journal_max_age_s: float = 300.0,
                 keep: int = 2):
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        if journal_max_bytes < 1:
            raise ValueError("journal_max_bytes must be >= 1")
        if journal_max_age_s <= 0:
            raise ValueError("journal_max_age_s must be > 0")
        if keep < 0:
            raise ValueError("keep must be >= 0")
        self.state_dir = state_dir
        self.checkpoint_every = int(checkpoint_every)
        self.journal = bool(journal)
        self.journal_max_bytes = int(journal_max_bytes)
        self.journal_max_age_s = float(journal_max_age_s)
        self.keep = int(keep)
        os.makedirs(state_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tmp_seq = 0
        self.writes = 0
        self.write_s = 0.0              # accumulated save wall (obs reads it)
        self.snapshot_writes = 0
        self.deletes = 0
        self.load_errors = 0
        # durable-state-plane counters (PR 18)
        self.bytes_full = 0             # record-envelope bytes written
        self.bytes_delta = 0            # journal-entry bytes appended
        self.journal_appends = 0
        self.compactions = 0
        self.corrupt_records = 0        # records quarantined at load
        self.torn_journals = 0          # journals with an abandoned tail
        self.persist_skipped = 0        # writes fast-failed while degraded
        # io fault hook (``FaultInjector.io_hook``) and obs handle; both
        # installed by the SessionManager when armed, both optional
        self.fault_hook = None
        self.obs = None
        self._jrn: Dict[str, _JournalTrack] = {}
        # persistence state machine: closed -> degraded -> recovering
        self._state = "closed"
        self._failures = 0
        self._retry_at = 0.0
        self._pending: set = set()
        self._pending_deletes: set = set()

    # -- paths -------------------------------------------------------------

    def _path(self, sid: str) -> str:
        # session ids are manager-generated ("s<N>") — no traversal risk,
        # but keep the guard so a hand-edited state dir cannot escape
        safe = "".join(ch for ch in sid if ch.isalnum() or ch in "-_")
        return os.path.join(self.state_dir, f"{safe}.json")

    def _jpath(self, sid: str) -> str:
        return f"{self._path(sid)[:-5]}.journal"

    # -- fault choke point --------------------------------------------------

    def _io(self, op: str, a, b=None) -> None:
        """Every byte this store persists flows through here: ``op`` is
        ``write`` (file object, bytes), ``fsync`` (file object), or
        ``replace`` (src, dst).  The fault hook may raise (``raise``/
        ``enospc`` modes), stall (``delay``), or return a tear fraction
        (``torn`` — the write stops at that fraction, flushes the torn
        prefix so it is really on disk, then fails like the kernel
        would)."""
        hook = self.fault_hook
        frac = hook(f"io-{op}") if hook is not None else None
        if op == "write":
            if frac is not None:
                a.write(b[:max(0, int(len(b) * min(1.0, frac)))])
                a.flush()
                raise OSError(errno.EIO,
                              f"injected torn write ({frac:g} of "
                              f"{len(b)} bytes)")
            a.write(b)
        elif op == "fsync":
            if frac is not None:
                raise OSError(errno.EIO, "injected torn fsync")
            a.flush()
            os.fsync(a.fileno())
        else:                           # replace
            if frac is not None:
                raise OSError(errno.EIO, "injected torn replace")
            os.replace(a, b)

    # -- persistence state machine ------------------------------------------

    def _gate(self, sid: str) -> None:
        """Fast-fail while degraded and the backoff has not elapsed: the
        session is queued as pending and the disk is not touched.  The
        first write after the backoff elapses is the recovery probe."""
        with self._lock:
            if self._state != "degraded":
                return
            wait = self._retry_at - time.monotonic()
            if wait <= 0:
                return                  # backoff elapsed: probe the disk
            self._pending.add(sid)
            self.persist_skipped += 1
        raise StorageDegradedError(
            f"persistence degraded; retry in {wait:.2f}s", wait)

    def _io_fail(self, sid: Optional[str]) -> None:
        with self._lock:
            self._failures += 1
            newly = self._state != "degraded"
            self._state = "degraded"
            backoff = min(_BACKOFF_CAP_S,
                          _BACKOFF_BASE_S * (2 ** min(self._failures - 1, 10)))
            self._retry_at = time.monotonic() + backoff
            if sid is not None:
                self._pending.add(sid)
        if newly:
            print(f"warning: persistence DEGRADED under {self.state_dir} "
                  f"(write failed); retrying in {backoff:.1f}s, sessions "
                  f"keep serving", file=sys.stderr)

    def _io_ok(self, sid: Optional[str]) -> None:
        with self._lock:
            if self._state == "closed":
                return
            if sid is not None:
                self._pending.discard(sid)
            if self._pending or self._pending_deletes:
                self._state = "recovering"
            else:
                self._state = "closed"
                self._failures = 0
                self._retry_at = 0.0

    def is_degraded(self) -> bool:
        with self._lock:
            return self._state == "degraded"

    def retry_ready(self) -> bool:
        """True when :meth:`SessionManager.persistence_retry` has work:
        the backoff elapsed on a degraded store, or a recovering store
        still has a pending backlog to flush."""
        with self._lock:
            if self._state == "recovering":
                return bool(self._pending or self._pending_deletes)
            return (self._state == "degraded"
                    and time.monotonic() >= self._retry_at)

    def retry_in_s(self) -> float:
        """Seconds until the next recovery probe — what the transport
        sizes ``Retry-After`` from."""
        with self._lock:
            if self._state != "degraded":
                return 0.0
            return max(0.0, self._retry_at - time.monotonic())

    def take_pending(self) -> List[str]:
        with self._lock:
            return sorted(self._pending)

    def take_pending_deletes(self) -> List[str]:
        with self._lock:
            return sorted(self._pending_deletes)

    def discard_pending(self, sid: str) -> None:
        with self._lock:
            self._pending.discard(sid)
            if self._state != "closed" \
                    and not (self._pending or self._pending_deletes) \
                    and self._state == "recovering":
                self._state = "closed"
                self._failures = 0

    def persistence_state(self) -> dict:
        with self._lock:
            retry = (max(0.0, self._retry_at - time.monotonic())
                     if self._state == "degraded" else 0.0)
            return {
                "state": self._state,
                "pending": len(self._pending) + len(self._pending_deletes),
                "failures": self._failures,
                "retry_in_s": round(retry, 3),
            }

    # -- write path --------------------------------------------------------

    def save(self, sid: str, spec: dict, generation: int,
             snapshot: Optional[dict], *, compaction: bool = False) -> None:
        """Atomically (re)write the full record for ``sid`` inside a v2
        CRC envelope, rotating the previous head (and its journal) one
        step down the last-good chain.  ``snapshot`` is the encoded grid
        dict plus its ``generation`` key, or None (replay will start
        from the seed).  Raises ``OSError`` on IO failure — the caller
        decides whether durability is best-effort (step path) or
        mandatory (drain)."""
        rec = {
            "v": RECORD_VERSION,
            "id": sid,
            "spec": spec,
            "generation": int(generation),
            "snapshot": snapshot,
        }
        blob = _rec_encode(rec)
        path = self._path(sid)
        self._gate(sid)
        t0 = time.perf_counter()
        with self._lock:
            self._tmp_seq += 1
            tmp = f"{path}.tmp{self._tmp_seq}"
        try:
            with open(tmp, "wb") as f:
                self._io("write", f, blob)
                self._io("fsync", f)
            if self.keep:
                self._rotate(sid)
            self._io("replace", tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            self._io_fail(sid)
            raise
        self._io_ok(sid)
        with self._lock:
            self.writes += 1
            self.write_s += time.perf_counter() - t0
            self.bytes_full += len(blob)
            if snapshot is not None:
                self.snapshot_writes += 1
            if compaction:
                self.compactions += 1
        if self.journal:
            prev, prev_shards = None, None
            if snapshot is not None and "shards" in snapshot:
                prev_shards = {
                    (int(sh["r0"]), int(sh["c0"])):
                        base64.b64decode(sh["packed"])
                    for sh in snapshot["shards"]
                }
            elif snapshot is not None:
                prev = _pack_rows(decode_grid(snapshot))
            with self._lock:
                self._jrn[sid] = _JournalTrack(prev, int(generation),
                                               prev_shards)

    def _rotate(self, sid: str) -> None:
        """Shift the head record and its journal one step down the
        ancestor chain (``.json``→``.json.1``→…), deepest first.  A
        missing source removes its destination so record/journal pairs
        never mismatch across depths."""
        path, jpath = self._path(sid), self._jpath(sid)
        for d in range(self.keep, 0, -1):
            src_r = path if d == 1 else f"{path}.{d - 1}"
            src_j = jpath if d == 1 else f"{jpath}.{d - 1}"
            self._shift(src_r, f"{path}.{d}")
            self._shift(src_j, f"{jpath}.{d}")

    @staticmethod
    def _shift(src: str, dst: str) -> None:
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            try:
                os.remove(dst)
            except FileNotFoundError:
                pass

    def commit_step(self, sid: str, spec: dict, generation: int,
                    snapshot: Optional[dict], grid=None,
                    shards=None) -> dict:
        """The step-commit persistence verb: append journal entries
        when journaling (a content ``rows``/``delta`` entry when
        ``grid`` rode along, one ``shard`` entry per *changed* device
        shard when ``shards=(brows, bcols, tiles)`` rode along, a
        ``mark`` otherwise), or rewrite the full record (journaling
        off, no chain base yet, or compaction due).  Returns
        ``{"form": "record"|"journal", "kind", "bytes", "compacted"}``
        for the caller's observability.  Raises ``OSError`` like
        :meth:`save`."""
        if not self.journal:
            self.save(sid, spec, generation, snapshot)
            return {"form": "record", "kind": None, "bytes": 0,
                    "compacted": False}
        with self._lock:
            st = self._jrn.get(sid)
        if st is None:                  # no chain base yet: full record
            self.save(sid, spec, generation, snapshot)
            return {"form": "record", "kind": None, "bytes": 0,
                    "compacted": False}
        if st.entries and (st.size >= self.journal_max_bytes
                           or time.monotonic() - st.opened
                           >= self.journal_max_age_s):
            self.save(sid, spec, generation, snapshot, compaction=True)
            return {"form": "record", "kind": None, "bytes": 0,
                    "compacted": True}
        new_shards = None
        if shards is not None:
            kind, blob, new_shards = self._encode_step_shards(
                st, int(generation), shards)
        else:
            kind, payload = self._encode_step(st, grid)
            blob = _jrn_encode(kind, int(generation), payload)
        self._gate(sid)
        jpath = self._jpath(sid)
        try:
            exists = os.path.exists(jpath)
            with open(jpath, "r+b" if exists else "wb") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() != st.size:
                    # a previously torn append left a bad tail: truncate
                    # back to the last durable entry boundary before
                    # appending, so the reader never loses good entries
                    # behind a torn one
                    f.seek(st.size)
                    f.truncate()
                self._io("write", f, blob)
                self._io("fsync", f)
        except OSError:
            self._io_fail(sid)
            raise
        self._io_ok(sid)
        st.size += len(blob)
        st.entries += 1
        st.gen = int(generation)
        if kind != _J_MARK and grid is not None:
            st.prev = _pack_rows(grid)
        if new_shards is not None:
            st.prev_shards = new_shards
        with self._lock:
            self.journal_appends += 1
            self.bytes_delta += len(blob)
        return {"form": "journal", "kind": _J_KINDS[kind],
                "bytes": len(blob), "compacted": False}

    @staticmethod
    def _encode_step_shards(st: _JournalTrack, generation: int,
                            shards) -> Tuple[int, bytes, Optional[dict]]:
        """Encode a shard-dimension commit: one ``shard`` journal frame
        per tile whose packed content changed since the last journaled
        state (all of them when there is no shard baseline), CRC-framed
        independently so a torn multi-shard append loses only its tail.
        A quiescent commit degenerates to a ``mark``."""
        brows, bcols, tiles = shards
        prev = st.prev_shards
        frames = []
        new_prev = {} if prev is None else dict(prev)
        for r0, c0, tile in tiles:
            arr = np.asarray(tile, dtype=np.uint8)
            packed = wire.pack_grid(arr)
            key = (int(r0), int(c0))
            if prev is not None and prev.get(key) == packed:
                continue
            new_prev[key] = packed
            head = _SHARD_HEAD.pack(int(brows), int(bcols), key[0], key[1],
                                    arr.shape[0], arr.shape[1])
            frames.append(_jrn_encode(_J_SHARD, generation, head + packed))
        if not frames:
            return _J_MARK, _jrn_encode(_J_MARK, generation, b""), new_prev
        return _J_SHARD, b"".join(frames), new_prev

    @staticmethod
    def _encode_step(st: _JournalTrack, grid) -> Tuple[int, bytes]:
        if grid is None:
            return _J_MARK, b""
        arr = np.asarray(grid, dtype=np.uint8)
        rows, cols = arr.shape
        packed = _pack_rows(arr)
        if st.prev is None or st.prev.shape != packed.shape:
            return _J_ROWS, _ROWS_HEAD.pack(rows, cols) + packed.tobytes()
        changed = np.nonzero(np.any(packed != st.prev, axis=1))[0]
        # past half the board a full-rows entry is smaller than the
        # delta's index overhead — and it re-anchors the chain
        if len(changed) * (4 + packed.shape[1]) >= packed.nbytes:
            return _J_ROWS, _ROWS_HEAD.pack(rows, cols) + packed.tobytes()
        head = _DELTA_HEAD.pack(rows, cols, len(changed))
        return _J_DELTA, head + changed.astype("<u4").tobytes() \
            + packed[changed].tobytes()

    def delete(self, sid: str) -> None:
        path, jpath = self._path(sid), self._jpath(sid)
        targets = [path, jpath]
        targets += [f"{path}.{d}" for d in range(1, self.keep + 1)]
        targets += [f"{jpath}.{d}" for d in range(1, self.keep + 1)]
        failed = False
        for p in targets:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            except OSError:
                failed = True
        with self._lock:
            self.deletes += 1
            self._jrn.pop(sid, None)
            self._pending.discard(sid)
            if failed:
                self._pending_deletes.add(sid)
            else:
                self._pending_deletes.discard(sid)
        if failed:
            self._io_fail(None)

    def retry_deletes(self) -> None:
        """Re-attempt deletes that failed while the disk was sick (part
        of the recovery flush)."""
        for sid in self.take_pending_deletes():
            with self._lock:
                self._pending_deletes.discard(sid)
            self.delete(sid)
            self._io_ok(None)

    def forget(self, sid: str) -> None:
        """Drop in-memory chain state without touching disk (the drain
        handoff: the successor restores from the durable record)."""
        with self._lock:
            self._jrn.pop(sid, None)
            self._pending.discard(sid)

    # -- read path ---------------------------------------------------------

    def _quarantine(self, path: str, sid: str, reason: str) -> None:
        base = self._path(sid)[:-5]
        n = 1
        while os.path.exists(f"{base}.corrupt-{n}"):
            n += 1
        qpath = f"{base}.corrupt-{n}"
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = None
        with self._lock:
            self.corrupt_records += 1
        print(f"warning: quarantined corrupt state record {path}"
              f"{' -> ' + qpath if qpath else ''} ({reason}); "
              f"falling back to last-good ancestor", file=sys.stderr)
        obs = self.obs
        if obs is not None:
            obs.event("state_quarantine", sid=sid,
                      path=os.path.basename(path), reason=reason)

    def _load_chain(self, sid: str) -> Optional[dict]:
        """Walk ``sid``'s last-good chain: quarantine corrupt records
        head-first, anchor on the newest verifiable one, then fold in
        every journal from that depth up to the live one.  Returns a
        v1-shaped record dict (``generation`` advanced to the last
        journaled one, ``snapshot`` replaced by the last journaled
        content) or None when nothing was verifiable."""
        path = self._path(sid)
        base, depth = None, 0
        for d in range(0, self.keep + 1):
            p = path if d == 0 else f"{path}.{d}"
            try:
                with open(p, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                continue
            except OSError:
                continue
            try:
                rec = _rec_decode(raw)
                if rec["id"] != sid:
                    raise RecordCorrupt(
                        f"record names {rec['id']!r}, expected {sid!r}")
            except RecordCorrupt as e:
                self._quarantine(p, sid, str(e))
                continue
            base, depth = rec, d
            break
        if base is None:
            return None
        snap = base.get("snapshot")
        if snap is not None:
            try:
                if "shards" in snap:
                    shards = {
                        (int(sh["r0"]), int(sh["c0"])):
                            (int(sh["rows"]), int(sh["cols"]),
                             base64.b64decode(sh["packed"]))
                        for sh in snap["shards"]
                    }
                    chain = _ChainState(None,
                                        int(snap["rows"]), int(snap["cols"]),
                                        int(base["generation"]),
                                        int(snap["generation"]),
                                        shards=shards)
                else:
                    chain = _ChainState(_pack_rows(decode_grid(snap)),
                                        int(snap["rows"]), int(snap["cols"]),
                                        int(base["generation"]),
                                        int(snap["generation"]))
            except (KeyError, TypeError, ValueError):
                return None             # snapshot dict itself is malformed
        else:
            chain = _ChainState(None, 0, 0, int(base["generation"]), 0)
        jpath = self._jpath(sid)
        stop = False
        for k in range(depth, -1, -1):
            if stop:
                break
            jp = jpath if k == 0 else f"{jpath}.{k}"
            try:
                with open(jp, "rb") as f:
                    jraw = f.read()
            except (FileNotFoundError, OSError):
                continue
            entries, _good, torn = _jrn_scan(jraw)
            if torn:
                with self._lock:
                    self.torn_journals += 1
            for kind, gen, payload in entries:
                if not chain.apply(kind, gen, payload):
                    stop = True
                    break
        out = dict(base)
        out["v"] = RECORD_VERSION
        out["generation"] = chain.gen
        if chain.touched:
            ns = chain.snapshot()
            ns["generation"] = chain.content_gen
            out["snapshot"] = ns
        return out

    def _sid_set(self) -> List[str]:
        try:
            names = os.listdir(self.state_dir)
        except FileNotFoundError:
            return []
        sids = set()
        for name in names:
            # session records only: the "s"-prefix discipline of
            # list_ids().  The dir is shared with per-node routing
            # tables (routing-<tag>.json) — those are the cluster
            # layer's files, not session records, and must never be
            # "restored" (or quarantined as corrupt records) here.
            if not name.startswith("s"):
                continue
            if name.endswith(".json"):
                sids.add(name[:-5])
            else:
                m = re.match(r"(.+)\.json\.\d+$", name)
                if m:
                    sids.add(m.group(1))
        return sorted(sids)

    def load_records(self) -> List[Dict]:
        """Every recoverable record, ordered by numeric session id (so
        restored ids and the id counter line up deterministically).
        Corrupt heads fall back down their last-good chain; sessions
        with nothing verifiable are skipped and counted
        (``load_errors``) — a recovery pass must salvage what it can,
        not die on the one record a crash mangled."""
        out = []
        for sid in self._sid_set():
            rec = self._load_chain(sid)
            if rec is None:
                with self._lock:
                    self.load_errors += 1
                continue
            out.append(rec)
        out.sort(key=lambda r: _sid_ordinal(r["id"]))
        return out

    def load_record(self, sid: str) -> Optional[Dict]:
        """The one recoverable record for ``sid``, or None (missing —
        closed or never checkpointed — or corrupt with no verifiable
        ancestor, which also counts a load error).  The failover
        adoption path reads exactly one session, verifying every byte
        before adopting; scanning the whole dir per adoption would be
        O(n²) across a dead node's sessions."""
        path = self._path(sid)
        exists = any(os.path.exists(p) for p in
                     [path] + [f"{path}.{d}" for d in range(1, self.keep + 1)])
        if not exists:
            return None
        rec = self._load_chain(sid)
        if rec is None:
            with self._lock:
                self.load_errors += 1
        return rec

    def list_ids(self) -> List[str]:
        """Session ids with a record on disk — filename-derived, no
        parsing (failover scans this for the dead node's tag suffix)."""
        try:
            names = sorted(os.listdir(self.state_dir))
        except FileNotFoundError:
            return []
        return [name[:-5] for name in names
                if name.endswith(".json") and name.startswith("s")]

    def stats(self) -> dict:
        with self._lock:
            return {
                "state_dir": self.state_dir,
                "checkpoint_every": self.checkpoint_every,
                "journal": self.journal,
                "writes": self.writes,
                "write_s": round(self.write_s, 6),
                "snapshot_writes": self.snapshot_writes,
                "deletes": self.deletes,
                "load_errors": self.load_errors,
                "bytes_full": self.bytes_full,
                "bytes_delta": self.bytes_delta,
                "journal_appends": self.journal_appends,
                "compactions": self.compactions,
                "corrupt_records": self.corrupt_records,
                "torn_journals": self.torn_journals,
                "persist_skipped": self.persist_skipped,
                "persistence": self._state,
            }


def _sid_ordinal(sid: str) -> int:
    # the leading digit run only: cluster-format ids ("s5-ab12cd")
    # must sort by ordinal like plain ones, not saturate the counter
    m = re.match(r"s(\d+)", sid)
    return int(m.group(1)) if m else 1 << 30


# -- offline verification (tools/scrub.py) ---------------------------------


def _snapshot_issue(rec: dict) -> Optional[str]:
    """Validate a decoded record's snapshot payload beyond the CRC —
    in particular the shard-dimension layout (each shard's base64
    packed bytes must match its declared geometry), so a scrub of a
    post-kill state dir verifies per-shard records all the way down."""
    snap = rec.get("snapshot")
    if snap is None:
        return None
    try:
        rows, cols = int(snap["rows"]), int(snap["cols"])
        if "shards" in snap:
            for sh in snap["shards"]:
                r0, c0 = int(sh["r0"]), int(sh["c0"])
                tr, tc = int(sh["rows"]), int(sh["cols"])
                if tr < 1 or tc < 1 or r0 + tr > rows or c0 + tc > cols:
                    return (f"shard {tr}x{tc}@({r0},{c0}) escapes the "
                            f"{rows}x{cols} board")
                need = (tr * tc + 7) // 8
                got = len(base64.b64decode(sh["packed"]))
                if got != need:
                    return (f"shard @({r0},{c0}) packed length {got} "
                            f"disagrees with geometry {tr}x{tc} "
                            f"(expected {need})")
        else:
            need = (rows * cols + 7) // 8
            got = len(base64.b64decode(snap["packed"]))
            if got != need:
                return (f"snapshot packed length {got} disagrees with "
                        f"geometry {rows}x{cols} (expected {need})")
    except (KeyError, TypeError, ValueError) as e:
        return f"malformed snapshot: {e}"
    return None


def _journal_entry_issue(kind: int, payload: bytes) -> Optional[str]:
    """Shape-validate one CRC-verified journal entry the way replay
    would — scrub's structural check over the shard-aware kinds."""
    if kind == _J_SHARD:
        if len(payload) < _SHARD_HEAD.size:
            return "shard entry shorter than its head"
        brows, bcols, r0, c0, srows, scols = _SHARD_HEAD.unpack_from(payload)
        nbytes = (srows * scols + 7) // 8
        if srows < 1 or scols < 1 or r0 + srows > brows or c0 + scols > bcols:
            return (f"shard entry {srows}x{scols}@({r0},{c0}) escapes the "
                    f"{brows}x{bcols} board")
        if len(payload) != _SHARD_HEAD.size + nbytes:
            return (f"shard entry payload {len(payload)} disagrees with "
                    f"geometry {srows}x{scols}")
    elif kind not in _J_KINDS:
        return f"unknown journal entry kind {kind}"
    return None


def scan_state_dir(state_dir: str, repair: bool = False) -> dict:
    """Walk every record (head + ancestors) and journal under
    ``state_dir``, verify each CRC frame, and report.  ``repair=True``
    truncates torn journal tails back to the last durable entry and
    quarantines corrupt records to ``<sid>.corrupt-<n>``.  The offline
    half of the durability story — ``tools/scrub.py`` is its CLI."""
    report = {
        "state_dir": state_dir,
        "records_ok": 0,
        "records_corrupt": 0,
        "journals_ok": 0,
        "journal_entries": 0,
        "shard_entries": 0,
        "torn_tails": 0,
        "stale_tmp": 0,
        "quarantined": [],
        "repaired": [],
        "issues": [],
    }
    try:
        names = sorted(os.listdir(state_dir))
    except FileNotFoundError:
        report["issues"].append(f"state dir {state_dir} does not exist")
        return report
    for name in names:
        path = os.path.join(state_dir, name)
        if ".tmp" in name:
            report["stale_tmp"] += 1
            report["issues"].append(f"{name}: stale tmp file")
            if repair:
                try:
                    os.remove(path)
                    report["repaired"].append(name)
                except OSError:
                    pass
            continue
        if name.startswith("routing-") and name.endswith(".json"):
            # per-node routing tables share the dir but are plain JSON
            # owned by the cluster layer (which self-heals a corrupt
            # one by rebuilding from gossip) — verify parseability,
            # never judge them against the record envelope
            try:
                with open(path, "rb") as f:
                    json.loads(f.read().decode("utf-8"))
            except OSError as e:
                report["issues"].append(f"{name}: unreadable ({e})")
            except (ValueError, UnicodeDecodeError):
                report["issues"].append(
                    f"{name}: unparseable routing table (the serving "
                    f"node rebuilds it from gossip; --repair "
                    f"quarantines it)")
                if repair:
                    qname = f"{name}.corrupt"
                    try:
                        os.replace(path, os.path.join(state_dir, qname))
                        report["repaired"].append(f"{name} -> {qname}")
                    except OSError:
                        pass
            continue
        if name.endswith(".json") or re.search(r"\.json\.\d+$", name):
            try:
                with open(path, "rb") as f:
                    rec = _rec_decode(f.read())
                issue = _snapshot_issue(rec)
                if issue is not None:
                    raise RecordCorrupt(issue)
                report["records_ok"] += 1
            except OSError as e:
                report["issues"].append(f"{name}: unreadable ({e})")
            except RecordCorrupt as e:
                report["records_corrupt"] += 1
                report["issues"].append(f"{name}: {e}")
                if repair:
                    base = re.sub(r"\.json(\.\d+)?$", "", name)
                    n = 1
                    while os.path.exists(
                            os.path.join(state_dir,
                                         f"{base}.corrupt-{n}")):
                        n += 1
                    qname = f"{base}.corrupt-{n}"
                    try:
                        os.replace(path,
                                   os.path.join(state_dir, qname))
                        report["quarantined"].append(name)
                        report["repaired"].append(f"{name} -> {qname}")
                    except OSError:
                        pass
        elif name.endswith(".journal") or re.search(r"\.journal\.\d+$",
                                                    name):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError as e:
                report["issues"].append(f"{name}: unreadable ({e})")
                continue
            entries, good, torn = _jrn_scan(raw)
            report["journal_entries"] += len(entries)
            for kind, _gen, payload in entries:
                if kind == _J_SHARD:
                    report["shard_entries"] += 1
                issue = _journal_entry_issue(kind, payload)
                if issue is not None:
                    report["issues"].append(f"{name}: {issue}")
            if torn:
                report["torn_tails"] += 1
                report["issues"].append(
                    f"{name}: torn tail ({len(raw) - good} bytes after "
                    f"entry {len(entries)})")
                if repair:
                    try:
                        with open(path, "r+b") as f:
                            f.seek(good)
                            f.truncate()
                            f.flush()
                            os.fsync(f.fileno())
                        report["repaired"].append(
                            f"{name}: truncated to {good} bytes")
                    except OSError:
                        pass
            else:
                report["journals_ok"] += 1
    report["clean"] = not report["issues"]
    return report
