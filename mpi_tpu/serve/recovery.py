"""Crash-safe session persistence — the serve layer's checkpoint/restore.

A ``kill -9`` of ``mpi_tpu serve`` must not lose live boards.  The
paper's design makes that cheap: stepping is deterministic from
``(spec, seed)`` and every engine is bit-identical to the ``serial_np``
oracle (PARITY.md), so a session is fully described by its *spec*, its
*generation*, and (as an optimization bounding replay length) an
occasional packed grid snapshot.  This module persists exactly that:
one JSON record per session under ``--state-dir``, rewritten on every
committed step via write-to-temp + ``os.replace`` (atomic on POSIX — a
crash mid-write leaves the previous complete record, never a torn one).

The grid snapshot rides in the record every ``checkpoint_every``
generations as base64 of ``np.packbits`` (1 bit/cell, ~8 KB for a
256x256 board).  On restart, :meth:`SessionManager._restore_all
<mpi_tpu.serve.session.SessionManager>` rebuilds each session from the
snapshot (or the seed) and replays the remaining generations through
its own backend — restored boards are bit-identical to an uninterrupted
run, which ``tests/test_serve_recovery.py`` asserts for both the
TPU-path engine and host backends.

What does NOT persist (by design): compiled engines (rebuilt lazily on
the first touch, softened by the persistent XLA cache), breaker state
and counters (a restart is the escape hatch a breaker exists to
approximate), and any in-flight step (the client saw an error or a dead
connection, never a commit).

Async tickets (PR 5) keep the same commit discipline: the dispatch loop
persists a session's record only AFTER a unit-round chain's
``block_until_ready`` returns — the generation bump and the checkpoint
write happen per *completed* dispatch, never per enqueued ticket.  A
``kill -9`` with tickets in flight therefore restores to the last
completed dispatch: the replayed generation can trail the steps clients
had enqueued, but never exceed what the device actually finished.  The
tickets themselves are process-local and die with the process — after a
restart, ``GET /result/<ticket>`` answers 404 and clients re-submit.
"""

from __future__ import annotations

import base64
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from mpi_tpu.serve import wire

RECORD_VERSION = 1


def encode_grid(grid: np.ndarray) -> dict:
    """A JSON-safe packed snapshot of a 0/1 uint8 grid — a base64
    wrapper over the one packbits core (``serve/wire.py``), so records
    and binary wire frames can never pack differently.  The bytes are
    unchanged from PR 3: existing ``--state-dir`` records decode
    bit-identically (pinned by ``tests/test_wire.py``)."""
    arr = np.asarray(grid, dtype=np.uint8)
    rows, cols = arr.shape
    return {
        "rows": int(rows),
        "cols": int(cols),
        "packed": base64.b64encode(wire.pack_grid(arr)).decode("ascii"),
    }


def decode_grid(snap: dict) -> np.ndarray:
    rows, cols = int(snap["rows"]), int(snap["cols"])
    return wire.unpack_grid(base64.b64decode(snap["packed"]), rows, cols)


class StateStore:
    """One JSON record per session under ``state_dir``.

    Record shape::

        {"v": 1, "id": "s3", "spec": {...create body...},
         "generation": 41,
         "snapshot": {"generation": 32, "rows": ..., "cols": ...,
                      "packed": "<base64 np.packbits>"} | null}

    ``save`` is called with the owning session's lock held (generation
    and snapshot must leave the lock together — the same torn-read
    discipline as the live snapshot verb), so the store's own lock only
    guards its counters and the shared tmp-name sequence.
    """

    def __init__(self, state_dir: str, checkpoint_every: int = 64):
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        self.state_dir = state_dir
        self.checkpoint_every = int(checkpoint_every)
        os.makedirs(state_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tmp_seq = 0
        self.writes = 0
        self.write_s = 0.0              # accumulated save wall (obs reads it)
        self.snapshot_writes = 0
        self.deletes = 0
        self.load_errors = 0

    # -- paths -------------------------------------------------------------

    def _path(self, sid: str) -> str:
        # session ids are manager-generated ("s<N>") — no traversal risk,
        # but keep the guard so a hand-edited state dir cannot escape
        safe = "".join(ch for ch in sid if ch.isalnum() or ch in "-_")
        return os.path.join(self.state_dir, f"{safe}.json")

    # -- write path --------------------------------------------------------

    def save(self, sid: str, spec: dict, generation: int,
             snapshot: Optional[dict]) -> None:
        """Atomically (re)write the record for ``sid``.  ``snapshot`` is
        the encoded grid dict plus its ``generation`` key, or None (replay
        will start from the seed)."""
        rec = {
            "v": RECORD_VERSION,
            "id": sid,
            "spec": spec,
            "generation": int(generation),
            "snapshot": snapshot,
        }
        path = self._path(sid)
        t0 = time.perf_counter()
        with self._lock:
            self._tmp_seq += 1
            tmp = f"{path}.tmp{self._tmp_seq}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self.writes += 1
            self.write_s += time.perf_counter() - t0
            if snapshot is not None:
                self.snapshot_writes += 1

    def delete(self, sid: str) -> None:
        try:
            os.remove(self._path(sid))
        except FileNotFoundError:
            pass
        with self._lock:
            self.deletes += 1

    # -- read path ---------------------------------------------------------

    def load_records(self) -> List[Dict]:
        """Every parseable record, ordered by numeric session id (so
        restored ids and the id counter line up deterministically).
        Corrupt or alien files are skipped and counted (``load_errors``)
        — a recovery pass must salvage what it can, not die on the one
        record a crash mangled."""
        out = []
        try:
            names = sorted(os.listdir(self.state_dir))
        except FileNotFoundError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.state_dir, name)
            try:
                with open(path) as f:
                    rec = json.load(f)
                if (not isinstance(rec, dict)
                        or rec.get("v") != RECORD_VERSION
                        or not isinstance(rec.get("id"), str)
                        or not isinstance(rec.get("spec"), dict)
                        or not isinstance(rec.get("generation"), int)):
                    raise ValueError(f"malformed session record {name}")
                out.append(rec)
            except (OSError, ValueError, json.JSONDecodeError):
                with self._lock:
                    self.load_errors += 1
        out.sort(key=lambda r: _sid_ordinal(r["id"]))
        return out

    def load_record(self, sid: str) -> Optional[Dict]:
        """The one parseable record for ``sid``, or None (missing —
        closed or never checkpointed — or corrupt, which also counts a
        load error).  The failover adoption path reads exactly one
        session; scanning the whole dir per adoption would be O(n²)
        across a dead node's sessions."""
        path = self._path(sid)
        try:
            with open(path) as f:
                rec = json.load(f)
            if (not isinstance(rec, dict)
                    or rec.get("v") != RECORD_VERSION
                    or rec.get("id") != sid
                    or not isinstance(rec.get("spec"), dict)
                    or not isinstance(rec.get("generation"), int)):
                raise ValueError(f"malformed session record for {sid!r}")
            return rec
        except FileNotFoundError:
            return None
        except (OSError, ValueError, json.JSONDecodeError):
            with self._lock:
                self.load_errors += 1
            return None

    def list_ids(self) -> List[str]:
        """Session ids with a record on disk — filename-derived, no
        parsing (failover scans this for the dead node's tag suffix)."""
        try:
            names = sorted(os.listdir(self.state_dir))
        except FileNotFoundError:
            return []
        return [name[:-5] for name in names
                if name.endswith(".json") and name.startswith("s")]

    def stats(self) -> dict:
        with self._lock:
            return {
                "state_dir": self.state_dir,
                "checkpoint_every": self.checkpoint_every,
                "writes": self.writes,
                "write_s": round(self.write_s, 6),
                "snapshot_writes": self.snapshot_writes,
                "deletes": self.deletes,
                "load_errors": self.load_errors,
            }


def _sid_ordinal(sid: str) -> int:
    # the leading digit run only: cluster-format ids ("s5-ab12cd")
    # must sort by ordinal like plain ones, not saturate the counter
    m = re.match(r"s(\d+)", sid)
    return int(m.group(1)) if m else 1 << 30
