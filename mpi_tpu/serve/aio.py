"""Selectors-based non-blocking HTTP front end (``--front aio``).

The threaded front burns one ``ThreadingHTTPServer`` thread per open
connection, so ten thousand idle ``GET /result/<t>?wait=1`` pollers are
ten thousand blocked threads.  This front inverts the model: ONE event
loop (stdlib ``selectors``) owns every socket and buffer, a small
:class:`~concurrent.futures.ThreadPoolExecutor` runs the blocking
session verbs (device dispatch stays on the existing SessionManager /
AsyncDispatcher worker threads — the loop never holds a session lock),
and the two places a thread used to idle become parked state:

* **Ticket waiters** — ``GET /result/<t>?wait=1`` registers a
  resolution callback on the ticket
  (:meth:`AsyncDispatcher.on_resolve`) and parks the socket.  Ticket
  resolution wakes exactly the sockets waiting on that ticket; a wait
  budget that expires first unparks and answers the same "pending"
  payload the threaded front would.  Ten thousand parked waiters cost
  ten thousand sockets and zero threads.
* **Live viewers** — ``GET /stream/<sid>?every=k`` answers a chunked
  ``application/x-gol-grid-stream`` response and parks; a step-commit
  listener on the manager (:meth:`SessionManager.add_step_listener`)
  marks the stream dirty and the loop pushes one binary frame per k
  generations.  A slow consumer never blocks a step and never builds an
  unbounded queue: when a connection's write buffer is over
  ``--stream-buffer-kib``, new frames overwrite a one-slot
  ``pending_frame`` (drop-to-latest) until the socket drains.

Request semantics (routes, validation, error shapes, the binary frame
protocol, the 413 body bound) all live in
:class:`~mpi_tpu.serve.transport.AppCore` — shared verbatim with the
threaded front, so the two cannot drift.

Threading rules (the loop's invariants):

* selector registration, connection state, timers: **loop thread only**;
* worker threads and ticket/step callbacks communicate with the loop
  exclusively via :meth:`_enqueue` (action deque + socketpair self-wake)
  — both are non-blocking, so a resolution callback firing inside the
  dispatch loop's commit (session locks held) costs an append and one
  pipe byte;
* the loop itself never blocks: accept/recv/send are non-blocking, and
  anything that could wait (session locks, watchdogs, device syncs)
  runs on the pool.
"""

from __future__ import annotations

import heapq
import io
import selectors
import socket
import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from http.client import responses as _REASONS
from typing import Dict, List, Optional, Set
from urllib.parse import parse_qs, urlencode, urlsplit

from mpi_tpu.serve import wire
from mpi_tpu.serve.session import SessionManager
from mpi_tpu.serve.transport import (
    AppCore, DEFAULT_MAX_BODY, Request, Response, StreamPlan, json_response,
)

DEFAULT_STREAM_BUFFER = 256 << 10       # per-socket write-buffer bound
MAX_HEADER = 64 << 10                   # request head must fit in this
_RECV_CHUNK = 1 << 16


class _Headers(dict):
    """Lower-cased header map with a case-insensitive ``get`` (the core
    asks for ``Content-Length``/``Accept`` in canonical case)."""

    def get(self, name, default=None):  # noqa: A003 — mapping contract
        return dict.get(self, name.lower(), default)


class _Conn:
    """One client connection's entire state (loop thread only)."""

    __slots__ = ("sock", "fd", "rbuf", "wbuf", "pending", "busy", "keep",
                 "close_after", "parked", "stream", "pending_frame",
                 "inflight", "closed")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.fd = sock.fileno()
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.pending = None             # parsed head awaiting its body
        self.busy = False               # a request is being handled
        self.keep = True                # keep-alive after current response
        self.close_after = False        # close once wbuf drains
        self.parked = None              # ticket-waiter state
        self.stream = None              # stream state
        self.pending_frame = None       # drop-to-latest slot (frame, gen)
        self.inflight = False           # a pool job owns this conn
        self.closed = False


class AioServer:
    """The event-loop server.  Mirrors the ``ThreadingHTTPServer``
    surface the CLI and tests drive: ``server_address``,
    ``serve_forever()``, ``shutdown()`` (thread-safe), and
    ``server_close()``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 manager: Optional[SessionManager] = None,
                 verbose: bool = False,
                 profile_dir: Optional[str] = None,
                 max_body: int = DEFAULT_MAX_BODY,
                 workers: int = 4,
                 stream_buffer: int = DEFAULT_STREAM_BUFFER):
        self.core = AppCore(manager, verbose=verbose,
                            profile_dir=profile_dir, max_body=max_body)
        self.manager = self.core.manager
        self.obs = self.core.obs
        self.verbose = verbose
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = int(workers)
        self.stream_buffer = max(1, int(stream_buffer))

        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(128)
        self._listen.setblocking(False)
        self.server_address = self._listen.getsockname()

        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._listen, selectors.EVENT_READ,
                           ("listen", None))
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           ("wake", None))

        self._pool = ThreadPoolExecutor(max_workers=self.workers,
                                        thread_name_prefix="mpi_tpu-aio")
        self._conns: Dict[int, _Conn] = {}
        self._actions: deque = deque()
        self._actions_lock = threading.Lock()
        self._timers: List[list] = []   # heap of [when, seq, fn-or-None]
        self._timer_seq = 0
        self._running = False
        self._shutdown_done = threading.Event()
        self._shutdown_done.set()       # not serving yet
        self._closed = False

        # streaming hub: sid -> conns; _stream_sids is the racily-read
        # fast-path filter for the step-listener (set mutated in the loop
        # thread only; a stale read costs one wasted action, never a miss
        # of a live stream — membership is re-checked in the loop)
        self._hub: Dict[str, Set[_Conn]] = {}
        self._stream_sids: Set[str] = set()
        self.manager.add_step_listener(self._on_step_commit)

        # counters (loop thread writes; /stats + scrape callbacks read)
        self.streams_opened = 0
        self.frames_pushed = 0
        self.frames_dropped = 0
        self.requests_handled = 0
        self.parked_total = 0
        self.resolved_dispatched = 0

        # parked-waiter fairness: a resolve burst (one batched commit can
        # resolve hundreds of parked tickets at once) must not flood the
        # small worker pool ahead of fresh requests.  Unparked waiters
        # queue here and drain FIFO, at most ``workers`` of them on the
        # pool at a time, so a new request's job is always behind a
        # bounded prefix of the burst instead of the whole of it.
        self._resolved_fifo: deque = deque()
        self._dispatching = 0           # request jobs on the pool now

        if self.obs is not None:
            m = self.obs.metrics
            m.gauge_fn("mpi_tpu_aio_open_connections",
                       "Sockets the aio front currently owns",
                       lambda: len(self._conns))
            m.gauge_fn("mpi_tpu_aio_parked_waiters",
                       "Ticket waiters parked as sockets (zero threads)",
                       lambda: self._count_conns(
                           lambda c: c.parked is not None))
            m.gauge_fn("mpi_tpu_aio_active_streams",
                       "Open chunked grid streams",
                       lambda: self._count_conns(
                           lambda c: c.stream is not None))
            m.counter_fn("mpi_tpu_aio_frames_pushed_total",
                         "Binary frames pushed to stream consumers",
                         lambda: self.frames_pushed)
            m.counter_fn("mpi_tpu_aio_frames_dropped_total",
                         "Stream frames dropped to latest (slow consumer)",
                         lambda: self.frames_dropped)
            m.gauge_fn("mpi_tpu_aio_resolve_queue_depth",
                       "Unparked waiters queued behind the fairness "
                       "bound (FIFO, at most --aio-workers on the pool)",
                       lambda: len(self._resolved_fifo))

    def _count_conns(self, pred) -> int:
        # scrape-time read of loop-thread state: a concurrent mutation
        # can break dict iteration — retry, it settles immediately
        for _ in range(8):
            try:
                return sum(1 for c in list(self._conns.values()) if pred(c))
            except RuntimeError:
                continue
        return 0

    # -- cross-thread signalling ------------------------------------------

    def _enqueue(self, fn) -> None:
        """Hand ``fn`` to the loop thread (any thread; non-blocking)."""
        with self._actions_lock:
            self._actions.append(fn)
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass                        # pipe full = wake already pending

    def _add_timer(self, delay_s: float, fn) -> list:
        self._timer_seq += 1
        entry = [time.monotonic() + max(0.0, delay_s), self._timer_seq, fn]
        heapq.heappush(self._timers, entry)
        return entry

    # -- the loop ----------------------------------------------------------

    def serve_forever(self) -> None:
        self._running = True
        self._shutdown_done.clear()
        try:
            while self._running:
                timeout = None
                if self._timers:
                    timeout = max(0.0,
                                  self._timers[0][0] - time.monotonic())
                for key, mask in self._sel.select(timeout):
                    kind, conn = key.data
                    if kind == "listen":
                        self._accept()
                    elif kind == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        self._on_conn(conn, mask)
                self._run_timers()
                self._run_actions()
        finally:
            self._shutdown_done.set()

    def shutdown(self) -> None:
        """Stop ``serve_forever`` (thread-safe; returns once the loop
        has exited, matching ``socketserver``'s contract)."""
        self._running = False
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._shutdown_done.wait(timeout=5.0)

    def server_close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._running = False
        self.manager.remove_step_listener(self._on_step_commit)
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        for sock in (self._listen, self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except (OSError, RuntimeError):
            pass
        self._pool.shutdown(wait=False)

    def _run_actions(self) -> None:
        while True:
            with self._actions_lock:
                if not self._actions:
                    return
                fn = self._actions.popleft()
            try:
                fn()
            except Exception:  # noqa: BLE001 — one bad action, not the loop
                traceback.print_exc(file=sys.stderr)

    def _run_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            entry = heapq.heappop(self._timers)
            fn = entry[2]
            if fn is None:
                continue                # cancelled
            try:
                fn()
            except Exception:  # noqa: BLE001
                traceback.print_exc(file=sys.stderr)

    # -- socket events -----------------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns[conn.fd] = conn
            self._sel.register(sock, selectors.EVENT_READ, ("conn", conn))

    def _set_write_interest(self, conn: _Conn, want: bool) -> None:
        if conn.closed:
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if want
                                         else 0)
        try:
            self._sel.modify(conn.sock, events, ("conn", conn))
        except (KeyError, ValueError, OSError):
            pass

    def _on_conn(self, conn: _Conn, mask: int) -> None:
        if conn.closed:
            return
        if mask & selectors.EVENT_READ:
            try:
                data = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                data = None
            except OSError:
                return self._close_conn(conn)
            if data == b"":
                return self._close_conn(conn)
            if data:
                conn.rbuf += data
                if conn.stream is not None or conn.parked is not None:
                    # a parked/streaming client has nothing more to say;
                    # cap what a misbehaving one can make us buffer
                    if len(conn.rbuf) > MAX_HEADER:
                        return self._close_conn(conn)
                else:
                    self._process_rbuf(conn)
                    if conn.closed:
                        return
        if mask & selectors.EVENT_WRITE:
            self._flush(conn)

    def _close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn.parked is not None:
            info, conn.parked = conn.parked, None
            self._cancel_park(info)
        if conn.stream is not None:
            self._detach_stream(conn)
        self._conns.pop(conn.fd, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- HTTP/1.1 parsing --------------------------------------------------

    def _process_rbuf(self, conn: _Conn) -> None:
        while not (conn.busy or conn.close_after or conn.closed):
            if conn.pending is None:
                idx = conn.rbuf.find(b"\r\n\r\n")
                if idx < 0:
                    if len(conn.rbuf) > MAX_HEADER:
                        self._deliver(conn, json_response(431, {
                            "error": "request head exceeds 64 KiB"},
                            close=True))
                    return
                head = bytes(conn.rbuf[:idx]).decode("latin-1")
                lines = head.split("\r\n")
                parts = lines[0].split()
                if len(parts) != 3 or not parts[2].startswith("HTTP/"):
                    return self._deliver(conn, json_response(400, {
                        "error": f"malformed request line {lines[0]!r}"},
                        close=True))
                method, target, version = parts
                headers = _Headers()
                for line in lines[1:]:
                    name, sep, value = line.partition(":")
                    if sep:
                        headers[name.strip().lower()] = value.strip()
                raw_cl = headers.get("content-length")
                try:
                    clen = int(raw_cl) if raw_cl else 0
                except ValueError:
                    clen = -1           # unframeable; core answers the 400
                token = (headers.get("connection") or "").lower()
                keep = (token == "keep-alive" if version == "HTTP/1.0"
                        else token != "close")
                conn.pending = [method, target, headers, idx + 4, clen,
                                keep]
            method, target, headers, body_off, clen, keep = conn.pending
            if clen < 0 or clen > self.core.max_body:
                # bad framing or over the body bound: hand the core an
                # empty body (it answers 400/413 without reading) and
                # close — the unread body poisons keep-alive framing
                body = b""
                keep = False
                del conn.rbuf[:]
            else:
                if len(conn.rbuf) - body_off < clen:
                    return              # body still arriving
                body = bytes(conn.rbuf[body_off:body_off + clen])
                del conn.rbuf[:body_off + clen]
            conn.pending = None
            conn.keep = keep
            req = Request(method, target, headers, io.BytesIO(body).read)
            self._start_request(conn, req)

    # -- request handling --------------------------------------------------

    def _start_request(self, conn: _Conn, req: Request) -> None:
        conn.busy = True
        self.requests_handled += 1
        if self._try_park(conn, req):
            return
        self._submit(conn, req)

    def _submit(self, conn: _Conn, req: Request) -> None:
        conn.inflight = True
        self._dispatching += 1          # loop thread only

        def done(fut):
            try:
                resp = fut.result()
            except Exception as e:  # noqa: BLE001 — dispatch never raises,
                # but a belt under the suspenders keeps the loop alive
                traceback.print_exc(file=sys.stderr)
                resp = json_response(500, {
                    "error": f"internal server error ({type(e).__name__})"})
            self._enqueue(lambda: self._finish_request(conn, resp))

        self._pool.submit(self.core.dispatch, req,
                          "aio").add_done_callback(done)

    def _finish_request(self, conn: _Conn, resp) -> None:
        self._dispatching -= 1
        conn.inflight = False
        self._deliver(conn, resp)
        self._drain_resolved()          # a worker freed: next waiter

    def _deliver(self, conn: _Conn, resp) -> None:
        if conn.closed:
            return
        if isinstance(resp, StreamPlan):
            return self._start_stream(conn, resp)
        head = self._head(resp.code, resp.content_type,
                          length=len(resp.body), extra=resp.headers,
                          close=resp.close or not conn.keep)
        conn.wbuf += head + resp.body
        if resp.close or not conn.keep:
            conn.close_after = True
        conn.busy = False
        self._flush(conn)
        if not (conn.closed or conn.close_after):
            self._process_rbuf(conn)    # pipelined requests, in order

    @staticmethod
    def _head(code: int, content_type: str, length: Optional[int] = None,
              extra=(), close: bool = False, chunked: bool = False) -> bytes:
        lines = [f"HTTP/1.1 {code} {_REASONS.get(code, 'Unknown')}",
                 f"Content-Type: {content_type}"]
        if chunked:
            lines.append("Transfer-Encoding: chunked")
        else:
            lines.append(f"Content-Length: {length}")
        for name, value in extra:
            lines.append(f"{name}: {value}")
        if close:
            lines.append("Connection: close")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    def _flush(self, conn: _Conn) -> None:
        if conn.closed:
            return
        while True:
            if (not conn.wbuf and conn.pending_frame is not None
                    and conn.stream is not None):
                # the socket drained: promote the drop-to-latest slot
                frame, gen = conn.pending_frame
                conn.pending_frame = None
                self._append_frame(conn, frame, gen)
            if (not conn.wbuf and conn.stream is not None
                    and conn.stream["key_pending"]):
                # delta-stream resync: the drop discarded a frame, so
                # fetch a fresh keyframe now that the socket drained
                conn.stream["key_pending"] = False
                self._request_frame(conn)
            if not conn.wbuf:
                break
            try:
                sent = conn.sock.send(conn.wbuf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                return self._close_conn(conn)
            if sent <= 0:
                break
            del conn.wbuf[:sent]
        if conn.wbuf:
            self._set_write_interest(conn, True)
        else:
            self._set_write_interest(conn, False)
            if conn.close_after:
                self._close_conn(conn)

    # -- parked ticket waiters ---------------------------------------------

    def _try_park(self, conn: _Conn, req: Request) -> bool:
        """Park ``GET /result/<t>?wait=1`` as a registered socket instead
        of a blocked thread.  Returns False when the request is not a
        waitable ticket read (including unknown tickets — the normal
        dispatch path owns the structured 404)."""
        dispatcher = self.manager.dispatcher
        if dispatcher is None or req.method != "GET":
            return False
        parts = [p for p in req.path.split("?")[0].split("/") if p]
        if len(parts) != 2 or parts[0] != "result":
            return False
        if not self.core._query_flag(req, "wait"):
            return False
        tid = parts[1]
        nowait = self._strip_wait(req)
        info = {"tid": tid, "req": nowait, "timer": None, "fn": None}

        def on_resolve(_ticket):
            # dispatch-loop thread, session locks possibly held: flag+wake
            self._enqueue(lambda: self._unpark(conn, info))

        info["fn"] = on_resolve
        conn.parked = info
        try:
            parked = dispatcher.on_resolve(tid, on_resolve)
        except KeyError:
            conn.parked = None
            return False
        except (ValueError, ConnectionError):
            conn.parked = None
            return False
        self.parked_total += 1
        if parked:
            try:
                budget = self.manager._budget(
                    self.core._timeout_override(req, {}))
            except Exception:  # noqa: BLE001 — bad timeout_s: let core 400
                self._cancel_park(info)
                conn.parked = None
                return False
            if budget is not None:
                info["timer"] = self._add_timer(
                    budget, lambda: self._unpark(conn, info))
        # already-resolved tickets ran on_resolve synchronously above —
        # the _unpark action is queued and will dispatch the read
        return True

    @staticmethod
    def _strip_wait(req: Request) -> Request:
        """The same read without ``wait`` — what a parked waiter
        dispatches after wake/timeout (the ticket is either resolved,
        giving the final payload, or the budget expired, giving the
        same "pending" answer the threaded front's timed-out wait
        returns)."""
        parts = urlsplit(req.path)
        qs = parse_qs(parts.query)
        qs.pop("wait", None)
        query = urlencode(qs, doseq=True)
        path = parts.path + (f"?{query}" if query else "")
        return Request(req.method, path, req.headers, io.BytesIO(b"").read)

    def _unpark(self, conn: _Conn, info: dict) -> None:
        if conn.closed or conn.parked is not info:
            return                      # stale wake (timeout + resolve race)
        conn.parked = None
        self._cancel_park(info)
        # fairness: never straight to the pool — through the FIFO, so a
        # resolve burst dispatches at most ``workers`` waiters at a time
        # and fresh requests interleave instead of starving behind it
        self._resolved_fifo.append((conn, info["req"]))
        self._drain_resolved()

    def _drain_resolved(self) -> None:
        """Dispatch queued unparked waiters FIFO while the pool has a
        free worker (loop thread only — no lock needed)."""
        while self._resolved_fifo and self._dispatching < self.workers:
            conn, req = self._resolved_fifo.popleft()
            if conn.closed:
                continue                # died while queued
            self.resolved_dispatched += 1
            self._submit(conn, req)

    def _cancel_park(self, info: dict) -> None:
        if info.get("timer") is not None:
            info["timer"][2] = None     # lazy-cancel in the heap
            info["timer"] = None
        dispatcher = self.manager.dispatcher
        if dispatcher is not None and info.get("fn") is not None:
            dispatcher.cancel_resolve(info["tid"], info["fn"])

    # -- streams -----------------------------------------------------------

    def _start_stream(self, conn: _Conn, plan: StreamPlan) -> None:
        # delta-stream state beyond the classic trio: ``prev`` is the
        # grid behind the last ENCODED frame (pool thread only — one job
        # in flight per connection), ``force_key``/``key_pending`` drive
        # the resync-after-drop protocol (a lost delta would silently
        # diverge the client's reconstruction, so a drop discards the
        # frame and schedules a keyframe once the socket drains)
        conn.stream = {"sid": plan.sid, "every": plan.every,
                       "last": None, "dirty": False,
                       "window": plan.window, "delta": plan.delta,
                       "kf": plan.keyframe_every, "prev": None,
                       "since_key": 0, "force_key": False,
                       "key_pending": False}
        conn.busy = True                # the stream owns this connection
        conn.wbuf += self._head(200, wire.STREAM_MEDIA_TYPE, chunked=True)
        self._hub.setdefault(plan.sid, set()).add(conn)
        self._stream_sids.add(plan.sid)
        self.streams_opened += 1
        self._flush(conn)
        self._request_frame(conn)       # first frame: the current grid

    def _detach_stream(self, conn: _Conn) -> None:
        st, conn.stream = conn.stream, None
        if st is None:
            return
        conns = self._hub.get(st["sid"])
        if conns is not None:
            conns.discard(conn)
            if not conns:
                del self._hub[st["sid"]]
                self._stream_sids.discard(st["sid"])

    def _on_step_commit(self, session) -> None:
        # manager step-listener: ANY thread, session lock typically held.
        # The set membership test is the cheap racy filter; everything
        # else happens on the loop thread.
        if session.id in self._stream_sids:
            sid = session.id
            self._enqueue(lambda: self._notify_streams(sid))

    def _notify_streams(self, sid: str) -> None:
        for conn in list(self._hub.get(sid, ())):
            if conn.stream is not None:
                conn.stream["dirty"] = True
                self._request_frame(conn)

    def _request_frame(self, conn: _Conn) -> None:
        """Fetch+encode the session's current grid (or viewport) on the
        pool, then deliver it to this stream (one job in flight per
        connection — a burst of commits coalesces into one fetch of the
        latest)."""
        if conn.inflight or conn.closed or conn.stream is None:
            return
        st = conn.stream
        st["dirty"] = False
        conn.inflight = True
        sid = st["sid"]
        core = self.core

        def job():
            try:
                if st["window"] is not None:
                    wx0, wy0, wh, ww = st["window"]
                    grid, gen, config = self.manager.snapshot_window(
                        sid, wx0, wy0, wh, ww)
                else:
                    grid, gen, config = self.manager.snapshot_array(sid)
                if st["delta"]:
                    # the every-gate runs BEFORE encoding here: a delta
                    # encoded but never delivered would still advance
                    # the base grid and diverge the client (st["last"]
                    # is a benign racy read — worst case one extra
                    # frame, never a missed diff)
                    last = st["last"]
                    if last is not None and gen < last + st["every"]:
                        self._enqueue(
                            lambda: self._deliver_frame(conn, None, gen))
                        return
                t0 = time.perf_counter()
                if core.obs is not None:
                    with core.obs.span("stream_push", sid=sid,
                                       generation=gen):
                        frame, fk = self._encode_stream_frame(
                            st, grid, gen, config)
                    core.obs.wire_encode.observe(
                        time.perf_counter() - t0, format="binary",
                        transport="aio")
                    if fk in ("key", "delta"):
                        core.obs.delta_frames.inc(kind=fk)
                    if st["window"] is not None:
                        core.obs.viewport_bytes.inc(len(frame),
                                                    transport="aio")
                else:
                    frame, fk = self._encode_stream_frame(
                        st, grid, gen, config)
                self._enqueue(
                    lambda: self._deliver_frame(conn, frame, gen))
            except Exception:  # noqa: BLE001 — session closed/deadline:
                # terminate the stream cleanly, the loop survives
                self._enqueue(lambda: self._end_stream(conn))

        self._pool.submit(job)

    def _encode_stream_frame(self, st: dict, grid, gen: int, config):
        """``(frame, kind)`` — the stream's next frame: a v1 full frame
        (plain streams), a v2 windowed frame (viewport streams), or a
        v2 keyframe/dirty-tile delta (delta streams; the delta base is
        the previously ENCODED grid, touched only by this connection's
        single in-flight pool job)."""
        window = st["window"]
        if not st["delta"]:
            if window is None:
                return (self.core.encode_grid_frame(grid, gen, config),
                        "full")
            return wire.encode_window_frame(
                grid, x0=window[0], y0=window[1],
                board_shape=(config.rows, config.cols), generation=gen,
                rule=config.rule, boundary=config.boundary), "window"
        x0, y0 = (window[0], window[1]) if window is not None else (0, 0)
        prev = st["prev"]
        need_key = (prev is None or st["force_key"]
                    or prev.shape != grid.shape
                    or st["since_key"] >= st["kf"])
        st["prev"] = grid
        if need_key:
            st["force_key"] = False
            st["since_key"] = 1
            return wire.encode_window_frame(
                grid, x0=x0, y0=y0,
                board_shape=(config.rows, config.cols), generation=gen,
                rule=config.rule, boundary=config.boundary), "key"
        st["since_key"] += 1
        tiles = wire.diff_tiles(prev, grid)
        return wire.encode_delta_frame(
            tiles, window=(x0, y0, grid.shape[0], grid.shape[1]),
            board_shape=(config.rows, config.cols), generation=gen,
            rule=config.rule, boundary=config.boundary), "delta"

    def _deliver_frame(self, conn: _Conn, frame: Optional[bytes],
                       gen: int) -> None:
        conn.inflight = False
        if conn.closed or conn.stream is None:
            return
        st = conn.stream
        if frame is None:
            # a delta stream's every-gate skipped this generation
            if st["dirty"]:
                self._request_frame(conn)
            return
        due = (st["delta"] or st["last"] is None
               or gen >= st["last"] + st["every"])
        if due:
            if len(conn.wbuf) > self.stream_buffer:
                if st["delta"]:
                    # a dropped delta would silently diverge the
                    # client's reconstruction: discard it and resync
                    # with a keyframe once the socket drains
                    st["force_key"] = True
                    st["key_pending"] = True
                    self.frames_dropped += 1
                else:
                    # slow consumer: drop to latest, never queue
                    # unboundedly
                    conn.pending_frame = (frame, gen)
                    self.frames_dropped += 1
            else:
                conn.pending_frame = None
                self._append_frame(conn, frame, gen)
                self._flush(conn)
        if conn.stream is not None and st["dirty"]:
            self._request_frame(conn)

    def _append_frame(self, conn: _Conn, frame: bytes, gen: int) -> None:
        chunk = b"%x\r\n" % len(frame) + frame + b"\r\n"
        conn.wbuf += chunk
        conn.stream["last"] = gen
        self.frames_pushed += 1
        self.core.count_out(len(chunk), "aio")

    def _end_stream(self, conn: _Conn) -> None:
        conn.inflight = False
        if conn.closed or conn.stream is None:
            return
        self._detach_stream(conn)
        conn.pending_frame = None
        conn.wbuf += b"0\r\n\r\n"       # terminal chunk
        conn.close_after = True
        self._flush(conn)

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        return {
            "open_connections": len(self._conns),
            "parked_waiters": self._count_conns(
                lambda c: c.parked is not None),
            "active_streams": self._count_conns(
                lambda c: c.stream is not None),
            "streams_opened": self.streams_opened,
            "frames_pushed": self.frames_pushed,
            "frames_dropped": self.frames_dropped,
            "requests_handled": self.requests_handled,
            "parked_total": self.parked_total,
            "resolved_dispatched": self.resolved_dispatched,
            "resolve_queue_depth": len(self._resolved_fifo),
            "workers": self.workers,
            "stream_buffer": self.stream_buffer,
        }


def make_aio_server(host: str = "127.0.0.1", port: int = 0,
                    manager: Optional[SessionManager] = None,
                    verbose: bool = False,
                    profile_dir: Optional[str] = None,
                    max_body: int = DEFAULT_MAX_BODY,
                    workers: int = 4,
                    stream_buffer: int = DEFAULT_STREAM_BUFFER) -> AioServer:
    """The aio twin of ``httpd.make_server`` (same call shape plus the
    aio-only knobs; ``port=0`` binds an ephemeral port)."""
    return AioServer(host, port, manager, verbose=verbose,
                     profile_dir=profile_dir, max_body=max_body,
                     workers=workers, stream_buffer=stream_buffer)
