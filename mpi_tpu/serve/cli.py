"""``mpi_tpu serve`` — run the session service.

Example::

    python -m mpi_tpu.cli serve --port 8000 --cache-size 8
    curl -X POST localhost:8000/sessions -d '{"rows":64,"cols":64,"backend":"serial"}'
    curl -X POST localhost:8000/sessions/s1/step -d '{"steps":10}'
    curl localhost:8000/sessions/s1/density
    curl localhost:8000/stats
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_tpu serve",
        description="persistent multi-session engine service "
        "(HTTP + JSON, compiled-stepper cache)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 binds an ephemeral port (printed on startup)")
    p.add_argument("--cache-size", type=int, default=8,
                   help="max cached compiled engines (LRU beyond this)")
    p.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="coalescing window for same-signature concurrent "
                   "steps: the first arrival waits this long collecting "
                   "peers before dispatching one stacked batched step "
                   "(0 disables the wait but still coalesces whatever is "
                   "already queued)")
    p.add_argument("--batch-max", type=int, default=8,
                   help="max boards per stacked batched dispatch")
    p.add_argument("--no-batch", action="store_true",
                   help="disable microbatching; every step dispatches solo")
    p.add_argument("--verbose", action="store_true",
                   help="log one line per HTTP request")
    return p


def serve_main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from mpi_tpu.config import ConfigError
    from mpi_tpu.serve.cache import EngineCache
    from mpi_tpu.serve.httpd import make_server
    from mpi_tpu.serve.session import SessionManager
    from mpi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    try:
        manager = SessionManager(
            EngineCache(max_size=args.cache_size),
            batching=not args.no_batch,
            batch_window_ms=args.batch_window_ms,
            batch_max=args.batch_max,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    server = make_server(args.host, args.port, manager, verbose=args.verbose)
    host, port = server.server_address[:2]
    batch = ("off" if args.no_batch else
             f"window {args.batch_window_ms}ms max {args.batch_max}")
    print(f"[mpi_tpu] serving on http://{host}:{port} "
          f"(cache size {args.cache_size}, batch {batch})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[mpi_tpu] shutting down", flush=True)
    finally:
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(serve_main())
