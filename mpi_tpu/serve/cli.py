"""``mpi_tpu serve`` — run the session service.

Example::

    python -m mpi_tpu.cli serve --port 8000 --cache-size 8
    curl -X POST localhost:8000/sessions -d '{"rows":64,"cols":64,"backend":"serial"}'
    curl -X POST localhost:8000/sessions/s1/step -d '{"steps":10}'
    curl localhost:8000/sessions/s1/density
    curl localhost:8000/stats

Fault tolerance (see README "Fault tolerance" for the full story)::

    python -m mpi_tpu.cli serve --state-dir /var/lib/mpi_tpu \\
        --request-timeout-s 30 --breaker-threshold 3

``--state-dir`` makes sessions survive a ``kill -9`` (crash-safe JSON
records + deterministic replay); ``--request-timeout-s`` bounds every
verb; the breaker flags govern when a failing engine's plan is
quarantined and its sessions degrade to the ``serial_np`` oracle.
``--inject-faults`` (or the ``MPI_TPU_FAULTS`` env var) drives the
recovery paths deterministically for testing.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_tpu serve",
        description="persistent multi-session engine service "
        "(HTTP + JSON, compiled-stepper cache)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 binds an ephemeral port (printed on startup)")
    p.add_argument("--cache-size", type=int, default=8,
                   help="max cached compiled engines (LRU beyond this)")
    p.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="coalescing window for same-signature concurrent "
                   "steps: the first arrival waits this long collecting "
                   "peers before dispatching one stacked batched step "
                   "(0 disables the wait but still coalesces whatever is "
                   "already queued)")
    p.add_argument("--batch-max", type=int, default=8,
                   help="max boards per stacked batched dispatch")
    p.add_argument("--no-batch", action="store_true",
                   help="disable microbatching; every step dispatches solo")
    p.add_argument("--no-async", action="store_true",
                   help="disable ticketed async stepping: POST /step with "
                   "async=1 answers 400 and no dispatch loop runs (async "
                   "is opt-in per request either way; the sync path is "
                   "identical with or without this flag)")
    p.add_argument("--async-queue-max", type=int, default=1024,
                   help="bound on tickets queued for the async dispatch "
                   "loop; an enqueue beyond it answers a structured 503 "
                   "(backpressure, not an error)")
    p.add_argument("--ticket-ttl-s", type=float, default=600.0,
                   help="seconds a RESOLVED async ticket stays "
                   "resolvable via GET /result/<ticket> before aging "
                   "out (0 keeps tickets until the 4096-entry size cap "
                   "evicts them; pending tickets never expire this way)")
    p.add_argument("--verbose", action="store_true",
                   help="log one line per HTTP request (with request ids)")
    p.add_argument("--state-dir", default=None,
                   help="persist session records here (crash-safe JSON); "
                   "restart with the same dir to restore every board by "
                   "deterministic replay, bit-identical")
    p.add_argument("--checkpoint-every", type=int, default=64,
                   help="generations between packed grid snapshots in the "
                   "session record (bounds replay length on restore)")
    p.add_argument("--state-degrade",
                   choices=("continue", "readonly", "shed"),
                   default="continue",
                   help="policy while persistence is degraded (the state "
                   "dir stopped taking writes): 'continue' keeps serving "
                   "and re-checkpoints when the disk heals, 'readonly' "
                   "refuses mutating verbs with 503+Retry-After, 'shed' "
                   "refuses all session verbs so a balancer drains the "
                   "node")
    p.add_argument("--no-state-journal", action="store_true",
                   help="disable the per-session append-only journal and "
                   "rewrite the full record every committed step (the "
                   "pre-v2 behavior; costs full-record bytes per step)")
    p.add_argument("--journal-max-bytes", type=int, default=1 << 20,
                   help="journal size that triggers compaction into a "
                   "full record write (default 1 MiB)")
    p.add_argument("--journal-max-age-s", type=float, default=300.0,
                   help="journal age that triggers compaction (bounds "
                   "replay work after a crash; default 300)")
    p.add_argument("--state-keep", type=int, default=2,
                   help="last-good ancestor records kept per session "
                   "(<sid>.json.1..N; restore falls back down this chain "
                   "when the head is corrupt; default 2)")
    p.add_argument("--request-timeout-s", type=float, default=30.0,
                   help="time budget per request; a hung dispatch becomes "
                   "a structured 503 with the session intact "
                   "(0 disables; per-request override: ?timeout_s=)")
    p.add_argument("--step-retries", type=int, default=2,
                   help="retries (with exponential backoff) for a failed "
                   "engine step before answering 503")
    p.add_argument("--retry-backoff-ms", type=float, default=50.0,
                   help="initial retry backoff, doubling per attempt")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive engine failures that open a plan "
                   "signature's circuit breaker")
    p.add_argument("--breaker-cooldown-s", type=float, default=30.0,
                   help="open-breaker cooldown before one half-open trial "
                   "dispatch is admitted")
    p.add_argument("--no-degrade", action="store_true",
                   help="do NOT fall back to the serial_np oracle when a "
                   "breaker opens; affected requests answer 503 instead")
    p.add_argument("--inject-faults", default=None, metavar="SPEC",
                   help="deterministic fault plan at the engine dispatch "
                   "boundary, e.g. 'step:3:raise' or 'any:2:hang:5' "
                   "(testing; env fallback MPI_TPU_FAULTS)")
    p.add_argument("--tune-cache", default=None, metavar="PATH",
                   help="apply autotuned plan winners from this tune "
                   "cache on every engine compile miss (see 'python -m "
                   "mpi_tpu.tune'); 'auto' resolves to "
                   "<state-dir>/tune_cache.json when --state-dir is set, "
                   "else the repo default perf/tune_cache.json.  Unset: "
                   "no cache is read, plans build exactly as requested")
    p.add_argument("--no-obs", action="store_true",
                   help="disable tracing/metrics entirely: /metrics answers "
                   "404 and the step path runs uninstrumented "
                   "(bit-identical results either way)")
    p.add_argument("--trace-log", default=None, metavar="PATH",
                   help="stream every trace span as a JSONL line to PATH "
                   "(the ring buffer alone otherwise; dumped on any 500)")
    p.add_argument("--trace-capacity", type=int, default=4096,
                   help="span ring-buffer size (oldest spans overwritten)")
    p.add_argument("--telemetry-interval-s", type=float, default=None,
                   metavar="SECS",
                   help="arm the in-process telemetry recorder + SLO "
                   "engine: sample selected metrics series and evaluate "
                   "burn rates every SECS seconds, serving GET /slo and "
                   "GET /debug/timeseries.  Unset (the default): no "
                   "sampler runs and the scrape/trace output is "
                   "byte-identical to pre-telemetry builds")
    p.add_argument("--slo-file", default=None, metavar="PATH",
                   help="JSON objectives for the SLO engine (see README "
                   "'SLOs and telemetry history' for the schema); implies "
                   "--telemetry-interval-s 5 when that flag is unset.  "
                   "Unset: built-in defaults (availability 99.9%%, "
                   "dispatch p99 < 1s, freshness 600s)")
    p.add_argument("--admission", action="store_true",
                   help="arm multi-tenant admission control with the "
                   "built-in unlimited default tenant: requests carry "
                   "X-Gol-Tenant/X-Gol-Class headers, quotas gate in "
                   "ledger currency, the async dispatcher schedules by "
                   "priority class, and a critical SLO sheds low classes "
                   "first.  Unset (and no --tenants-file): no admission "
                   "layer runs and ids/payloads/scrape text are "
                   "byte-identical to pre-admission builds")
    p.add_argument("--tenants-file", default=None, metavar="PATH",
                   help="JSON tenant registry (see README 'Admission "
                   "control and multi-tenancy' for the schema); implies "
                   "--admission.  Unset with --admission: one unlimited "
                   "default tenant")
    p.add_argument("--flight-recorder", action="store_true",
                   help="arm the per-dispatch flight recorder: every "
                   "committed dispatch leaves a bounded ring record "
                   "(plan signature, engine kind, k-segment composition, "
                   "batch riders, sparse rung, donation, timing split) "
                   "served at GET /debug/flights and folded into crash "
                   "dumps.  Unset: no ring exists and the scrape/trace "
                   "output is byte-identical to pre-flight builds")
    p.add_argument("--flight-capacity", type=int, default=1024,
                   help="flight-record ring size (oldest records "
                   "overwritten; one flight_drop trace event per full "
                   "ring turn)")
    p.add_argument("--anomaly-detect", action="store_true",
                   help="arm per-signature dispatch-latency drift "
                   "detection on the telemetry cadence (implies "
                   "--flight-recorder, and --telemetry-interval-s 5 when "
                   "that flag is unset): sustained 1m+5m median drift vs "
                   "the 1h baseline emits a dispatch_anomaly trace "
                   "event, serves GET /debug/anomalies, and — with "
                   "--profile-dir — arms one bounded, cooldown-gated "
                   "jax.profiler capture per episode")
    p.add_argument("--anomaly-cooldown-s", type=float, default=600.0,
                   help="minimum seconds between anomaly-armed profiler "
                   "captures (never back-to-back)")
    p.add_argument("--anomaly-retention", type=int, default=4,
                   help="keep at most this many anomaly-* capture dirs "
                   "under --profile-dir (oldest pruned first)")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="arm POST /debug/profile?secs=N: captures a "
                   "jax.profiler device trace into DIR (off when unset); "
                   "also where --anomaly-detect rotates its captures")
    p.add_argument("--front", choices=("threaded", "aio"),
                   default="threaded",
                   help="HTTP front end: 'threaded' (stdlib thread-per-"
                   "connection, the default — byte-compatible JSON) or "
                   "'aio' (selectors event loop: idle ticket waiters park "
                   "as sockets, GET /stream/<sid> pushes binary frames)")
    p.add_argument("--http-max-body", type=int, default=64 << 20,
                   metavar="BYTES",
                   help="reject request bodies larger than this with a "
                   "structured 413 before reading (default 64 MiB)")
    p.add_argument("--aio-workers", type=int, default=4,
                   help="worker threads the aio front uses for blocking "
                   "session verbs (the event loop itself never blocks)")
    p.add_argument("--stream-buffer-kib", type=int, default=256,
                   help="per-socket write-buffer bound for /stream "
                   "consumers; a slower consumer gets drop-to-latest "
                   "frames instead of an unbounded queue")
    p.add_argument("--peers", default=None, metavar="HOST:PORT,...",
                   help="comma-separated peer serving addresses; setting "
                   "this (or --peers-file) turns on cluster mode: sticky "
                   "session routing, gossip, and cluster roll-ups on "
                   "/usage and /healthz.  Unset: single-process serving, "
                   "bit-identical to pre-cluster builds")
    p.add_argument("--peers-file", default=None, metavar="PATH",
                   help="seed-peer file, one host:port per line "
                   "('#' comments allowed); merged with --peers")
    p.add_argument("--advertise", default=None, metavar="HOST:PORT",
                   help="the address peers reach THIS process at (the "
                   "node id); defaults to the bound host:port, which is "
                   "only right when peers share the host")
    p.add_argument("--gossip-interval-s", type=float, default=1.0,
                   help="seconds between gossip rounds; peer-down and "
                   "breaker-quarantine TTL default to 3x this")
    p.add_argument("--peer-timeout-s", type=float, default=5.0,
                   help="socket timeout for proxied requests and gossip "
                   "sends to a peer")
    p.add_argument("--peer-down-s", type=float, default=None,
                   help="heartbeat silence before a peer is reported "
                   "down/suspect (default 3x the gossip interval)")
    p.add_argument("--peer-dead-s", type=float, default=None,
                   help="heartbeat silence before a peer is CONFIRMED "
                   "dead: removed from the ring, its sessions adopted "
                   "from the shared --state-dir (default 3x --peer-down-s)")
    p.add_argument("--proxy-retries", type=int, default=2,
                   help="retries (doubling backoff) for an unreachable "
                   "peer on idempotent proxied verbs (GET); "
                   "non-idempotent verbs always fail fast")
    p.add_argument("--proxy-backoff-ms", type=float, default=50.0,
                   help="initial proxy retry backoff, doubling per attempt")
    p.add_argument("--proxy-timeout-s", type=float, default=None,
                   help="socket timeout per proxy hop attempt "
                   "(default: --peer-timeout-s)")
    return p


def serve_main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from mpi_tpu.config import ConfigError
    from mpi_tpu.serve.cache import EngineCache
    from mpi_tpu.serve.httpd import make_server
    from mpi_tpu.serve.session import SessionManager
    from mpi_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    faults = args.inject_faults or os.environ.get("MPI_TPU_FAULTS") or None
    obs = None
    if not args.no_obs:
        from mpi_tpu.obs import Obs

        obs = Obs(trace_capacity=args.trace_capacity,
                  trace_log=args.trace_log)
    tune_cache = args.tune_cache
    if tune_cache == "auto":
        from mpi_tpu.tune import default_cache_path

        tune_cache = (os.path.join(args.state_dir, "tune_cache.json")
                      if args.state_dir else default_cache_path())
    cluster_mode = (args.peers is not None or args.peers_file is not None)
    try:
        manager = SessionManager(
            EngineCache(max_size=args.cache_size,
                        breaker_threshold=args.breaker_threshold,
                        breaker_cooldown_s=args.breaker_cooldown_s),
            batching=not args.no_batch,
            batch_window_ms=args.batch_window_ms,
            batch_max=args.batch_max,
            async_enabled=not args.no_async,
            async_queue_max=args.async_queue_max,
            ticket_ttl_s=args.ticket_ttl_s,
            state_dir=args.state_dir,
            checkpoint_every=args.checkpoint_every,
            state_degrade=args.state_degrade,
            state_journal=not args.no_state_journal,
            journal_max_bytes=args.journal_max_bytes,
            journal_max_age_s=args.journal_max_age_s,
            state_keep=args.state_keep,
            request_timeout_s=args.request_timeout_s,
            step_retries=args.step_retries,
            retry_backoff_s=args.retry_backoff_ms / 1e3,
            degrade=not args.no_degrade,
            faults=faults,
            obs=obs,
            tune_cache=tune_cache,
            # cluster mode shares --state-dir across nodes: restore is
            # deferred to attach_cluster, which takes only owned records
            defer_restore=cluster_mode and args.state_dir is not None,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    flight_on = args.flight_recorder or args.anomaly_detect
    if flight_on and obs is None:
        print("error: --flight-recorder/--anomaly-detect need "
              "observability (drop --no-obs)", file=sys.stderr)
        return 2
    telemetry_s = args.telemetry_interval_s
    if telemetry_s is None and (args.slo_file or args.anomaly_detect):
        # --slo-file implies arming; --anomaly-detect too — drift
        # evaluation rides the sampler cadence
        telemetry_s = 5.0
    if telemetry_s is not None and obs is None:
        print("error: --telemetry-interval-s/--slo-file need "
              "observability (drop --no-obs)", file=sys.stderr)
        return 2
    if telemetry_s is not None:
        from mpi_tpu.obs.slo import load_slo_file

        objectives = None
        if args.slo_file:
            try:
                objectives, slo_opts = load_slo_file(args.slo_file)
            except ConfigError as e:
                print(f"error: --slo-file: {e}", file=sys.stderr)
                return 2
        else:
            slo_opts = {}
        obs.arm_telemetry(interval_s=telemetry_s, manager=manager,
                          objectives=objectives, **slo_opts)
    if flight_on:
        # after arm_telemetry: the devmem sampler and the drift
        # evaluation chain onto the ticker, which must exist first
        anomaly_kw = {}
        if args.anomaly_detect:
            anomaly_kw = {"cooldown_s": args.anomaly_cooldown_s,
                          "retention": args.anomaly_retention}
        obs.arm_flight(capacity=args.flight_capacity, manager=manager,
                       anomaly=args.anomaly_detect,
                       profile_dir=args.profile_dir, **anomaly_kw)
    admission_on = args.admission or bool(args.tenants_file)
    if admission_on and obs is None:
        print("error: --admission/--tenants-file need "
              "observability (drop --no-obs)", file=sys.stderr)
        return 2
    if admission_on:
        # after arm_telemetry: the shedder subscribes to the live SLO
        # engine, which only exists once telemetry is armed
        from mpi_tpu.admission import AdmissionControl
        from mpi_tpu.admission.tenants import load_tenants_file

        tenants = None
        if args.tenants_file:
            try:
                tenants = load_tenants_file(args.tenants_file)
            except ConfigError as e:
                print(f"error: --tenants-file: {e}", file=sys.stderr)
                return 2
        AdmissionControl(tenants).arm(manager, obs)
    if args.front == "aio":
        from mpi_tpu.serve.aio import make_aio_server

        server = make_aio_server(
            args.host, args.port, manager, verbose=args.verbose,
            profile_dir=args.profile_dir, max_body=args.http_max_body,
            workers=args.aio_workers,
            stream_buffer=args.stream_buffer_kib << 10)
    else:
        server = make_server(args.host, args.port, manager,
                             verbose=args.verbose,
                             profile_dir=args.profile_dir,
                             max_body=args.http_max_body)
    host, port = server.server_address[:2]
    node = None
    if cluster_mode:
        import socket

        from mpi_tpu.cluster import ClusterNode

        peers: List[str] = []
        if args.peers:
            peers += [a.strip() for a in args.peers.split(",") if a.strip()]
        if args.peers_file:
            try:
                with open(args.peers_file) as f:
                    for line in f:
                        line = line.split("#", 1)[0].strip()
                        if line:
                            peers.append(line)
            except OSError as e:
                print(f"error: --peers-file: {e}", file=sys.stderr)
                server.server_close()
                return 2
        advertise = args.advertise or f"{host}:{port}"
        try:
            node = ClusterNode(advertise, peers, manager,
                               interval_s=args.gossip_interval_s,
                               timeout_s=args.peer_timeout_s,
                               down_after_s=args.peer_down_s,
                               dead_after_s=args.peer_dead_s,
                               proxy_retries=args.proxy_retries,
                               proxy_backoff_s=args.proxy_backoff_ms / 1e3,
                               proxy_timeout_s=args.proxy_timeout_s,
                               state_dir=args.state_dir, obs=obs)
        except ValueError as e:        # ConfigError included
            print(f"error: {e}", file=sys.stderr)
            server.server_close()
            return 2
        manager.attach_cluster(node)
        server.core.cluster = node
        if obs is not None:
            # cluster scrapes are federated: every sample carries the
            # process identity (single-process mode never sets these)
            obs.metrics.set_const_labels(
                {"host": socket.gethostname(), "process": advertise})
        node.start()
    batch = ("off" if args.no_batch else
             f"window {args.batch_window_ms}ms max {args.batch_max}")
    extras = []
    if args.no_async:
        extras.append("async off")
    if args.state_dir:
        extras.append(f"state-dir {args.state_dir}")
        if manager.restored_sessions:
            extras.append(f"restored {manager.restored_sessions}")
    if faults:
        extras.append(f"faults '{faults}'")
    if tune_cache:
        extras.append(f"tune-cache {tune_cache}")
    if args.no_obs:
        extras.append("obs off")
    elif args.trace_log:
        extras.append(f"trace-log {args.trace_log}")
    if telemetry_s is not None:
        extras.append(f"telemetry {telemetry_s}s"
                      + (f" slo-file {args.slo_file}"
                         if args.slo_file else ""))
    if admission_on:
        extras.append("admission"
                      + (f" tenants-file {args.tenants_file}"
                         if args.tenants_file else " (default tenant)"))
    if flight_on:
        extras.append(f"flight {args.flight_capacity}"
                      + (" anomaly" if args.anomaly_detect else ""))
    if args.profile_dir:
        extras.append(f"profile-dir {args.profile_dir}")
    if args.front != "threaded":
        extras.append(f"front {args.front} ({args.aio_workers} workers)")
    if node is not None:
        extras.append(f"cluster {node.id} tag {node.tag} "
                      f"peers {len(node.peers)}")
    extra = (", " + ", ".join(extras)) if extras else ""
    print(f"[mpi_tpu] serving on http://{host}:{port} "
          f"(cache size {args.cache_size}, batch {batch}{extra})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[mpi_tpu] shutting down", flush=True)
    finally:
        if node is not None:
            node.stop()
        server.server_close()
        if obs is not None:
            obs.close()                 # flush + fsync the trace log
    return 0


if __name__ == "__main__":
    sys.exit(serve_main())
