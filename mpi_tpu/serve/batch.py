"""Same-signature microbatch scheduler — the serving hot path batched.

PERF.md's round-2 measurement: one executable dispatch over the device
tunnel costs ~68 ms regardless of work, so small boards (the typical
serving workload) are dispatch-bound — N concurrent sessions stepping
once pay N fixed dispatch costs per generation.  The fix is the
continuous-batching insight of LLM serving (Orca, Yu et al., OSDI'22)
applied to boards: requests whose compiled program is IDENTICAL (same
``plan_signature``) and whose step depth matches are coalesced into one
stacked ``[B, ...]`` batch and advanced through a single vmapped device
dispatch (``Engine.step_batched``) — 68/B ms of fixed cost per board.

Mechanics: ``submit`` enqueues the request into a per-``(signature,
depth)`` queue.  The FIRST arrival becomes the *leader*: it sleeps a
small coalescing window (``window_ms``), then drains the queue in chunks
of ``max_batch`` and executes each chunk; later arrivals are *followers*
that just wait for the leader to deliver their result.  Mismatched
pending depths land in different queues (and batches of one take the
plain solo path), a session already in the chunk steps solo after the
batch, and any batched-path failure falls back to stepping each board
solo — correctness NEVER depends on batching, it only removes
dispatches.  Per-session locks are taken by the leader (in session-id
order) for the duration of the coalesced step, so snapshots and closes
serialize against the batch exactly as they do against a solo step.
"""

from __future__ import annotations

import threading
import time

from mpi_tpu.obs.trace import (
    current_request_id, reset_request_id, set_request_id,
)
from mpi_tpu.obs.tracectx import (
    current_trace_context, reset_trace_context, set_trace_context,
)


class _Entry:
    """One enqueued step request: filled with either ``result`` or
    ``error`` by the leader, then ``event`` wakes the waiting thread.
    ``rid`` carries the submitter's request id across the thread hop —
    the leader runs follower work on ITS thread, so the contextvar set
    by the HTTP handler does not flow; the leader re-enters each entry's
    id around its commit so downstream spans (checkpoint writes) land
    under the request that asked for them.  ``tctx`` carries the
    submitter's trace context across the same hop for the same reason."""

    __slots__ = ("session", "steps", "event", "result", "error", "rid",
                 "tctx")

    def __init__(self, session, steps: int):
        self.session = session
        self.steps = steps
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.rid = current_request_id()
        self.tctx = current_trace_context()


class MicroBatcher:
    """Coalesces concurrent same-signature steps into batched dispatches.

    Counters (surfaced on ``/stats`` as the ``batch`` section):

    * ``coalesced_calls``/``batched_boards`` — batched device calls
      (B >= 2) and the boards they carried; occupancy = boards/calls.
    * ``solo_steps``/``solo_step_s`` — entries that went through the
      scheduler but stepped alone (single arrival in the window, engine
      mismatch, duplicate session in a chunk, batched-path failure).
    * ``batched_step_s`` — wall time inside the batched dispatches;
      ``batched_step_s / batched_boards`` is the measured per-board
      amortized dispatch+step cost, the number this scheduler exists to
      shrink.
    """

    def __init__(self, window_ms: float = 2.0, max_batch: int = 8):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.window_s = max(0.0, float(window_ms)) / 1e3
        self.max_batch = int(max_batch)
        self._lock = threading.Lock()
        self._queues = {}                # (signature, steps, qos) -> [_Entry]
        self.coalesced_calls = 0
        self.batched_boards = 0
        self.max_occupancy = 0
        self.solo_steps = 0
        self.batched_step_s = 0.0
        self.solo_step_s = 0.0
        self.batched_fallbacks = 0       # batched attempts that fell solo

    # -- public ------------------------------------------------------------

    def submit(self, manager, session, steps: int) -> dict:
        """Step ``session`` by ``steps`` through the coalescing queue;
        blocks until the (own or some leader's) dispatch delivers.  Raises
        whatever the solo path would have raised (closed session ->
        KeyError, etc.)."""
        # admission tags the session with a priority class; batches
        # compose within class only (qos is None everywhere unarmed, so
        # the grouping — and the key — is unchanged on default servers)
        key = (session.plan_sig, steps, getattr(session, "qos", None))
        entry = _Entry(session, steps)
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                self._queues[key] = [entry]
                leader = True
            else:
                q.append(entry)
                leader = False
        if leader:
            if self.window_s:
                t0 = time.perf_counter()
                time.sleep(self.window_s)
                if manager.obs is not None:
                    manager.obs.event("batch_window",
                                      time.perf_counter() - t0, t0,
                                      sid=session.id)
            self._run_leader(manager, key)
        else:
            entry.event.wait()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def queue_depth(self) -> int:
        """Entries currently waiting in coalescing queues (scraped as the
        ``mpi_tpu_batch_queue_depth`` gauge)."""
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def stats(self) -> dict:
        with self._lock:
            calls, boards = self.coalesced_calls, self.batched_boards
            return {
                "window_ms": self.window_s * 1e3,
                "max_batch": self.max_batch,
                "coalesced_calls": calls,
                "batched_boards": boards,
                "avg_occupancy": round(boards / calls, 3) if calls else None,
                "max_occupancy": self.max_occupancy,
                "solo_steps": self.solo_steps,
                "batched_fallbacks": self.batched_fallbacks,
                "batched_step_s": round(self.batched_step_s, 6),
                "solo_step_s": round(self.solo_step_s, 6),
                "amortized_board_step_s": (
                    round(self.batched_step_s / boards, 6) if boards else None
                ),
            }

    def reset_stats(self) -> None:
        """Zero the counters (the batched micro-benchmark warms compiles
        first, then measures a clean window)."""
        with self._lock:
            self.coalesced_calls = 0
            self.batched_boards = 0
            self.max_occupancy = 0
            self.solo_steps = 0
            self.batched_fallbacks = 0
            self.batched_step_s = 0.0
            self.solo_step_s = 0.0

    # -- leader ------------------------------------------------------------

    def _run_leader(self, manager, key) -> None:
        """Drain the queue in chunks until it is empty AND removed (the
        removal is atomic with seeing it empty, so a late arrival either
        lands in a chunk here or becomes the next leader)."""
        while True:
            with self._lock:
                q = self._queues.get(key, [])
                chunk = q[: self.max_batch]
                del q[: len(chunk)]
                if not q:
                    self._queues.pop(key, None)
                    done = True
                else:
                    done = False
            if chunk:
                self._run_chunk(manager, chunk)
            if done:
                return

    def _run_chunk(self, manager, entries) -> None:
        """Execute one drained chunk: lock every session (id order — the
        only multi-lock acquirer in the process, so order alone prevents
        deadlock), batch the groups that share an engine, solo the rest.
        EVERY entry leaves completed (result or error) and signaled."""
        steps = entries[0].steps
        try:
            # a session enqueued twice in one window must not appear twice
            # in one stacked batch (both lanes would step the same
            # pre-grid); the duplicate steps solo after the batch, under
            # the lock the first occurrence already holds
            seen, ordered, dupes = set(), [], []
            for e in entries:
                if id(e.session) in seen:
                    dupes.append(e)
                else:
                    seen.add(id(e.session))
                    ordered.append(e)
            ordered.sort(key=lambda e: e.session.id)
            for e in ordered:
                e.session.lock.acquire()
            try:
                live, groups = [], {}
                for e in ordered:
                    if e.session.closed or e.session.engine is None:
                        e.error = KeyError(e.session.id)
                    else:
                        live.append(e)
                        groups.setdefault(id(e.session.engine), []).append(e)
                for group in groups.values():
                    if len(group) >= 2:
                        self._step_group_batched(manager, group, steps)
                    else:
                        self._step_solo(manager, group[0], steps)
                for e in dupes:
                    if e.session.closed or e.session.engine is None:
                        e.error = KeyError(e.session.id)
                    else:
                        self._step_solo(manager, e, steps)
            finally:
                for e in ordered:
                    e.session.lock.release()
        finally:
            for e in entries:
                if e.result is None and e.error is None:
                    e.error = RuntimeError(
                        "microbatch leader failed before completing entry")
                e.event.set()

    def _step_solo(self, manager, entry, steps: int) -> None:
        # re-enter the submitter's request id (and trace context): this
        # runs on the LEADER's thread, whose contextvars belong to a
        # different request
        token = set_request_id(entry.rid)
        ttoken = (set_trace_context(entry.tctx)
                  if entry.tctx is not None else None)
        t0 = time.perf_counter()
        try:
            entry.result = manager._step_locked(entry.session, steps)
        except Exception as e:  # noqa: BLE001 — delivered to the waiter
            entry.error = e
        finally:
            if ttoken is not None:
                reset_trace_context(ttoken)
            reset_request_id(token)
        with self._lock:
            self.solo_steps += 1
            self.solo_step_s += time.perf_counter() - t0

    def _step_group_batched(self, manager, group, steps: int) -> None:  # lint: disable=lock-discipline -- leader path: _run_chunk holds every rider's session.lock (id-ordered)
        """One stacked dispatch for a group of sessions sharing an engine;
        any failure falls back to stepping each board solo (the stack
        COPIES, so the per-session grids are untouched until the batch
        succeeds and the scatter replaces them)."""
        import jax

        engine = group[0].session.engine
        B = len(group)
        try:
            # stacking + a first-(depth, B) compile are setup, not
            # stepping — same accounting split as the solo path
            t0 = time.perf_counter()
            stepper, _hit = manager.cache.get_or_build_batched(
                group[0].session.plan_sig, B,
                lambda: engine.batched_stepper(B))
            stacked = engine.stack_grids([e.session.grid for e in group])
            engine.ensure_compiled_batched(stacked, steps)
            t1 = time.perf_counter()
            out = stepper(stacked, steps)
            jax.block_until_ready(out)
            t2 = time.perf_counter()
            boards = engine.unstack_grids(out)
        except Exception:  # noqa: BLE001 — batching must never cost correctness
            with self._lock:
                self.batched_fallbacks += 1
            for e in group:
                self._step_solo(manager, e, steps)
            return
        obs = manager.obs
        if obs is not None:
            # one dispatch serves B requests: the span lists every rid so
            # any of them reconstructs this shared leg from the JSONL;
            # each rider's trace context rides as a *link*, never a
            # parent — the shared dispatch belongs to no single trace
            links = [e.tctx.link() for e in group if e.tctx is not None]
            obs.event("batched_dispatch", t2 - t1, t1, B=B, steps=steps,
                      sids=[e.session.id for e in group],
                      request_ids=[e.rid for e in group],
                      **({"links": links} if links else {}))
            obs.occupancy_series.observe(B)
            if getattr(engine, "tuned_plan", None):
                obs.dispatch_batched_tuned.observe(t2 - t1)
            else:
                obs.dispatch_batched.observe(t2 - t1)
            tel = obs.telemetry
            if tel is not None:
                tel.dispatch_digest.observe(t2 - t1)
            # usage ledger: ONE sync split evenly across the B riders
            # (shares sum to the leader's block time); the failed-batch
            # path above commits nothing here — each solo fallback
            # records its own sync in _step_locked, never both
            card = engine.cost_card(steps, B)
            per_flops = card.flops / B if card is not None else 0.0
            obs.ledger.record(
                "batched", engine.sig_label, t2 - t1,
                [(e.session.id, steps, steps * e.session.config.cells,
                  per_flops) for e in group])
            fl = obs.flight
            if fl is not None:
                fl.record("batched", engine=engine, steps=steps,
                          batch=B, setup_s=t1 - t0, device_s=t2 - t1,
                          sessions=[e.session.id for e in group],
                          request_ids=[e.rid for e in group],
                          links=links or None)
        for e, grid in zip(group, boards):
            s = e.session
            s.setup_s += t1 - t0
            s.steady_s += t2 - t1
            s.grid = grid
            s.generation += steps
            s.batched_steps += 1
            # commit under the submitter's request id and trace context
            # so the checkpoint write's span carries both (this is the
            # leader's thread)
            token = set_request_id(e.rid)
            ttoken = (set_trace_context(e.tctx)
                      if e.tctx is not None else None)
            try:
                manager._checkpoint(s)  # session lock is held (leader)
            finally:
                if ttoken is not None:
                    reset_trace_context(ttoken)
                reset_request_id(token)
            manager._notify_step(s)
            e.result = {"id": s.id, "generation": s.generation,
                        "steps": steps, "batched": B}
        manager._mark_dispatch_ok()
        with self._lock:
            self.coalesced_calls += 1
            self.batched_boards += B
            self.max_occupancy = max(self.max_occupancy, B)
            self.batched_step_s += t2 - t1
